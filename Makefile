# Developer entry points.
.PHONY: test lint typecheck lint-demo lock-graph witness-check fork-inventory loop-witness-check native proto bench history-demo chaos-demo trace-demo trace-overhead restart-demo persist-fsync-check persist-overhead fleet-query-demo shard-demo egress-demo egress-drain-check scenario-demo fuzz-smoke pressure-demo store-demo dashboard-demo alert-demo clean

test:
	python -m pytest tests/ -q

# Static analysis gate (README "Static analysis"). Two layers:
#   exporter-lint — the codebase's own invariant rules (lock discipline,
#     schema-registered metric names, monotonic-clock, thread conventions,
#     /debug gating, flag coverage), stdlib-only, always runs; fails on any
#     finding not in .exporter-lint-baseline.json.
#   ruff — generic real-bug pass (F + E9 only), runs when installed
#     (CI always installs it; minimal dev boxes skip with a notice).
lint:
	python -m tpu_pod_exporter.analysis
	@if python -c "import ruff" 2>/dev/null; then \
		python -m ruff check tpu_pod_exporter tests; \
	else \
		echo "ruff not installed; skipped (CI runs it — pip install ruff)"; \
	fi

# Strict-ish typing on the core modules ([tool.mypy] in pyproject.toml).
# Gated on availability for the same reason as ruff above.
typecheck:
	@if python -c "import mypy" 2>/dev/null; then \
		python -m mypy tpu_pod_exporter; \
	else \
		echo "mypy not installed; skipped (CI runs it — pip install mypy)"; \
	fi

# Seed one deliberate violation per rule family into a temp copy of the
# package — a lock-scoped json.dumps, an unregistered metric name, a
# lock-order inversion pair, and a wrong-thread WAL cursor move — and
# require exporter-lint to catch ALL of them: the lint analog of
# chaos-demo/trace-demo/restart-demo (exits non-zero if any seeded
# violation slips through).
lint-demo:
	python -m tpu_pod_exporter.analysis --demo

# Regenerate the REVIEWED lock-acquisition order graph artifacts
# (README "Concurrency contracts"). deploy/lock-graph.json must match
# the model byte-for-byte — tests/test_concurrency.py fails when it is
# stale, so a diff here is a reviewable concurrency-structure change.
lock-graph:
	python -m tpu_pod_exporter.analysis \
		--lock-graph deploy/lock-graph.json \
		--lock-graph-dot deploy/lock-graph.dot

# Run tier-1 under the runtime lock witness and cross-check the observed
# acquisition-order edges against the static model (the CI `concurrency`
# leg; deploy/RUNBOOK.md "Concurrency contracts"). Fails on a witnessed
# inversion (conftest exit 3) or an edge the static graph cannot explain.
witness-check:
	TPE_LOCK_WITNESS=1 TPE_LOCK_WITNESS_OUT=lock-witness.json \
		python -m pytest tests/ -q -m 'not slow'
	python -m tpu_pod_exporter.analysis --check-witness lock-witness.json

# Regenerate the REVIEWED pre-fork resource inventory (README
# "Execution-context contracts"). Every thread-spawn, lock, and kernel-
# object creation site that may be live when the multi-core plane forks;
# CI diffs it, so a change here is a reviewable pre-fork-surface change.
fork-inventory:
	python -m tpu_pod_exporter.analysis \
		--fork-inventory deploy/fork-inventory.json

# Run tier-1 under the runtime loop-stall witness and cross-check every
# loop-executed callback against the static loop-role model (the CI
# `concurrency` leg; deploy/RUNBOOK.md "Execution-context contracts").
# Fails on an inline stall (conftest exit 4) or a callback the static
# model cannot explain.
loop-witness-check:
	TPE_LOOP_WITNESS=1 TPE_LOOP_WITNESS_OUT=loop-witness.json \
		python -m pytest tests/ -q -m 'not slow'
	python -m tpu_pod_exporter.analysis --check-loop-witness loop-witness.json

# Replay the round-5 real-hardware trace through the history flight
# recorder and print what /api/v1/window_stats would answer — the offline
# forensics path (deploy/RUNBOOK.md "Forensics after an incident").
history-demo:
	python -m tpu_pod_exporter.history --replay tests/fixtures/real-trace-r5.jsonl

# Wedge a live in-process exporter's device backend (deterministic chaos
# injection) and watch supervision recover it: the hung read is abandoned at
# the phase deadline, the breaker opens, the backend reconnects, up returns
# to 1 — while /metrics answers from the stale snapshot throughout
# (deploy/RUNBOOK.md "Wedged source playbook").
chaos-demo:
	python -m tpu_pod_exporter.chaos --trace-out chaos-incident-trace.json

# Replay the round-5 real-hardware trace through a TRACED collector and
# print the rendered trace tree of the last poll — per-phase spans with
# statuses, breaker states and series counts (deploy/RUNBOOK.md "Reading a
# poll trace").
trace-demo:
	python -m tpu_pod_exporter.trace --replay tests/fixtures/real-trace-r5.jsonl

# Tracing-is-on-by-default overhead contract: poll-loop CPU with tracing
# on must stay within budget of tracing off on the bench/loadgen shape.
# The local budget is the ISSUE's 5%; CI runs with a wider margin for
# noisy shared runners (see .github/workflows/ci.yml).
trace-overhead:
	python -m tpu_pod_exporter.trace --overhead-check --polls 200 --chips 256 --budget 0.05

# Kill/restart chaos harness (deploy/RUNBOOK.md "Restart survivability"):
# SIGKILL a live exporter mid-poll via the chaos `kill` injection, restart
# it on the same --state-dir, and assert (1) /api/v1/query_range shows a
# contiguous series across the restart boundary, (2) the device breaker
# carried its quarantine over instead of re-learning from closed, (3) a
# WAL corrupted mid-file still boots. CI uploads the state dir on failure.
restart-demo:
	python -m tpu_pod_exporter.persist --restart-demo --state-dir restart-demo-state

# fsync-latency budget on the persistence hot path: WAL-shaped records
# (256-chip samples payload) appended + fsynced; fails past the p99 budget.
persist-fsync-check:
	python -m tpu_pod_exporter.persist --fsync-check --records 100 --budget-ms 50

# Persistence-on vs -off poll-thread CPU at 256 chips (the ISSUE's 2%
# budget). Persistence I/O runs on its own writer thread by design; the
# check also reports whole-process CPU for honesty.
persist-overhead:
	python -m tpu_pod_exporter.persist --overhead-check --polls 200 --chips 256 --budget 0.02

# Federated query plane acceptance (deploy/RUNBOOK.md "Slice-wide
# forensics"): 64 simulated exporters in one process, a real aggregator
# fanning /api/v1/query_range out to all of them (tracing + persistence
# ON), one target SIGKILL-shaped mid-run. Asserts the full merge with
# per-target staleness, partial=true with the remaining 63 merged, and
# the fleet-query p99 budget (CI runs with a wider budget for shared
# runners — see .github/workflows/ci.yml).
fleet-query-demo:
	python -m tpu_pod_exporter.loadgen.fleet --targets 64 --budget-ms 1500

# Sharded HA aggregation tree acceptance (deploy/RUNBOOK.md "Leaf death
# playbook"): 1000 synthetic node targets behind 8 consistent-hash leaf
# shards (HA pairs) and a freshest-wins root merge tier, everything
# talking real HTTP. The scripted timeline (chaos.LeafKillHook) staggers
# every HA pair to prove freshest-wins dedup, SIGKILLs one leaf MID-ROUND
# (zero series lost at the root, twin staleness within one round),
# restarts it on its state dir (breaker + shard-map carryover), and runs
# a 32-target churn wave through the shared targets file (assignment
# moves bounded by churned + targets/shards; every tier reshards live).
# Rollups are asserted equal to a flat single-aggregator oracle over the
# same scrape set at every checkpoint. CI runs a reduced-target smoke
# (see .github/workflows/ci.yml) and uploads the state dir on failure.
# Mixed fleet by default: 2 of the farm's 8 slices are GPU node pools
# (gpu_* node surface), so both device families ride one tree and the
# per-family fleet rollups are asserted against a per-family oracle +
# arithmetic ground truth. --gpu-slices 0 restores a homogeneous farm.
shard-demo:
	python -m tpu_pod_exporter.loadgen.fleet --mode shard --targets 1000 \
		--shards 8 --chips 2 --churn 32 --round-budget-s 15 \
		--gpu-slices 2 --state-root shard-demo-state

# GPU path, deterministically, without a driver: replay the committed
# NVML-shaped fixture (tests/fixtures/gpu-recorded.jsonl — 2 simulated
# A100s, per-process tables, one injected NVML_ERROR_TIMEOUT) through the
# real collector and assert the gpu_* node surface comes out, per-pod GPU
# memory joins, and the injected fault degrades that chip only.
gpu-demo:
	python -m tpu_pod_exporter.backend.nvml --demo \
		--recording tests/fixtures/gpu-recorded.jsonl

# Remote-write egress acceptance (deploy/RUNBOOK.md "Egress backlog
# playbook"): a seeded chaos receiver (hang/5xx/429/mid-body truncation)
# wedges a live exporter's egress — breaker opens, backlog buffers to the
# on-disk WAL — then a SIGKILL lands MID-SEND and the restarted shipper
# resumes from the fsynced ack cursor. Asserts the zero-loss exactly-once
# ledger (contiguous batch seqs, no duplicate batch or sample) and that
# scrape+poll p99 with egress ON and the receiver WEDGED stay within 5%
# of egress OFF. CI uploads the egress dir on failure.
egress-demo:
	python -m tpu_pod_exporter.egress --demo --egress-dir egress-demo-state

# Backlog-drain budget: the send buffer a simulated 3-minute receiver
# outage leaves behind must drain within budget once the receiver returns
# (in-process, send-injected — measures shipper drain throughput).
egress-drain-check:
	python -m tpu_pod_exporter.egress --drain-check --outage-s 180 --budget-s 20

# Fleet scenario engine (deploy/RUNBOOK.md "Partition playbook"): runs
# every named chaos timeline (symmetric/asymmetric/flapping partitions,
# slice preemption, restart wave + hotspot, churn storm, receiver outage,
# the resource-pressure drills, store continuity, and the mixed_wedge GPU
# parity drill — tpu_pod_exporter/scenario.py DSL) against the FULL
# simulated stack
# (synthetic node farm → real HA leaf tier → real root → remote-write
# egress into a ledgered chaos receiver), with invariants asserted at
# every tick: zero acked-sample loss, bounded per-tier staleness, root
# rollups oracle-equal outside injected windows, no series/RSS leaks, and
# every injected fault attributable from the exposition alone.
# Deterministic under --seed; CI runs a reduced-scale smoke and uploads
# the state dir + per-tick scenario trace on failure.
scenario-demo:
	python -m tpu_pod_exporter.loadgen.scenario --targets 120 --shards 4 \
		--state-root scenario-demo-state

# Scenario fuzzer smoke (README "Scenario fuzzer"): seeded random valid
# timelines through the full engine with every invariant armed, failures
# ddmin-minimized to canonical DSL reproducers, (seam x invariant)
# coverage written to fuzz-state/coverage.json and checked against the
# chaos seam registry (any unregistered seam is a hard error). Fixed seed
# list so CI is deterministic: any failure replays from its printed
# `--fuzz-replay SEED:TRIAL` coordinates alone. The larger soak budget
# lives behind `pytest -m slow` (tests/test_fuzz.py).
fuzz-smoke:
	python -m tpu_pod_exporter.fuzz --seeds 5,11 --trials 4 \
		--state-root fuzz-state

# Streaming dashboard plane acceptance (deploy/RUNBOOK.md "Dashboard storm
# playbook"): 5000 concurrent /api/v1/stream subscriptions held against
# one root + 2 stateless read replicas over a real leaf tier. Asserts
# bounded per-round push p99, flat RSS through the storm, zero duplicate/
# missed rounds per subscriber, delta replay == the polled answer for
# every sampled subscriber every round, a replica kill mid-stream
# degrading ONLY its own viewers (they reconnect to a peer and resync),
# and counted subscriber-shed semantics. The second run is the NEGATIVE
# CONTROL: one delta frame per subscriber is dropped client-side and the
# replay-equality invariant must catch it (the drill proves it can fail).
dashboard-demo:
	python -m tpu_pod_exporter.loadgen.fleet --mode dashboard \
		--targets 100 --shards 4 --chips 2 --subs 5000 --rounds 10 \
		--replicas 2 --state-root dashboard-demo-state
	python -m tpu_pod_exporter.loadgen.fleet --mode dashboard \
		--targets 24 --shards 2 --chips 2 --subs 48 --rounds 4 \
		--replicas 1 --state-root dashboard-demo-state/negative \
		--negative

# Native alerting acceptance (deploy/RUNBOOK.md "Alerting without
# Prometheus"): the alert_partition drill — an asymmetric root-leaf cut
# where EXACTLY TpuRootLeafPartitioned must fire (TpuRootLeafDown held
# down by the stale-serve suspicion suppression, nothing else firing), a
# receiver outage covering the partition onset so the webhook notifier
# wedges (breaker open, WAL backlog) and drains after heal with a
# contiguous exactly-once ledger, firing states queryable from the fleet
# store as ALERTS series and streamed over the alerts route. The second
# run is the NEGATIVE CONTROL: suppression deliberately broken
# (--alert-suppression off), TpuRootLeafDown fires too, and the
# fired-set assertion must make the drill FAIL (non-zero exit asserted).
alert-demo:
	python -m tpu_pod_exporter.loadgen.scenario \
		--scenarios alert_partition --targets 48 --shards 2 \
		--state-root alert-demo-state
	! python -m tpu_pod_exporter.loadgen.scenario \
		--scenarios alert_partition --targets 24 --shards 2 \
		--alert-suppression off --log-level error \
		--state-root alert-demo-state/negative

# Resource-pressure governor acceptance (deploy/RUNBOOK.md "Resource
# pressure playbook"): three drills against real components —
#   disk:   a live exporter (persister + WAL + egress into a ledgered
#           chaos receiver) on a budget its steady state cannot fit; the
#           ladder must climb IN ORDER (WAL thinning -> egress compaction
#           -> checkpoint halving -> WAL off), usage must stop growing,
#           scraping must keep serving, the egress exactly-once ledger
#           must end intact, and recovery steps down rung by rung.
#   memory: history rings + trace ring + fleet cache under a byte budget;
#           sheds land coarse-tiers-last and the rings keep their NEWEST
#           samples.
#   storm:  admission control vs a 500-connection keep-alive storm; a
#           polite scraper's p99 stays within 5% (+5 ms noise floor) of
#           its baseline and open connections never exceed the cap.
# Then the NEGATIVE CONTROL: the disk drill re-runs WITHOUT the governor
# and must VISIBLY break the budget invariant (exit 0 only when it does).
pressure-demo:
	python -m tpu_pod_exporter.pressure --demo
	python -m tpu_pod_exporter.pressure --negative-control

# Fleet TSDB-lite acceptance (deploy/RUNBOOK.md "Incident forensics from
# the store"): two drills against the root-side store —
#   retention: 7 simulated days at 1000 targets folded into disk-backed
#           downsample tiers on a compressed timescale, a kill/replay
#           restart mid-window, and a governor-enforced disk budget the
#           ladder must answer with store_thin (finest tier shed first,
#           counted; the 7-day coarse span must SURVIVE the shed, and
#           rule-backed + per-target queries must answer the full window).
#   query:  stored-rollup query p99 vs the cold two-level fan-out at 200
#           real-HTTP targets — recording rules must beat the fan-out.
# The scenario engine's store_continuity drill (make scenario-demo) covers
# the restart+reshard boundary; CI also runs its --store off negative
# control (the gap invariant must FAIL without the store).
store-demo:
	python -m tpu_pod_exporter.store --demo --state-dir store-demo-state

native:
	$(MAKE) -C native

proto:
	cd tpu_pod_exporter/attribution/proto && protoc --python_out=. podresources.proto
	cd tpu_pod_exporter/backend/proto && protoc --python_out=. tpu_metric_service.proto

bench: native
	python bench.py

clean:
	$(MAKE) -C native clean
	find . -name __pycache__ -type d -exec rm -rf {} +
