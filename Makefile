# Developer entry points.
.PHONY: test native proto bench clean

test:
	python -m pytest tests/ -q

native:
	$(MAKE) -C native

proto:
	cd tpu_pod_exporter/attribution/proto && protoc --python_out=. podresources.proto
	cd tpu_pod_exporter/backend/proto && protoc --python_out=. tpu_metric_service.proto

bench: native
	python bench.py

clean:
	$(MAKE) -C native clean
	find . -name __pycache__ -type d -exec rm -rf {} +
