# Developer entry points.
.PHONY: test native proto bench history-demo chaos-demo trace-demo trace-overhead clean

test:
	python -m pytest tests/ -q

# Replay the round-5 real-hardware trace through the history flight
# recorder and print what /api/v1/window_stats would answer — the offline
# forensics path (deploy/RUNBOOK.md "Forensics after an incident").
history-demo:
	python -m tpu_pod_exporter.history --replay tests/fixtures/real-trace-r5.jsonl

# Wedge a live in-process exporter's device backend (deterministic chaos
# injection) and watch supervision recover it: the hung read is abandoned at
# the phase deadline, the breaker opens, the backend reconnects, up returns
# to 1 — while /metrics answers from the stale snapshot throughout
# (deploy/RUNBOOK.md "Wedged source playbook").
chaos-demo:
	python -m tpu_pod_exporter.chaos --trace-out chaos-incident-trace.json

# Replay the round-5 real-hardware trace through a TRACED collector and
# print the rendered trace tree of the last poll — per-phase spans with
# statuses, breaker states and series counts (deploy/RUNBOOK.md "Reading a
# poll trace").
trace-demo:
	python -m tpu_pod_exporter.trace --replay tests/fixtures/real-trace-r5.jsonl

# Tracing-is-on-by-default overhead contract: poll-loop CPU with tracing
# on must stay within budget of tracing off on the bench/loadgen shape.
# The local budget is the ISSUE's 5%; CI runs with a wider margin for
# noisy shared runners (see .github/workflows/ci.yml).
trace-overhead:
	python -m tpu_pod_exporter.trace --overhead-check --polls 200 --chips 256 --budget 0.05

native:
	$(MAKE) -C native

proto:
	cd tpu_pod_exporter/attribution/proto && protoc --python_out=. podresources.proto
	cd tpu_pod_exporter/backend/proto && protoc --python_out=. tpu_metric_service.proto

bench: native
	python bench.py

clean:
	$(MAKE) -C native clean
	find . -name __pycache__ -type d -exec rm -rf {} +
