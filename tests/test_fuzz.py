"""Scenario fuzzer: renderer fixpoint, generator validity/determinism,
seam-registry completeness, coverage ledger, minimizer, and the
determinism audit of the engine under --seed.

tpu-pod-exporter — chaos drills only prove the failure modes someone
thought to write down. These tests pin the machinery that generates the
rest: canonical rendering (so reproducers are copy-pasteable DSL),
seeded generation (so (seed, trial) IS the corpus), seam bookkeeping (so
a new injector can't silently contribute zero coverage), and the ddmin
minimizer (so a 4-event failure lands in the repo as a 1-2 event drill).
"""

from __future__ import annotations

import random

import pytest

from tpu_pod_exporter import fuzz
from tpu_pod_exporter import scenario as sc
from tpu_pod_exporter.chaos import SEAM_REGISTRY, register_seam, registered_seams

# ----------------------------------------------------- canonical renderer


class TestCanonicalRenderer:
    @pytest.mark.parametrize("kind", sc.EVENT_KINDS)
    def test_render_parse_fixpoint_per_kind(self, kind):
        """render(parse(render(e))) == render(e) for generated events of
        EVERY kind — canonical text is a fixpoint of the round trip."""
        for seed in range(25):
            rng = random.Random(f"fixpoint:{kind}:{seed}")
            text = sc.generate_event(kind, rng)
            ev = sc.parse_scenario(text)[0]
            once = sc.render_event(ev)
            again = sc.render_event(sc.parse_scenario(once)[0])
            assert once == again

    def test_render_timeline_fixpoint_named_drills(self):
        for name, scn in sc.SCENARIOS.items():
            if not scn.timeline:
                continue
            events = sc.parse_scenario(scn.timeline)
            once = sc.render_timeline(events)
            assert sc.render_timeline(sc.parse_scenario(once)) == once, name

    def test_render_is_order_insensitive(self):
        a = sc.parse_scenario("preempt(slice-0)@2+2; clock_step(45)@5")
        assert sc.render_timeline(list(reversed(a))) == sc.render_timeline(a)

    def test_render_omits_defaults(self):
        text = sc.render_timeline(sc.parse_scenario(
            "restart_wave(3, stagger=1)@2; hotspot(job-1)@4+1"))
        # stagger=1 and +1 are the parser defaults; canonical text drops
        # them (and restart_wave's derived duration is never rendered).
        assert text == "restart_wave(3)@2; hotspot(job-1)@4"


# ------------------------------------------------------------- generation


class TestGeneration:
    def test_generated_timelines_always_valid(self):
        for seed in range(40):
            text = sc.generate_timeline(random.Random(seed))
            events = sc.parse_scenario(text)  # must not raise
            assert events
            assert sc.render_timeline(events) == text  # already canonical

    def test_generation_is_deterministic(self):
        for seed in (0, 7, 99):
            assert (sc.generate_timeline(random.Random(seed))
                    == sc.generate_timeline(random.Random(seed)))

    def test_timeline_for_trial_is_pure(self):
        """Bias weights derive from generated timelines only, so the
        (seed, trial) → timeline map needs no corpus state."""
        got = [fuzz.timeline_for_trial(11, t) for t in range(4)]
        assert got == [fuzz.timeline_for_trial(11, t) for t in range(4)]
        assert len(set(got)) > 1  # trials actually differ

    def test_generation_touches_no_wallclock_or_global_rng(self, monkeypatch):
        """The determinism audit's sharp edge: generation must draw ONLY
        from the passed rng. Wall clock and the global random module are
        booby-trapped; any leak raises."""
        import time

        def boom(*a, **k):
            raise AssertionError("unseeded source consulted")

        monkeypatch.setattr(time, "time", boom)
        monkeypatch.setattr(time, "monotonic", boom)
        for fn in ("random", "randint", "choice", "choices", "uniform"):
            monkeypatch.setattr(random, fn, boom)
        text = fuzz.timeline_for_trial(3, 2)
        assert sc.parse_scenario(text)

    def test_weights_bias_kind_selection(self):
        heavy = {k: 0.0 for k in sc.EVENT_KINDS}
        heavy["clock_step"] = 1.0
        text = sc.generate_timeline(random.Random(5), max_events=3,
                                    weights=heavy)
        assert all(e.kind == "clock_step"
                   for e in sc.parse_scenario(text))

    def test_unknown_kind_rejected(self):
        with pytest.raises(ValueError, match="no generator"):
            sc.generate_event("warp_core_breach", random.Random(0))


# ---------------------------------------------------- seam registry check


class TestSeamRegistry:
    def test_registry_and_kind_map_are_closed(self):
        """Zero drift in either direction: every event kind maps to
        registered seams and every registered seam is reachable."""
        assert fuzz.seam_map_problems() == []

    def test_every_kind_mapped(self):
        assert set(fuzz.KIND_SEAMS) == set(sc.EVENT_KINDS)

    def test_partition_resolves_per_edge(self):
        events = sc.parse_scenario(
            "partition(node<->leaf, symmetric)@2; "
            "partition(root<->recv, symmetric)@5")
        assert fuzz.seams_of(events) == {"wire:node-leaf", "wire:root-recv"}

    def test_duplicate_registration_rejected(self):
        with pytest.raises(ValueError, match="registered twice"):
            register_seam("disk", "twice")

    def test_unregistered_seam_surfaces_in_report(self):
        ledger = fuzz.CoverageLedger()
        ledger.record({"wire:node-leaf", "unmapped:ghost"}, ["egress_ledger"])
        rep = ledger.report()
        assert rep["unregistered_seams"] == ["unmapped:ghost"]
        assert rep["matrix"]["wire:node-leaf"]["egress_ledger"] == 1


# --------------------------------------------------------- coverage ledger


class TestCoverageLedger:
    def test_dark_pairs_shrink_as_trials_record(self):
        ledger = fuzz.CoverageLedger()
        total = len(registered_seams()) * len(sc.INVARIANTS)
        assert len(ledger.dark_pairs()) == total
        ledger.record({"disk"}, sc.INVARIANTS)
        assert len(ledger.dark_pairs()) == total - len(sc.INVARIANTS)
        rep = ledger.report()
        assert rep["pairs_covered"] == len(sc.INVARIANTS)
        assert rep["trials"] == 1

    def test_kind_weights_favor_dark_seams(self):
        counts = {s: 3 for s in registered_seams()}
        counts["wallclock"] = 0
        w = fuzz.kind_weights(counts)
        assert w["clock_step"] > w["preempt"]
        # All-lit registry → uniform weights.
        assert len(set(fuzz.kind_weights(
            {s: 1 for s in registered_seams()}).values())) == 1


# -------------------------------------------------------------- minimizer


COMPOSITE = ("mem_pressure()@2+2; clock_step(3600)@3; "
             "preempt(slice-1)@5+2; churn_storm(6)@6+2")


class TestMinimizer:
    def test_shrinks_composite_to_culprit(self):
        """A 4-event timeline whose failure needs only the clock_step
        must shrink to exactly that event, with its magnitude and round
        floored — and every candidate the predicate saw must have been a
        valid timeline."""
        seen: list[str] = []

        def failing(events):
            text = sc.render_timeline(events)
            sc.parse_scenario(text)  # invalid candidate would raise here
            seen.append(text)
            return any(e.kind == "clock_step" for e in events)

        out = fuzz.minimize(sc.parse_scenario(COMPOSITE), failing)
        assert len(out) == 1
        assert out[0].kind == "clock_step"
        assert out[0].step_s == 45.0
        assert out[0].at_round == fuzz.TRIAL_BOUNDS.min_round
        assert len(seen) > 3  # it actually searched

    def test_minimize_to_interacting_pair(self):
        def failing(events):
            kinds = {e.kind for e in events}
            return {"preempt", "churn_storm"} <= kinds

        out = fuzz.minimize(sc.parse_scenario(COMPOSITE), failing)
        assert sorted(e.kind for e in out) == ["churn_storm", "preempt"]

    def test_minimize_is_deterministic(self):
        def failing(events):
            return any(e.kind == "churn_storm" for e in events)

        a = fuzz.minimize(sc.parse_scenario(COMPOSITE), failing)
        b = fuzz.minimize(sc.parse_scenario(COMPOSITE), failing)
        assert sc.render_timeline(a) == sc.render_timeline(b)
        assert len(a) == 1 and a[0].count == 2  # churn floor is 2

    def test_shrink_variants_always_valid(self):
        for seed in range(20):
            text = sc.generate_timeline(random.Random(f"sv:{seed}"))
            for ev in sc.parse_scenario(text):
                for cand in fuzz._shrink_variants(ev):
                    sc.parse_scenario(sc.render_event(cand))  # no raise

    def test_budget_respected(self):
        calls = [0]

        def failing(events):
            calls[0] += 1
            return True

        fuzz.minimize(sc.parse_scenario(COMPOSITE), failing, max_checks=5)
        assert calls[0] <= 5

    def test_invalid_input_rejected(self):
        with pytest.raises(ValueError, match="not valid"):
            fuzz.minimize([], lambda e: True)


# ------------------------------------------------------ alert bounds


class TestAlertBounds:
    def test_asymmetric_leaf_root_requires_partition_alert(self):
        req, allowed = fuzz.expected_alert_bounds(sc.parse_scenario(
            "partition(leaf<->root, asymmetric)@3+2"))
        assert req == ("TpuRootLeafPartitioned",)
        assert "TpuRootLeafDown" in allowed

    def test_symmetric_cut_only_allows(self):
        req, allowed = fuzz.expected_alert_bounds(sc.parse_scenario(
            "partition(leaf<->root, symmetric)@3+2"))
        assert req == ()
        assert set(allowed) == {"TpuRootLeafDown", "TpuRootLeafPartitioned"}

    def test_asymmetric_overlapping_dead_root_demoted_to_allowed(self):
        req, allowed = fuzz.expected_alert_bounds(sc.parse_scenario(
            "root_restart()@3+3; partition(leaf<->root, asymmetric)@4+2"))
        assert req == ()
        assert "TpuRootLeafPartitioned" in allowed

    def test_unrelated_events_stay_exact(self):
        req, allowed = fuzz.expected_alert_bounds(sc.parse_scenario(
            "mem_pressure()@2+2; scrape_storm(40)@4"))
        assert req == () and allowed == ()


# ------------------------------------------- fuzzer-found regressions


class TestFuzzerFoundRegressions:
    """Minimized fuzzer finds, committed as named drills. Each green test
    has a negative control proving the drill bites with the fix gone."""

    def test_root_restart_egress_drill_green(self, tmp_path, quiet_logs):
        """root_restart()@2 (ddmin'd from a 4-event composite): a frozen
        snapshot must never be framed twice — zero duplicate samples in
        the exactly-once ledger across the dead window."""
        from tpu_pod_exporter.loadgen.scenario import run_one

        result, _ = run_one(sc.SCENARIOS["fuzz_root_restart_egress"],
                            16, 2, 1, str(tmp_path / "state"), seed=42)
        assert result["ok"], result.get("problems")
        assert result["egress"]["duplicate_samples"] == 0

    def test_root_restart_egress_negative_control(
            self, tmp_path, quiet_logs, monkeypatch):
        """Fix reverted (the same-poll-instant guard disabled): the drill
        must FAIL with duplicate samples — the regression drill is not
        vacuous."""
        from tpu_pod_exporter.egress import RemoteWriteShipper
        from tpu_pod_exporter.loadgen.scenario import run_one

        monkeypatch.setattr(RemoteWriteShipper, "_same_poll_instant",
                            lambda self, wall: False)
        result, _ = run_one(sc.SCENARIOS["fuzz_root_restart_egress"],
                            16, 2, 1, str(tmp_path / "state"), seed=42)
        assert not result["ok"]
        assert any("duplicate" in p for p in result["problems"])

    def test_hotspot_churn_drill_green(self, tmp_path, quiet_logs):
        """hotspot x churn_storm: pod_gen rotation mid-window must not
        orphan the hot set — attributability holds through the churn."""
        from tpu_pod_exporter.loadgen.scenario import run_one

        result, _ = run_one(sc.SCENARIOS["fuzz_hotspot_churn"],
                            16, 2, 1, str(tmp_path / "state"), seed=42)
        assert result["ok"], result.get("problems")


# ---------------------------------------------- determinism audit (engine)


@pytest.fixture
def quiet_logs():
    import logging

    logging.disable(logging.WARNING)
    yield
    logging.disable(logging.NOTSET)


@pytest.mark.slow
class TestFuzzSoak:
    def test_soak_larger_budget(self, tmp_path, quiet_logs):
        """The bigger trial budget behind -m slow: several seeds, every
        failure minimized, coverage written, exit 0 (no live bugs)."""
        rc = fuzz.main([
            "--seeds", "1,2,3,4,6,7", "--trials", "6", "--keep-going",
            "--state-root", str(tmp_path / "soak"),
        ])
        assert rc == 0


class TestDeterminismAudit:
    def test_same_seed_trial_gives_identical_schedule_trace(
            self, tmp_path, quiet_logs):
        """Two full engine runs of one (seed, trial): the injected
        schedule — rounds, active windows, effective cuts — must match
        tick for tick. This is the property --fuzz-replay stands on."""
        seed, trial = 5, 0
        timeline = fuzz.timeline_for_trial(seed, trial)
        traces = []
        for leg in ("a", "b"):
            _result, trace = fuzz.run_trial(
                seed, trial, timeline, str(tmp_path / leg))
            traces.append(fuzz.schedule_trace(trace))
        assert traces[0] == traces[1]
        assert any(t["active"] or t["cuts"] for t in traces[0])
