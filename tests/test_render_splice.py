"""Incremental exposition render (ISSUE 13): splice correctness.

The ExpositionTemplate keeps the whole text body as pre-rendered per-family
byte blocks and splices only changed float cells per poll. These tests pin
the one contract everything rests on: the spliced body is BYTE-IDENTICAL to
a from-scratch full render of the same snapshot — across value changes,
cell-width changes, layout-generation changes (labels added/evicted,
conditional families appearing and emptying), special float values, and a
seeded randomized sweep — and the per-encoding (gzip / OpenMetrics) caches
are invalidated exactly when the identity bytes change and shared exactly
when they do not.
"""

from __future__ import annotations

import gzip
import random

from tpu_pod_exporter.metrics.registry import (
    COUNTER,
    MetricSpec,
    PrefixCache,
    SnapshotBuilder,
)

GAUGE_SPEC = MetricSpec(
    name="splice_test_gauge",
    help="a labeled gauge",
    label_names=("chip", "pod"),
)
SCALAR_SPEC = MetricSpec(name="splice_test_scalar", help="an unlabeled gauge")
COUNTER_SPEC = MetricSpec(
    name="splice_test_ops_total",
    help="a counter (OpenMetrics header rewrite path)",
    type=COUNTER,
    label_names=("kind",),
)
CONDITIONAL_SPEC = MetricSpec(
    name="splice_test_conditional",
    help="a family that appears mid-run",
    label_names=("reason",),
)


def build(data, cache=None, timestamp=1.0):
    """One poll's snapshot from ``data``: a list of (spec, samples) pairs
    in family order, samples keyed by pre-ordered label-value tuples."""
    b = SnapshotBuilder(prefix_cache=cache)
    for spec, samples in data:
        b.declare(spec)
        fam = b.series(spec)
        for lvs, v in samples.items():
            fam[lvs] = v
    return b.build(timestamp=timestamp)


def assert_matches_full_render(data, cache):
    """The core invariant: the spliced body equals a from-scratch render of
    the same data, in every (format, encoding) pair."""
    spliced = build(data, cache)
    reference = build(data)  # no cache: the full re-render path
    assert spliced.encode() == reference.encode()
    assert spliced.encode_openmetrics() == reference.encode_openmetrics()
    assert gzip.decompress(spliced.encode_gzip()) == reference.encode()
    assert (
        gzip.decompress(spliced.encode_openmetrics_gzip())
        == reference.encode_openmetrics()
    )
    return spliced


class TestSpliceByteIdentical:
    def test_value_changes_steady_layout(self):
        cache = PrefixCache()
        data = [
            (GAUGE_SPEC, {("0", "a"): 1.5, ("1", "a"): 2.0}),
            (SCALAR_SPEC, {(): 7.0}),
        ]
        assert_matches_full_render(data, cache)
        # Same layout, same-width new values: pure cell splices.
        data = [
            (GAUGE_SPEC, {("0", "a"): 2.5, ("1", "a"): 2.0}),
            (SCALAR_SPEC, {(): 8.0}),
        ]
        assert_matches_full_render(data, cache)
        tmpl = cache.template
        assert tmpl is not None and tmpl.spliced_cells >= 2

    def test_cell_width_change_rebuilds_block(self):
        cache = PrefixCache()
        data = [(GAUGE_SPEC, {("0", "a"): 1.0, ("1", "a"): 2.0})]
        assert_matches_full_render(data, cache)
        # 1 -> 123456.75: wider cell, the block must re-join cleanly.
        data = [(GAUGE_SPEC, {("0", "a"): 123456.75, ("1", "a"): 2.0})]
        assert_matches_full_render(data, cache)
        # and narrower again
        data = [(GAUGE_SPEC, {("0", "a"): 3.0, ("1", "a"): 2.0})]
        assert_matches_full_render(data, cache)
        assert cache.template.rebuilt_blocks >= 1

    def test_labels_added_and_evicted(self):
        cache = PrefixCache()
        gen0 = cache.template.generation
        data = [(GAUGE_SPEC, {("0", "a"): 1.0})]
        assert_matches_full_render(data, cache)
        # Series added (pod churn: a new label set appears).
        data = [(GAUGE_SPEC, {("0", "a"): 1.0, ("0", "b"): 2.0})]
        assert_matches_full_render(data, cache)
        # Series evicted (structural GC: the old pod's series vanish).
        data = [(GAUGE_SPEC, {("0", "b"): 2.5})]
        assert_matches_full_render(data, cache)
        assert cache.template.generation > gen0

    def test_conditional_family_appears_and_empties(self):
        cache = PrefixCache()
        base = [(GAUGE_SPEC, {("0", "a"): 1.0})]
        assert_matches_full_render(base, cache)
        # A conditional surface appears mid-run (declared + sampled).
        data = base + [(CONDITIONAL_SPEC, {("oom",): 1.0})]
        assert_matches_full_render(data, cache)
        # It stays declared but loses all samples: header-only block.
        data = base + [(CONDITIONAL_SPEC, {})]
        assert_matches_full_render(data, cache)
        # And comes back.
        data = base + [(CONDITIONAL_SPEC, {("evict",): 2.0})]
        assert_matches_full_render(data, cache)

    def test_special_float_values(self):
        cache = PrefixCache()
        data = [(GAUGE_SPEC, {("0", "a"): 1.0, ("1", "a"): 2.0})]
        assert_matches_full_render(data, cache)
        data = [(GAUGE_SPEC, {
            ("0", "a"): float("nan"), ("1", "a"): float("inf"),
        })]
        assert_matches_full_render(data, cache)
        data = [(GAUGE_SPEC, {
            ("0", "a"): float("-inf"), ("1", "a"): -0.0,
        })]
        assert_matches_full_render(data, cache)

    def test_escaped_label_values(self):
        cache = PrefixCache()
        data = [(GAUGE_SPEC, {
            ('quo"te', "a"): 1.0,
            ("back\\slash", "a"): 2.0,
            ("new\nline", "a"): 3.0,
        })]
        assert_matches_full_render(data, cache)
        data = [(GAUGE_SPEC, {
            ('quo"te', "a"): 4.0,
            ("back\\slash", "a"): 2.0,
            ("new\nline", "a"): 3.0,
        })]
        assert_matches_full_render(data, cache)

    def test_splice_disabled_still_identical(self):
        cache = PrefixCache(splice=False)
        assert cache.template is None
        data = [
            (GAUGE_SPEC, {("0", "a"): 1.0}),
            (COUNTER_SPEC, {("x",): 10.0}),
        ]
        assert_matches_full_render(data, cache)
        data = [
            (GAUGE_SPEC, {("0", "a"): 2.0}),
            (COUNTER_SPEC, {("x",): 11.0}),
        ]
        assert_matches_full_render(data, cache)


class TestEncodingCacheInvalidation:
    def test_unchanged_polls_share_the_bodyset(self):
        """Byte-identical consecutive polls reuse the SAME BodySet: the
        gzip compressed at poll N is served verbatim at poll N+k."""
        cache = PrefixCache()
        data = [(GAUGE_SPEC, {("0", "a"): 1.0})]
        s1 = build(data, cache)
        s1.encode()
        gz1 = s1.encode_gzip()
        om1 = s1.encode_openmetrics()
        s2 = build(data, cache)
        s2.encode()
        assert s2._bodyset is s1._bodyset
        # Derived encodings are already cached — identical objects, no
        # recompression.
        assert s2.encode_gzip() is gz1
        assert s2.encode_openmetrics() is om1
        assert s2.cached_exposition(gzipped=True) is gz1

    def test_changed_bytes_mint_a_new_bodyset(self):
        cache = PrefixCache()
        data = [(GAUGE_SPEC, {("0", "a"): 1.0})]
        s1 = build(data, cache)
        s1.encode()
        gz1 = s1.encode_gzip()
        om1 = s1.encode_openmetrics()
        data = [(GAUGE_SPEC, {("0", "a"): 2.0})]
        s2 = build(data, cache)
        s2.encode()
        assert s2._bodyset is not s1._bodyset
        assert s2._bodyset.revision > s1._bodyset.revision
        # Fresh revision: stale encodings must not be served.
        assert s2.cached_exposition(gzipped=True) is None
        gz2 = s2.encode_gzip()
        assert gz2 is not gz1
        assert gzip.decompress(gz2) == s2.encode()
        assert s2.encode_openmetrics() != om1
        # The earlier snapshot still serves ITS revision untouched.
        assert gzip.decompress(gz1) == s1.encode()

    def test_nan_cells_do_not_churn_the_bodyset(self):
        """A NaN value compares unequal to itself every poll but renders
        the same 'NaN' bytes — it must NOT mint a new BodySet per poll
        (that would silently discard the gzip/OpenMetrics caches for a
        byte-identical body)."""
        cache = PrefixCache()
        data = [(GAUGE_SPEC, {("0", "a"): float("nan"), ("1", "a"): 1.0})]
        s1 = build(data, cache)
        s1.encode()
        gz1 = s1.encode_gzip()
        s2 = build(data, cache)
        s2.encode()
        assert s2._bodyset is s1._bodyset
        assert s2.encode_gzip() is gz1

    def test_layout_churn_bumps_generation_and_invalidates(self):
        cache = PrefixCache()
        data = [(GAUGE_SPEC, {("0", "a"): 1.0})]
        s1 = build(data, cache)
        s1.encode()
        s1.encode_gzip()
        g1 = s1._bodyset.generation
        data = [(GAUGE_SPEC, {("0", "a"): 1.0, ("9", "z"): 5.0})]
        s2 = build(data, cache)
        s2.encode()
        assert s2._bodyset.generation > g1
        assert s2.cached_exposition(gzipped=True) is None
        assert gzip.decompress(s2.encode_gzip()) == s2.encode()

    def test_identity_body_cached_at_encode(self):
        """The event-loop inline fast path: after swap-time encode() the
        identity body is served from cache with no render work."""
        cache = PrefixCache()
        s = build([(GAUGE_SPEC, {("0", "a"): 1.0})], cache)
        assert s.cached_exposition() is None  # not yet encoded
        body = s.encode()
        assert s.cached_exposition() is body
        assert s.cached_exposition(openmetrics=True) is None
        om = s.encode_openmetrics()
        assert s.cached_exposition(openmetrics=True) == om


def _random_label(rng: random.Random) -> str:
    pool = ["plain", 'quo"te', "back\\slash", "new\nline", "ünicode", ""]
    return rng.choice(pool) + str(rng.randrange(4))


def _random_value(rng: random.Random) -> float:
    r = rng.random()
    if r < 0.05:
        return float("nan")
    if r < 0.08:
        return float("inf")
    if r < 0.10:
        return float("-inf")
    if r < 0.40:
        return float(rng.randrange(-1000, 1000))  # integer-formatted
    return rng.uniform(-1e12, 1e12)


def test_seeded_property_sweep():
    """Randomized poll sequence (seeded, so failures reproduce): random
    value churn, series add/evict, family appear/empty — every poll's
    spliced body must equal the full re-render, in all four encodings."""
    rng = random.Random(0xC0FFEE)
    cache = PrefixCache()
    specs = [GAUGE_SPEC, SCALAR_SPEC, COUNTER_SPEC, CONDITIONAL_SPEC]
    # Mutable model state the polls evolve.
    samples: dict[str, dict[tuple[str, ...], float]] = {
        GAUGE_SPEC.name: {("0", "a"): 1.0},
        SCALAR_SPEC.name: {(): 0.0},
        COUNTER_SPEC.name: {("x",): 0.0},
        CONDITIONAL_SPEC.name: {},
    }

    def lvs_for(spec: MetricSpec) -> tuple[str, ...]:
        return tuple(_random_label(rng) for _ in spec.label_names)

    for poll in range(60):
        for spec in specs:
            fam = samples[spec.name]
            # value churn on some existing series
            for k in list(fam):
                if rng.random() < 0.5:
                    fam[k] = _random_value(rng)
            # occasional series add / evict (not for the scalar family)
            if spec.label_names:
                if rng.random() < 0.25:
                    fam[lvs_for(spec)] = _random_value(rng)
                if fam and rng.random() < 0.15:
                    fam.pop(rng.choice(list(fam)))
        data = [(spec, dict(samples[spec.name])) for spec in specs]
        spliced = assert_matches_full_render(data, cache)
        assert spliced._bodyset is not None
    stats = cache.template.stats()
    # The sweep must actually exercise the incremental machinery, not
    # fall through to full rebuilds every poll.
    assert stats["polls"] == 60  # the no-cache reference renders bypass it
    assert stats["spliced_cells"] > 0
    assert stats["generation"] > 0
