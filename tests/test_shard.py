"""Sharded HA aggregation tree (tpu_pod_exporter.shard) tests.

Covers the ISSUE 8 acceptance surface:

- consistent-hash properties: assignment stability, bounded movement on
  target add/remove (only the churned targets move) and shard add/remove
  (≤ targets/n + slack), shard-map persistence roundtrip across a leaf
  restart;
- TargetSet live membership (--targets-file mtime reload, filter cut,
  breaker carryover for targets that reshard in);
- leaf component emission and the root's freshest-wins HA dedup (zero
  series loss when one HA leaf dies, stale-win counting when the freshest
  leaf lacks a series);
- root rollups equal to a flat single-aggregator oracle over the same
  scrape set;
- the two-level query plane's envelope (per-leaf state + per-target
  state, uncovered-shard partiality);
- the chaos leaf-kill timeline grammar and hook;
- status --tree rendering.
"""

from __future__ import annotations

import math
import os
import time

import pytest

from tpu_pod_exporter import shard as sh
from tpu_pod_exporter.aggregate import (
    SliceAggregator,
    TargetSet,
    read_targets_file,
)
from tpu_pod_exporter.metrics import SnapshotStore, schema
from tpu_pod_exporter.metrics.parse import parse_families


# --------------------------------------------------------------- fixtures


def node_body(idx: int, rnd: int = 0, chips: int = 2, n_slices: int = 4) -> str:
    """Deterministic synthetic exporter body for target ``idx`` at round
    ``rnd`` — the no-sockets twin of loadgen's SynthTargetFarm.body."""
    sl = idx % n_slices
    host = f"host-{idx:04d}"
    base = (f'accelerator="v5p-sim",slice_name="slice-{sl}",host="{host}",'
            f'worker_id="{idx}"')
    pod = f"job-{idx % 5}"
    lines = []
    pod_hbm = 0.0
    for c in range(chips):
        cl = (f'chip_id="{c}",device_path="",{base},pod="{pod}",'
              f'namespace="sim",container="w"')
        hbm = float((idx + 1) * 2**20 + rnd * 65536 + c * 4096)
        pod_hbm += hbm
        lines.append(f'tpu_chip_info{{{cl},device_kind="",coords=""}} 1')
        lines.append(f'tpu_hbm_used_bytes{{{cl}}} {hbm:.1f}')
        lines.append(f'tpu_hbm_total_bytes{{{cl}}} {float(96 * 2**30):.1f}')
        lines.append(
            f'tpu_tensorcore_duty_cycle_percent{{{cl}}} '
            f'{float((idx * 7 + c + rnd) % 100):.1f}')
    lines.append(
        f'tpu_host_info{{{base},multislice_group="ms-{sl % 2}",'
        f'num_slices="2"}} 1')
    lines.append(
        f'tpu_pod_chip_count{{pod="{pod}",namespace="sim",{base}}} {chips}')
    lines.append(
        f'tpu_pod_hbm_used_bytes{{pod="{pod}",namespace="sim",{base}}} '
        f'{pod_hbm:.1f}')
    return "\n".join(lines) + "\n"


def target_name(idx: int) -> str:
    return f"h{idx}:8000"


def make_fetch(rnd_ref, down=()):
    down = set(down)

    def fetch(target, timeout_s):
        if target in down:
            raise ConnectionError(f"{target} down")
        idx = int(target.split(":")[0][1:])
        return node_body(idx, rnd_ref[0])

    return fetch


ROLLUPS = (
    "tpu_slice_hosts_reporting",
    "tpu_slice_chip_count",
    "tpu_slice_hbm_used_bytes",
    "tpu_slice_hbm_total_bytes",
    "tpu_slice_hbm_used_percent",
    "tpu_slice_tensorcore_duty_cycle_avg_percent",
    "tpu_multislice_slices_reporting",
    "tpu_multislice_hosts_reporting",
    "tpu_multislice_chip_count",
    "tpu_multislice_hbm_used_bytes",
    "tpu_workload_chip_count",
    "tpu_workload_hbm_used_bytes",
    "tpu_workload_hosts",
    "tpu_aggregator_target_up",
)


def rollup_map(text: str) -> dict:
    fams = parse_families(text)
    out = {}
    for name in ROLLUPS:
        for s in fams.get(name, ()):
            out[(name, tuple(sorted(s.labels.items())))] = s.value
    return out


def build_tree(targets, shards=2, ha=True, rnd_ref=None, down=()):
    """In-process tree over injected fetches: {leaf addr: (agg, store)},
    topology, shard map."""
    rnd_ref = rnd_ref if rnd_ref is not None else [0]
    fetch = make_fetch(rnd_ref, down)
    smap = sh.ShardMap(sh.default_shards(shards))
    leaves = {}
    topo = {}
    for si in range(shards):
        shard_id = f"shard-{si}"
        addrs = []
        for suffix in ("a", "b") if ha else ("a",):
            store = SnapshotStore()
            agg = sh.LeafAggregator(
                shard_id, f"{si}{suffix}", smap,
                targets=targets, store=store, fetch=fetch,
            )
            addr = f"leaf-{si}{suffix}:9100"
            leaves[addr] = (agg, store)
            addrs.append(addr)
        topo[shard_id] = tuple(addrs)
    return leaves, topo, smap, fetch, rnd_ref


def leaf_fetch_for(leaves, dead=()):
    dead = set(dead)

    def leaf_fetch(addr, timeout_s):
        if addr in dead:
            raise ConnectionError(f"{addr} killed")
        return leaves[addr][1].current().encode().decode()

    return leaf_fetch


# ------------------------------------------------------------- ShardMap


class TestShardMap:
    def test_assignment_stability(self):
        targets = [target_name(i) for i in range(500)]
        a = sh.ShardMap(sh.default_shards(8)).assignments(targets)
        b = sh.ShardMap(sh.default_shards(8)).assignments(targets)
        assert a == b

    def test_every_target_assigned_to_known_shard(self):
        m = sh.ShardMap(sh.default_shards(5))
        for i in range(200):
            assert m.assign(target_name(i)) in m.shards

    def test_distribution_roughly_even(self):
        m = sh.ShardMap(sh.default_shards(8))
        counts: dict[str, int] = {}
        for i in range(2000):
            s = m.assign(target_name(i))
            counts[s] = counts.get(s, 0) + 1
        # vnodes=64 keeps the spread within ~2x of ideal.
        ideal = 2000 / 8
        assert min(counts.values()) > ideal / 2
        assert max(counts.values()) < ideal * 2

    @pytest.mark.parametrize("seed", range(5))
    def test_target_churn_moves_only_churned_targets(self, seed):
        m = sh.ShardMap(sh.default_shards(8))
        targets = [target_name(seed * 1000 + i) for i in range(300)]
        before = m.assignments(targets)
        removed = targets[seed::17][:16]
        added = [target_name(seed * 1000 + 1000 + i) for i in range(16)]
        after_targets = [t for t in targets if t not in removed] + added
        after = m.assignments(after_targets)
        # Surviving targets NEVER move on pure target churn.
        for t in set(targets) & set(after_targets):
            assert before[t] == after[t]
        moves = sh.count_moves(before, after)
        assert moves == len(removed) + len(added)
        # The acceptance bound, with slack: churned + targets/shards.
        assert moves <= 32 + len(after_targets) // 8

    @pytest.mark.parametrize("n,delta", [(4, 1), (8, 1), (8, -1)])
    def test_shard_churn_bounded_movement(self, n, delta):
        targets = [target_name(i) for i in range(800)]
        before = sh.ShardMap(sh.default_shards(n)).assignments(targets)
        after = sh.ShardMap(sh.default_shards(n + delta)).assignments(targets)
        moved = sum(1 for t in targets if before[t] != after[t])
        smaller = min(n, n + delta)
        # One shard's worth of arcs, with 2x slack for vnode variance.
        assert moved <= 2 * len(targets) // smaller

    def test_doc_roundtrip(self):
        m = sh.ShardMap(sh.default_shards(3), vnodes=16)
        m2 = sh.ShardMap.from_doc(m.to_doc())
        targets = [target_name(i) for i in range(100)]
        assert m.assignments(targets) == m2.assignments(targets)

    def test_rejects_empty(self):
        with pytest.raises(ValueError):
            sh.ShardMap([])
        with pytest.raises(ValueError):
            sh.default_shards(0)


class TestShardMapFile:
    def test_roundtrip_and_tolerant_load(self, tmp_path):
        from tpu_pod_exporter.persist import ShardMapFile

        f = ShardMapFile(str(tmp_path / "map.json"))
        assert f.load() == {}
        f.save({"ring": {"shards": ["shard-0"], "vnodes": 8}, "moves": 3})
        doc = f.load()
        assert doc["moves"] == 3
        assert doc["ring"]["shards"] == ["shard-0"]
        # Corrupt file: tolerated, never refuses.
        (tmp_path / "map.json").write_bytes(b"{truncated")
        assert f.load() == {}


# ------------------------------------------------------------- TargetSet


class TestTargetSet:
    def test_static_membership(self):
        ts = TargetSet(("a:1", "b:1", "a:1"))
        assert ts.targets == ("a:1", "b:1")
        assert ts.refresh() == (0, 0)

    def test_filter_cut(self):
        ts = TargetSet(("a:1", "b:1", "c:1"),
                       filter_fn=lambda t: [x for x in t if x != "b:1"])
        assert ts.targets == ("a:1", "c:1")

    def test_file_reload_on_mtime_change(self, tmp_path):
        f = tmp_path / "targets"
        f.write_text("a:1\nb:1\n")
        ts = TargetSet(targets_file=str(f))
        assert ts.targets == ("a:1", "b:1")
        assert ts.moves == 0  # boot population is not churn
        f.write_text("a:1\nc:1\n# comment\n")
        os.utime(f, (time.time() + 5, time.time() + 5))
        assert ts.refresh() == (1, 1)
        assert ts.targets == ("a:1", "c:1")
        assert ts.moves == 2
        # Unchanged mtime: no reload work.
        assert ts.refresh() == (0, 0)

    def test_unreadable_file_keeps_membership(self, tmp_path):
        f = tmp_path / "targets"
        f.write_text("a:1\n")
        ts = TargetSet(targets_file=str(f))
        f.unlink()
        assert ts.refresh() == (0, 0)
        assert ts.targets == ("a:1",)

    def test_breakers_follow_membership(self):
        ts = TargetSet(("a:1", "b:1"), breaker_failures=2)
        assert set(ts.breakers) == {"a:1", "b:1"}
        br_map_identity = ts.breakers
        ts.set_targets(("b:1", "c:1"))
        assert set(ts.breakers) == {"b:1", "c:1"}
        # The dict OBJECT is stable: fleet-plane holders see live state.
        assert ts.breakers is br_map_identity

    def test_saved_breaker_restored_when_target_reshards_in(self, tmp_path):
        from tpu_pod_exporter.persist import BreakerStateFile

        store = BreakerStateFile(str(tmp_path / "b.json"))
        ts = TargetSet(("a:1",), breaker_failures=1, breaker_store=store)
        ts.breakers["a:1"].record_failure()
        assert ts.breakers["a:1"].state != "closed"
        ts.maybe_save_breakers()
        # New process, target arrives LATER via a membership change: the
        # quarantine must still carry over.
        ts2 = TargetSet((), targets_file="", breaker_failures=1,
                        breaker_store=BreakerStateFile(str(tmp_path / "b.json")))
        ts2.set_targets(("a:1",))
        assert ts2.breakers["a:1"].state != "closed"

    def test_empty_reload_keeps_membership_and_breakers(self, tmp_path):
        # A truncated in-place rewrite reads as an EMPTY file for one
        # round; applying it would wipe every quarantine and empty the
        # fleet view. The reload must keep the last known membership.
        f = tmp_path / "targets"
        f.write_text("a:1\nb:1\n")
        ts = TargetSet(targets_file=str(f), breaker_failures=1)
        ts.breakers["a:1"].record_failure()
        assert ts.breakers["a:1"].state != "closed"
        f.write_text("")
        os.utime(f, (time.time() + 5, time.time() + 5))
        assert ts.refresh() == (0, 0)
        assert ts.targets == ("a:1", "b:1")
        assert ts.breakers["a:1"].state != "closed"
        # The repaired file (fresh mtime) applies normally.
        f.write_text("b:1\n")
        os.utime(f, (time.time() + 10, time.time() + 10))
        assert ts.refresh() == (0, 1)
        assert ts.targets == ("b:1",)

    def test_recovered_target_not_requarantined_from_stale_boot_doc(
            self, tmp_path):
        from tpu_pod_exporter.persist import BreakerStateFile

        store = BreakerStateFile(str(tmp_path / "b.json"))
        ts = TargetSet(("a:1",), breaker_failures=1, breaker_store=store)
        ts.breakers["a:1"].record_failure()
        ts.maybe_save_breakers()
        # New process: boot restores OPEN, the target recovers...
        ts2 = TargetSet(("a:1",), breaker_failures=1,
                        breaker_store=BreakerStateFile(str(tmp_path / "b.json")))
        assert ts2.breakers["a:1"].state != "closed"
        ts2.breakers["a:1"].record_success()
        br = ts2.breakers["a:1"]
        while br.state != "closed":  # half_open probe path
            br.decide()
            br.record_success()
        # ...then bounces out and back: the consumed boot doc must NOT
        # re-quarantine the healthy target.
        ts2.set_targets(())
        ts2.set_targets(("a:1",))
        assert ts2.breakers["a:1"].state == "closed"

    def test_quarantine_survives_remove_readd_bounce(self):
        ts = TargetSet(("a:1", "b:1"), breaker_failures=1)
        ts.breakers["a:1"].record_failure()
        assert ts.breakers["a:1"].state != "closed"
        ts.set_targets(("b:1",))       # a:1 bounces out (partial read)...
        ts.set_targets(("a:1", "b:1"))  # ...and back next round
        assert ts.breakers["a:1"].state != "closed"

    def test_read_targets_file_grammar(self, tmp_path):
        f = tmp_path / "t"
        f.write_text("a:1, b:1\n# all of c\nc:1\n\na:1\n")
        assert read_targets_file(str(f)) == ("a:1", "b:1", "c:1")


# ---------------------------------------------------------------- leaf tier


class TestLeafAggregator:
    def test_shard_filter_partitions_targets(self):
        targets = tuple(target_name(i) for i in range(60))
        leaves, topo, smap, fetch, rnd = build_tree(targets, shards=3,
                                                    ha=False)
        owned = []
        for addr, (agg, _store) in leaves.items():
            owned.extend(agg.targets)
            for t in agg.targets:
                assert smap.assign(t) == agg.shard_id
        assert sorted(owned) == sorted(targets)

    def test_component_emission(self):
        targets = tuple(target_name(i) for i in range(10))
        leaves, topo, smap, fetch, rnd = build_tree(targets, shards=1,
                                                    ha=False)
        agg, store = leaves["leaf-0a:9100"]
        agg.poll_once()
        fams = parse_families(store.current().encode().decode())
        comp = fams[schema.TPU_LEAF_SLICE_COMPONENT.name]
        fields = {s.labels["field"] for s in comp}
        assert fields == set(schema.LEAF_SLICE_FIELDS)
        # chips component must agree with the public rollup.
        chips_pub = {
            s.labels["slice_name"]: s.value
            for s in fams["tpu_slice_chip_count"]
        }
        chips_comp = {
            s.labels["slice_name"]: s.value
            for s in comp if s.labels["field"] == "chips"
        }
        assert chips_pub == chips_comp
        info = fams[schema.TPU_LEAF_SHARD_INFO.name][0]
        assert info.labels["shard"] == "shard-0"
        assert fams[schema.TPU_LEAF_TARGETS.name][0].value == 10.0
        assert schema.TPU_LEAF_WORKLOAD_COMPONENT.name in fams
        assert schema.TPU_LEAF_SLICE_GROUP_INFO.name in fams

    def test_live_reshard_via_targets_file(self, tmp_path):
        f = tmp_path / "targets"
        targets = [target_name(i) for i in range(20)]
        f.write_text("\n".join(targets) + "\n")
        rnd = [0]
        smap = sh.ShardMap(sh.default_shards(2))
        store = SnapshotStore()
        agg = sh.LeafAggregator(
            "shard-0", "0a", smap, targets_file=str(f),
            store=store, fetch=make_fetch(rnd),
        )
        before = set(agg.targets)
        assert all(smap.assign(t) == "shard-0" for t in before)
        # Churn the GLOBAL list; the leaf keeps only its own cut.
        added = [target_name(100 + i) for i in range(10)]
        f.write_text("\n".join(targets[5:] + added) + "\n")
        os.utime(f, (time.time() + 5, time.time() + 5))
        agg.poll_once()
        after = set(agg.targets)
        assert all(smap.assign(t) == "shard-0" for t in after)
        expected = {
            t for t in (targets[5:] + added) if smap.assign(t) == "shard-0"
        }
        assert after == expected
        # Moves counted = targets that entered/left THIS shard.
        delta = len(before - after) + len(after - before)
        assert agg._tset.moves == delta

    def test_shard_map_persistence_roundtrip_across_restart(self, tmp_path):
        from tpu_pod_exporter.persist import ShardMapFile

        f = tmp_path / "targets"
        targets = [target_name(i) for i in range(20)]
        f.write_text("\n".join(targets) + "\n")
        rnd = [0]
        smap = sh.ShardMap(sh.default_shards(2))
        mstore = ShardMapFile(str(tmp_path / "map.json"))
        agg = sh.LeafAggregator(
            "shard-0", "0a", smap, shard_map_store=mstore,
            targets_file=str(f), store=SnapshotStore(),
            fetch=make_fetch(rnd),
        )
        first = set(agg.targets)
        # Reshard while "down": rewrite the file, then boot a NEW leaf on
        # the same store — the boot delta counts as moves, carried on top
        # of the restored counter.
        added = [target_name(200 + i) for i in range(8)]
        f.write_text("\n".join(targets[4:] + added) + "\n")
        os.utime(f, (time.time() + 5, time.time() + 5))
        agg2 = sh.LeafAggregator(
            "shard-0", "0a", smap,
            shard_map_store=ShardMapFile(str(tmp_path / "map.json")),
            targets_file=str(f), store=SnapshotStore(),
            fetch=make_fetch(rnd),
        )
        second = set(agg2.targets)
        delta = len(first - second) + len(second - first)
        assert agg2._tset.moves == delta
        doc = ShardMapFile(str(tmp_path / "map.json")).load()
        assert doc["ring"] == smap.to_doc()
        assert set(doc["assigned"]) == second


# ------------------------------------------------------- root merge / dedup


class TestMergeShardViews:
    def _view(self, leaf, ts, slices=None, targets=None):
        v = sh.LeafView(leaf=leaf, round_ts=ts)
        for key, chips in (slices or {}).items():
            v.slice_fields[key] = {"chips": chips, "hosts": 1.0}
        for t, up in (targets or {}).items():
            v.target_up[t] = up
        return v

    def test_freshest_leaf_wins_per_series(self):
        a = self._view("a", 100.0, slices={("s", "v"): 4.0},
                       targets={"t1": 1.0})
        b = self._view("b", 200.0, slices={("s", "v"): 8.0},
                       targets={"t1": 0.0})
        out = sh.merge_shard_views([a, b])
        assert out.slices[("s", "v")].chips == 8.0
        assert out.target_up["t1"] == (0.0, 200.0)
        assert out.stale_wins == 0

    def test_stale_win_counted_when_freshest_lacks_series(self):
        # b is freshest but mid-warmup: it has no view of slice ("s2","v")
        # or target t2 — the stale leaf's values must still land.
        a = self._view("a", 100.0,
                       slices={("s", "v"): 4.0, ("s2", "v"): 2.0},
                       targets={"t1": 1.0, "t2": 1.0})
        b = self._view("b", 200.0, slices={("s", "v"): 8.0},
                       targets={"t1": 1.0})
        out = sh.merge_shard_views([a, b])
        assert out.slices[("s2", "v")].chips == 2.0
        assert out.target_up["t2"] == (1.0, 100.0)
        assert out.stale_wins == 2
        assert out.slices[("s", "v")].chips == 8.0  # fresh one still wins

    def test_single_view_passthrough(self):
        a = self._view("a", 50.0, slices={("s", "v"): 4.0})
        out = sh.merge_shard_views([a])
        assert out.slices[("s", "v")].chips == 4.0
        assert out.stale_wins == 0

    def test_empty(self):
        out = sh.merge_shard_views([])
        assert out.slices == {} and out.stale_wins == 0


class TestRootAggregator:
    def test_root_equals_flat_oracle(self):
        targets = tuple(target_name(i) for i in range(40))
        rnd = [0]
        leaves, topo, smap, fetch, rnd = build_tree(targets, shards=2,
                                                    ha=True, rnd_ref=rnd)
        for agg, _s in leaves.values():
            agg.poll_once()
        root_store = SnapshotStore()
        root = sh.RootAggregator(topo, root_store,
                                 fetch=leaf_fetch_for(leaves))
        root.poll_once()
        oracle_store = SnapshotStore()
        oracle = SliceAggregator(targets, oracle_store,
                                 fetch=make_fetch(rnd))
        oracle.poll_once()
        rm = rollup_map(root_store.current().encode().decode())
        om = rollup_map(oracle_store.current().encode().decode())
        assert set(rm) == set(om)
        for k in om:
            assert math.isclose(rm[k], om[k], rel_tol=1e-9), (k, rm[k], om[k])
        root.close()
        oracle.close()

    def test_ha_leaf_death_loses_zero_series(self):
        targets = tuple(target_name(i) for i in range(40))
        leaves, topo, smap, fetch, rnd = build_tree(targets, shards=2,
                                                    ha=True)
        for agg, _s in leaves.values():
            agg.poll_once()
        root_store = SnapshotStore()
        root = sh.RootAggregator(topo, root_store,
                                 fetch=leaf_fetch_for(leaves))
        root.poll_once()
        before = rollup_map(root_store.current().encode().decode())
        dead = topo["shard-0"][0]
        root._fetch = leaf_fetch_for(leaves, dead=[dead])
        root.poll_once()
        body = root_store.current().encode().decode()
        after = rollup_map(body)
        assert set(after) == set(before)
        for k in before:
            assert math.isclose(after[k], before[k], rel_tol=1e-9)
        fams = parse_families(body)
        up = {(s.labels["shard"], s.labels["leaf"]): s.value
              for s in fams[schema.TPU_ROOT_LEAF_UP.name]}
        assert up[("shard-0", dead)] == 0.0
        assert up[("shard-0", topo["shard-0"][1])] == 1.0
        root.close()

    def test_both_leaves_of_shard_dead_drops_only_that_shard(self):
        targets = tuple(target_name(i) for i in range(40))
        leaves, topo, smap, fetch, rnd = build_tree(targets, shards=2,
                                                    ha=True)
        for agg, _s in leaves.values():
            agg.poll_once()
        root_store = SnapshotStore()
        root = sh.RootAggregator(
            topo, root_store,
            fetch=leaf_fetch_for(leaves, dead=list(topo["shard-0"])),
            breaker_failures=0,
        )
        root.poll_once()
        fams = parse_families(root_store.current().encode().decode())
        up_targets = {s.labels["target"]
                      for s in fams["tpu_aggregator_target_up"]}
        shard1_targets = {t for t in targets
                          if smap.assign(t) == "shard-1"}
        assert up_targets == shard1_targets
        root.close()

    def test_shard_claim_mismatch_rejected(self):
        targets = tuple(target_name(i) for i in range(10))
        leaves, topo, smap, fetch, rnd = build_tree(targets, shards=2,
                                                    ha=False)
        for agg, _s in leaves.values():
            agg.poll_once()
        # Cross-wire: put shard-1's leaf under shard-0 in the topology.
        bad_topo = {"shard-0": (topo["shard-1"][0],)}
        root_store = SnapshotStore()
        root = sh.RootAggregator(bad_topo, root_store,
                                 fetch=leaf_fetch_for(leaves))
        root.poll_once()
        fams = parse_families(root_store.current().encode().decode())
        up = {s.labels["leaf"]: s.value
              for s in fams[schema.TPU_ROOT_LEAF_UP.name]}
        # The mis-claimed body is refused: the leaf reads down.
        assert up[topo["shard-1"][0]] == 0.0
        root.close()

    def test_removed_target_counter_series_pruned(self, tmp_path):
        # Per-target counters must leave the exposition with the target:
        # on a churning fleet they would otherwise accumulate forever.
        f = tmp_path / "targets"
        f.write_text("h1:8000\nh2:8000\n")
        rnd = [0]

        def fetch(target, timeout_s):
            if target == "h1:8000":
                raise ConnectionError("down")
            return node_body(2, rnd[0])

        store = SnapshotStore()
        agg = SliceAggregator((), store, fetch=fetch, breaker_failures=0,
                              targets_file=str(f))
        agg.poll_once()
        fams = parse_families(store.current().encode().decode())
        errs = {s.labels["target"]
                for s in fams["tpu_aggregator_scrape_errors_total"]}
        assert errs == {"h1:8000"}
        f.write_text("h2:8000\n")
        os.utime(f, (time.time() + 5, time.time() + 5))
        agg.poll_once()
        fams = parse_families(store.current().encode().decode())
        assert "tpu_aggregator_scrape_errors_total" not in fams or not [
            s for s in fams["tpu_aggregator_scrape_errors_total"]
            if s.labels["target"] == "h1:8000"
        ]
        agg.close()

    def test_root_empty_targets_file_keeps_assignments(self, tmp_path):
        targets = [target_name(i) for i in range(20)]
        f = tmp_path / "targets"
        f.write_text("\n".join(targets) + "\n")
        leaves, topo, smap, fetch, rnd = build_tree(tuple(targets),
                                                    shards=2, ha=False)
        for agg, _s in leaves.values():
            agg.poll_once()
        root_store = SnapshotStore()
        root = sh.RootAggregator(topo, root_store,
                                 fetch=leaf_fetch_for(leaves),
                                 targets_file=str(f), shard_map=smap)
        root.poll_once()
        f.write_text("")  # torn in-place rewrite reads empty for a round
        os.utime(f, (time.time() + 5, time.time() + 5))
        root.poll_once()
        fams = parse_families(root_store.current().encode().decode())
        assert fams[schema.TPU_ROOT_RESHARD_MOVES_TOTAL.name][0].value == 0.0
        root.close()

    def test_ring_mismatch_rejected(self):
        # Same shard id, different ring: a leaf restarted with a new
        # --num-shards covers a different target subset — summing its
        # body would double-count. The root must refuse it.
        targets = tuple(target_name(i) for i in range(10))
        rnd = [0]
        smap16 = sh.ShardMap(sh.default_shards(16))
        store = SnapshotStore()
        agg = sh.LeafAggregator("shard-0", "0a", smap16, targets=targets,
                                store=store, fetch=make_fetch(rnd))
        agg.poll_once()
        leaves = {"leaf-0a:9100": (agg, store)}
        root_store = SnapshotStore()
        root = sh.RootAggregator(
            {"shard-0": ("leaf-0a:9100",)}, root_store,
            fetch=leaf_fetch_for(leaves),
            shard_map=sh.ShardMap(sh.default_shards(8)),
        )
        root.poll_once()
        fams = parse_families(root_store.current().encode().decode())
        up = {s.labels["leaf"]: s.value
              for s in fams[schema.TPU_ROOT_LEAF_UP.name]}
        assert up["leaf-0a:9100"] == 0.0
        root.close()
        agg.close()

    def test_reshard_accounting_via_targets_file(self, tmp_path):
        from tpu_pod_exporter.persist import ShardMapFile

        targets = [target_name(i) for i in range(30)]
        f = tmp_path / "targets"
        f.write_text("\n".join(targets) + "\n")
        leaves, topo, smap, fetch, rnd = build_tree(tuple(targets),
                                                    shards=2, ha=False)
        for agg, _s in leaves.values():
            agg.poll_once()
        root_store = SnapshotStore()
        root = sh.RootAggregator(
            topo, root_store, fetch=leaf_fetch_for(leaves),
            targets_file=str(f), shard_map=smap,
            shard_map_store=ShardMapFile(str(tmp_path / "rm.json")),
        )
        root.poll_once()
        fams = parse_families(root_store.current().encode().decode())
        assert fams[schema.TPU_ROOT_RESHARD_MOVES_TOTAL.name][0].value == 0.0
        f.write_text("\n".join(targets[4:]) + "\n")
        os.utime(f, (time.time() + 5, time.time() + 5))
        root.poll_once()
        fams = parse_families(root_store.current().encode().decode())
        assert fams[schema.TPU_ROOT_RESHARD_MOVES_TOTAL.name][0].value == 4.0
        # Restart: counter restored from the shard-map file.
        root2 = sh.RootAggregator(
            topo, SnapshotStore(), fetch=leaf_fetch_for(leaves),
            targets_file=str(f), shard_map=smap,
            shard_map_store=ShardMapFile(str(tmp_path / "rm.json")),
        )
        store2 = root2._store
        root2.poll_once()
        fams = parse_families(store2.current().encode().decode())
        assert fams[schema.TPU_ROOT_RESHARD_MOVES_TOTAL.name][0].value == 4.0
        root.close()
        root2.close()


class TestParseLeafTopology:
    def test_grammar(self):
        topo = sh.parse_leaf_topology(
            "shard-0=a:1|b:1, shard-1=c:1")
        assert topo == {"shard-0": ("a:1", "b:1"), "shard-1": ("c:1",)}

    @pytest.mark.parametrize("bad", [
        "", "shard-0", "shard-0=", "=a:1", "shard-0=a:1,shard-0=b:1",
    ])
    def test_rejects(self, bad):
        with pytest.raises(ValueError):
            sh.parse_leaf_topology(bad)


# ------------------------------------------------------ two-level queries


class TestRootQueryPlane:
    def _leaf_env(self, rows, targets=None, partial=False):
        return {
            "status": "ok", "partial": partial,
            "data": {"result": rows},
            "targets": targets or {},
        }

    def _row(self, metric, host, value, ts):
        return {"metric": metric, "labels": {"host": host},
                "stats": {"last": value}, "last_sample_wall_ts": ts}

    def test_ha_dedup_freshest_row_wins(self):
        topo = {"shard-0": ("la:1", "lb:1")}
        envs = {
            "la:1": self._leaf_env(
                [self._row("m", "h0", 1.0, 100.0)],
                targets={"t0": {"state": "ok"}}),
            "lb:1": self._leaf_env(
                [self._row("m", "h0", 2.0, 200.0)],
                targets={"t0": {"state": "ok"}}),
        }

        def fetch(url, timeout_s):
            for leaf, env in envs.items():
                if leaf.split(":")[0] in url:
                    return env
            raise ConnectionError(url)

        plane = sh.RootQueryPlane(topo, fetch=fetch)
        out = plane.window_stats("m")
        assert out["partial"] is False
        rows = out["data"]["result"]
        assert len(rows) == 1 and rows[0]["stats"]["last"] == 2.0
        assert out["fleet"]["duplicate_series"] == 1
        assert out["leaves"]["la:1"]["state"] == "ok"
        assert out["targets"]["t0"]["state"] == "ok"
        plane.close()

    def test_dead_leaf_with_live_twin_not_partial(self):
        topo = {"shard-0": ("la:1", "lb:1")}

        def fetch(url, timeout_s):
            if "la" in url:
                raise ConnectionError("down")
            return self._leaf_env([self._row("m", "h0", 2.0, 200.0)],
                                  targets={"t0": {"state": "ok"}})

        plane = sh.RootQueryPlane(topo, fetch=fetch)
        out = plane.window_stats("m")
        assert out["partial"] is False
        assert out["leaves"]["la:1"]["state"] == "error"
        assert out["fleet"]["uncovered_shards"] == []
        plane.close()

    def test_uncovered_shard_is_partial(self):
        topo = {"shard-0": ("la:1",), "shard-1": ("lb:1",)}

        def fetch(url, timeout_s):
            if "la" in url:
                raise ConnectionError("down")
            return self._leaf_env([self._row("m", "h1", 1.0, 10.0)])

        plane = sh.RootQueryPlane(topo, fetch=fetch)
        out = plane.window_stats("m")
        assert out["partial"] is True
        assert out["fleet"]["uncovered_shards"] == ["shard-0"]
        plane.close()

    def test_404_everywhere_is_no_data_not_partial(self):
        import urllib.error

        topo = {"shard-0": ("la:1",)}

        def fetch(url, timeout_s):
            raise urllib.error.HTTPError(url, 404, "nf", None, None)

        plane = sh.RootQueryPlane(topo, fetch=fetch)
        out = plane.window_stats("m")
        assert out["partial"] is False
        assert out["leaves"]["la:1"]["state"] == "no_data"
        assert out["data"]["result"] == []
        plane.close()

    def test_slow_leaf_marked_timeout_within_overall_deadline(self):
        # A leaf drip-feeding bytes keeps every socket op under the fetch
        # timeout; the ONE overall deadline must mark it `timeout` and
        # answer from the live twin instead of blocking the query.
        topo = {"shard-0": ("la:1", "lb:1")}

        def fetch(url, timeout_s):
            if "la" in url:
                time.sleep(5.0)  # well past the 0.2 + 0.5 deadline
                return self._leaf_env([])
            return self._leaf_env([self._row("m", "h0", 2.0, 200.0)])

        plane = sh.RootQueryPlane(topo, timeout_s=0.2, fetch=fetch)
        t0 = time.monotonic()
        out = plane.window_stats("m")
        assert time.monotonic() - t0 < 3.0
        assert out["leaves"]["la:1"]["state"] == "timeout"
        assert out["leaves"]["lb:1"]["state"] == "ok"
        assert out["partial"] is False  # twin covers the shard
        assert out["data"]["result"][0]["stats"]["last"] == 2.0
        plane.close()

    def test_target_state_best_wins(self):
        topo = {"shard-0": ("la:1", "lb:1")}
        envs = {
            "la": self._leaf_env([], targets={"t0": {"state": "error"}},
                                 partial=True),
            "lb": self._leaf_env([], targets={"t0": {"state": "ok"}}),
        }

        def fetch(url, timeout_s):
            return envs["la" if "la" in url else "lb"]

        plane = sh.RootQueryPlane(topo, fetch=fetch)
        out = plane.window_stats("m")
        assert out["targets"]["t0"]["state"] == "ok"
        assert out["partial"] is False
        plane.close()


# --------------------------------------------------------------- leaf chaos


class TestLeafTimeline:
    def test_parse(self):
        from tpu_pod_exporter.chaos import parse_leaf_timeline

        evs = parse_leaf_timeline("kill:1a@3#12, restart:1a@6")
        assert [(e.action, e.leaf, e.round_idx, e.at_call) for e in evs] == [
            ("kill", "1a", 3, 12), ("restart", "1a", 6, None)]

    @pytest.mark.parametrize("bad", [
        "", "boom:1a@3", "kill:1a", "kill:1a@x", "restart:1a@3#5",
    ])
    def test_parse_rejects(self, bad):
        from tpu_pod_exporter.chaos import parse_leaf_timeline

        with pytest.raises(ValueError):
            parse_leaf_timeline(bad)

    def test_hook_fires_at_coordinates(self):
        from tpu_pod_exporter.chaos import LeafKillHook, parse_leaf_timeline

        killed, restarted = [], []
        hook = LeafKillHook(
            parse_leaf_timeline("kill:1a@2#3,restart:1a@4,kill:0b@5"),
            kill_fn=killed.append, restart_fn=restarted.append,
        )
        hook.begin_round(2)
        assert killed == []  # mid-round kill waits for its scrape index
        assert hook.on_scrape("1a", 2, 1) is False
        assert hook.on_scrape("1a", 2, 3) is True
        assert hook.on_scrape("1a", 2, 4) is False  # one-shot
        assert killed == ["1a"]
        hook.begin_round(4)
        assert restarted == ["1a"]
        hook.begin_round(5)
        assert killed == ["1a", "0b"]  # whole-round kill, no #call
        assert hook.executed == [
            (2, "kill", "1a"), (4, "restart", "1a"), (5, "kill", "0b")]


# -------------------------------------------------------------- status --tree


class TestStatusTree:
    def test_fetch_and_render(self):
        from tpu_pod_exporter.server import MetricsServer
        from tpu_pod_exporter.status import fetch_tree, render_tree

        targets = tuple(target_name(i) for i in range(20))
        leaves, topo, smap, fetch, rnd = build_tree(targets, shards=2,
                                                    ha=True)
        for agg, _s in leaves.values():
            agg.poll_once()
        root_store = SnapshotStore()
        dead = topo["shard-1"][0]
        root = sh.RootAggregator(topo, root_store,
                                 fetch=leaf_fetch_for(leaves, dead=[dead]))
        root.poll_once()
        srv = MetricsServer(root_store, host="127.0.0.1", port=0)
        srv.start()
        try:
            doc = fetch_tree(f"127.0.0.1:{srv.port}")
        finally:
            srv.stop()
            root.close()
        assert set(doc["shards"]) == {"shard-0", "shard-1"}
        assert doc["shards"]["shard-1"]["leaves"][dead]["up"] == 0.0
        assert doc["fleet"]["targets"] == 20
        text = render_tree(doc)
        assert "shard-0" in text and "DOWN" in text
        assert "fleet:" in text and "leaves down:" in text


# -------------------------------------------------------- demo end-to-end


@pytest.mark.parametrize("n_targets", [40])
def test_shard_demo_small_end_to_end(tmp_path, n_targets):
    """The acceptance harness itself, at test scale: churn storm, mid-round
    HA leaf kill + restart, freshest-wins, oracle equality, budgets."""
    from tpu_pod_exporter.loadgen.fleet import run_shard_demo

    result = run_shard_demo(
        n_targets, shards=2, ha=True, chips=2, churn=8,
        round_budget_s=30.0, stale_budget_s=10.0,
        state_root=str(tmp_path / "state"),
    )
    assert result["ok"], result.get("error")
    assert result["kill"]["series_lost"] == []
    assert result["churn"]["assignment_moves"] <= result["churn"]["bound"]
