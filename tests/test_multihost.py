"""Multi-host slice tests (SURVEY.md §4.4, baseline config 4).

N exporter instances — each with its own fake backends representing one host
of a v5p-64 slice — scraped by one Prometheus-style aggregator. Cross-host
rollups happen via labels only; the exporters never talk to each other
(SURVEY.md §2.8: ICI/DCN are measured quantities, not transports).
"""

import urllib.request
from collections import defaultdict

import pytest
from prometheus_client.parser import text_string_to_metric_families

from tpu_pod_exporter.app import ExporterApp
from tpu_pod_exporter.attribution.fake import FakeAttribution, simple_allocation
from tpu_pod_exporter.backend.fake import FakeBackend, FakeChipScript
from tpu_pod_exporter.config import ExporterConfig

GIB = 1024**3

# v5p-64: 32 chips over 8 hosts, 4 chips/host, 6 ICI links/chip (3D torus).
NUM_HOSTS = 8
CHIPS_PER_HOST = 4


def make_host(worker_id: int):
    backend = FakeBackend(
        chips=CHIPS_PER_HOST,
        script=FakeChipScript(
            hbm_total_bytes=96 * GIB,
            hbm_used_bytes=(worker_id + 1) * GIB,
            duty_cycle_percent=80.0,
            ici_link_count=6,
            ici_bytes_per_step=1_000_000.0,
        ),
    )
    # One training job spans the whole slice: same pod name on every host
    # (a multi-host JobSet replica), each host's 4 chips allocated to it.
    attr = FakeAttribution(
        [
            simple_allocation(
                "llm-train-0",
                [str(i) for i in range(CHIPS_PER_HOST)],
                namespace="ml",
            )
        ]
    )
    cfg = ExporterConfig(
        port=0,
        host="127.0.0.1",
        interval_s=0.05,
        accelerator="v5p-64",
        slice_name="slice-a",
        node_name=f"host-{worker_id}",
        worker_id=str(worker_id),
    )
    return ExporterApp(cfg, backend=backend, attribution=attr)


@pytest.fixture(scope="module")
def slice_apps():
    apps = [make_host(w) for w in range(NUM_HOSTS)]
    for app in apps:
        app.start()
    yield apps
    for app in apps:
        app.stop()


def scrape_all(apps):
    out = []
    for app in apps:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{app.port}/metrics", timeout=5
        ) as r:
            out.append(r.read().decode())
    return out


class TestSliceAggregation:
    def test_every_host_reports_its_chips(self, slice_apps):
        texts = scrape_all(slice_apps)
        for w, text in enumerate(texts):
            fams = {f.name: f for f in text_string_to_metric_families(text)}
            used = fams["tpu_hbm_used_bytes"].samples
            assert len(used) == CHIPS_PER_HOST
            for s in used:
                assert s.labels["worker_id"] == str(w)
                assert s.labels["host"] == f"host-{w}"
                assert s.labels["slice_name"] == "slice-a"
                assert s.labels["pod"] == "llm-train-0"
                assert s.value == (w + 1) * GIB

    def test_cross_host_rollup_by_labels(self, slice_apps):
        """The aggregation Prometheus would do: sum over the slice label."""
        texts = scrape_all(slice_apps)
        slice_hbm = 0.0
        slice_chips = 0
        per_pod_chips = defaultdict(int)
        for text in texts:
            for fam in text_string_to_metric_families(text):
                if fam.name == "tpu_hbm_used_bytes":
                    for s in fam.samples:
                        assert s.labels["slice_name"] == "slice-a"
                        slice_hbm += s.value
                        slice_chips += 1
                if fam.name == "tpu_pod_chip_count":
                    for s in fam.samples:
                        per_pod_chips[(s.labels["pod"], s.labels["namespace"])] += int(
                            s.value
                        )
        assert slice_chips == NUM_HOSTS * CHIPS_PER_HOST  # 32 chips on v5p-64
        assert slice_hbm == sum((w + 1) * GIB * CHIPS_PER_HOST for w in range(NUM_HOSTS))
        # the slice-wide job owns all 32 chips, summed across hosts by labels
        assert per_pod_chips[("llm-train-0", "ml")] == 32

    def test_ici_series_per_host(self, slice_apps):
        import time

        time.sleep(0.15)  # ≥2 polls so rates exist
        texts = scrape_all(slice_apps)
        for text in texts:
            fams = {f.name: f for f in text_string_to_metric_families(text)}
            counters = fams["tpu_ici_transferred_bytes"].samples
            assert len(counters) == CHIPS_PER_HOST * 6
            links = {s.labels["link"] for s in counters}
            assert links == {"0", "1", "2", "3", "4", "5"}
            rates = fams["tpu_ici_link_bandwidth_bytes_per_second"].samples
            assert len(rates) == CHIPS_PER_HOST * 6
            for s in rates:
                assert s.value >= 0

    def test_worker_ids_unique_across_slice(self, slice_apps):
        texts = scrape_all(slice_apps)
        workers = set()
        for text in texts:
            for fam in text_string_to_metric_families(text):
                if fam.name == "tpu_hbm_used_bytes":
                    workers.update(s.labels["worker_id"] for s in fam.samples)
        assert workers == {str(w) for w in range(NUM_HOSTS)}


# TestAggregatorAtSliceScale lives in test_aggregator_scale.py: its timing
# guards must not share a module with the live slice_apps exporters above —
# the module-scoped fixture keeps 8 collector loops polling at 20 Hz until
# module teardown, and that contention alone can triple the measured round.
