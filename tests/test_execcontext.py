"""Execution-context contracts (analysis/execcontext.py).

Synthetic mini-packages exercise each rule family in isolation
(loop-blocking role propagation + laundering, durability state-write /
fsync-reach / single-mover legs, fork-safety + inventory); the runtime
LoopWitness and the static<->witness cross-check get unit coverage; the
real-tree tests pin the contracts CI actually enforces — the scrape fast
path stays loop-legal, ``_WorkerPool.submit`` launders the blocking set,
cursor movers are sender-thread-only, and the committed fork inventory
is fresh.
"""

import ast
import functools
import json
from pathlib import Path
from types import SimpleNamespace

from tpu_pod_exporter.analysis import execcontext, witness
from tpu_pod_exporter.analysis.concurrency import build_model
from tpu_pod_exporter.analysis.engine import build_context, lint_package
from tpu_pod_exporter.analysis.execcontext import (
    CursorMoverRule,
    LoopAllowance,
    check_durability_ordering,
    check_fork_safety,
    check_loop_blocking,
    cross_check_loop,
    fork_inventory,
    get_exec_model,
)

_REPO_ROOT = str(Path(__file__).resolve().parent.parent)


def _trees(**modules: str) -> dict:
    """{"server": src} -> {"tpu_pod_exporter/server.py": ast}."""
    return {
        f"tpu_pod_exporter/{name.replace('.', '/')}.py": ast.parse(src)
        for name, src in modules.items()
    }


def _ctx(**modules: str):
    """Minimal LintContext stand-in: the exec rules only touch
    ``package_trees`` plus the memo attributes get_model/get_exec_model
    hang on the context."""
    return SimpleNamespace(package_trees=_trees(**modules))


# A synthetic event-loop server: the class/method names are what
# CALLBACK_ROLES keys role seeding on, so callbacks registered through
# call_soon get the tpu-exporter-http role exactly like the real tree.
_LOOP_SRC = """
import threading
import time


class _EventLoopServer:
    def call_soon(self, fn):
        self._pending.append(fn)

    def call_later(self, delay, fn):
        self._timers.append((delay, fn))
"""


class TestLoopBlocking:
    def test_inline_sleep_on_loop_flagged(self):
        diags = check_loop_blocking(_ctx(server=_LOOP_SRC + """

def _cb():
    time.sleep(0.5)


def _register(loop):
    loop.call_soon(_cb)
"""))
        assert len(diags) == 1
        assert diags[0].rule == "loop-blocking"
        assert "time.sleep" in diags[0].message
        assert "_cb" in diags[0].message

    def test_transitive_blocking_through_helper_flagged(self):
        # The helper is not registered anywhere — but the role fixpoint
        # tags it through the call edge, so its direct open() is caught.
        diags = check_loop_blocking(_ctx(server=_LOOP_SRC + """

def _helper(path):
    with open(path) as f:
        return f.read()


def _cb():
    return _helper('/etc/hostname')


def _register(loop):
    loop.call_soon(_cb)
"""))
        assert any("_helper" in d.message and "open()" in d.message
                   for d in diags)

    def test_clean_callback_not_flagged(self):
        diags = check_loop_blocking(_ctx(server=_LOOP_SRC + """

def _cb():
    return 1 + 1


def _register(loop):
    loop.call_soon(_cb)
"""))
        assert diags == []

    def test_worker_pool_submit_launders(self):
        # The closure handed to pool.submit runs on a worker, not the
        # loop — its blocking work must NOT be a loop finding.
        diags = check_loop_blocking(_ctx(server=_LOOP_SRC + """

def _cb(pool):
    def run():
        time.sleep(1.0)
    pool.submit(run)


def _register(loop, pool):
    loop.call_soon(_cb)
"""))
        assert diags == []

    def test_lock_with_blocking_holder_flagged(self):
        # The loop only increments under the lock, but another thread
        # holds the same lock across file I/O — acquiring it on the loop
        # can park the loop for that I/O.
        diags = check_loop_blocking(_ctx(server=_LOOP_SRC + """

_lock = threading.Lock()


def _cb():
    with _lock:
        pass


def _register(loop):
    loop.call_soon(_cb)


def _writer_main():
    with _lock:
        with open('/tmp/x', 'w') as f:
            f.write('x')


def _start():
    threading.Thread(target=_writer_main, name='tpu-writer',
                     daemon=True).start()
"""))
        assert any("server._lock" in d.message
                   and "_writer_main" in d.message for d in diags)

    def test_lock_without_blocking_holder_clean(self):
        diags = check_loop_blocking(_ctx(server=_LOOP_SRC + """

_lock = threading.Lock()


def _cb():
    with _lock:
        pass


def _register(loop):
    loop.call_soon(_cb)


def _writer_main():
    with _lock:
        pass


def _start():
    threading.Thread(target=_writer_main, name='tpu-writer',
                     daemon=True).start()
"""))
        assert diags == []

    def test_allowance_exempts_and_rots(self, monkeypatch):
        src = _LOOP_SRC + """

def _cb():
    time.sleep(0.5)


def _register(loop):
    loop.call_soon(_cb)
"""
        monkeypatch.setattr(execcontext, "LOOP_ALLOWED", (
            LoopAllowance("server._cb", "test exemption"),))
        assert check_loop_blocking(_ctx(server=src)) == []
        # A stale allowance (no such function) is itself a finding.
        monkeypatch.setattr(execcontext, "LOOP_ALLOWED", (
            LoopAllowance("server._gone", "rotted"),))
        diags = check_loop_blocking(_ctx(server=src))
        assert any("LOOP_ALLOWED" in d.message and "_gone" in d.message
                   for d in diags)


class TestDurabilityOrdering:
    def test_raw_open_on_state_path_flagged(self, monkeypatch):
        monkeypatch.setattr(execcontext, "CURSOR_MOVERS", ())
        diags = check_durability_ordering(_ctx(a="""
def bad(root):
    with open(root + '/cursor.json', 'w') as f:
        f.write('{}')
"""))
        assert len(diags) == 1
        assert "atomic_write" in diags[0].message

    def test_read_open_and_non_state_path_clean(self, monkeypatch):
        monkeypatch.setattr(execcontext, "CURSOR_MOVERS", ())
        assert check_durability_ordering(_ctx(a="""
def ok(root):
    with open(root + '/cursor.json') as f:
        data = f.read()
    with open(root + '/notes.txt', 'w') as f:
        f.write(data)
""")) == []

    def test_named_constant_resolved_cross_module(self, monkeypatch):
        # The basename literal lives in module a; module b writes
        # through the imported name — still a finding.
        monkeypatch.setattr(execcontext, "CURSOR_MOVERS", ())
        diags = check_durability_ordering(_ctx(
            a="STATUS_NAME = 'egress-status.json'\n",
            b="""
import os

from tpu_pod_exporter.a import STATUS_NAME


def bad(root):
    with open(os.path.join(root, STATUS_NAME), 'w') as f:
        f.write('{}')
"""))
        assert len(diags) == 1
        assert "b.bad" in diags[0].message

    def test_mover_without_fsync_reach_flagged(self, monkeypatch):
        monkeypatch.setattr(execcontext, "CURSOR_MOVERS", ())
        diags = check_durability_ordering(_ctx(a="""
class Buf:
    CURSOR_NAME = 'cursor.json'

    def ack(self):
        self._pos += 1
"""))
        assert any("a.Buf.ack" in d.message
                   and "fsync-reachable" in d.message for d in diags)

    def test_mover_reaching_atomic_write_clean(self, monkeypatch):
        monkeypatch.setattr(execcontext, "CURSOR_MOVERS", ())
        assert check_durability_ordering(_ctx(a="""
import json
import os


def atomic_write(path, data):
    with open(path + '.tmp', 'wb') as f:
        f.write(data)
        os.fsync(f.fileno())
    os.replace(path + '.tmp', path)


class Buf:
    CURSOR_NAME = 'cursor.json'

    def ack(self):
        self._advance(1)

    def _advance(self, n):
        self._pos += n
        atomic_write(self._cursor, json.dumps({'pos': self._pos}).encode())
""")) == []

    def test_undeclared_buffer_flagged(self, monkeypatch):
        monkeypatch.setattr(execcontext, "CURSOR_MOVERS", ())
        diags = check_durability_ordering(_ctx(m="""
from tpu_pod_exporter.persist import WalBuffer


class Sub:
    def __init__(self):
        self.buf = WalBuffer('/tmp/x')
"""))
        assert any("m.Sub.buf" in d.message
                   and "no declared mover role" in d.message for d in diags)

    def test_second_mover_thread_flagged(self, monkeypatch):
        monkeypatch.setattr(execcontext, "CURSOR_MOVERS", (
            CursorMoverRule("m.Sub.buf", "tpu-mover-a", "test"),))
        diags = check_durability_ordering(_ctx(m="""
import threading

from tpu_pod_exporter.persist import WalBuffer


class Sub:
    def __init__(self):
        self.buf = WalBuffer('/tmp/x')
        self._ta = threading.Thread(target=self._move_a,
                                    name='tpu-mover-a', daemon=True)
        self._tb = threading.Thread(target=self._move_b,
                                    name='tpu-mover-b', daemon=True)

    def _move_a(self):
        self.buf.ack()

    def _move_b(self):
        self.buf.trim_to_bytes(0)
"""))
        offenders = [d for d in diags if "tpu-mover-b" in d.message]
        assert len(offenders) == 1
        assert "tpu-mover-a" in offenders[0].message  # names the owner
        assert not any("tpu-mover-a'," in d.message for d in diags
                       if d not in offenders)

    def test_declaration_rot_flagged_demo_exempt(self, monkeypatch):
        monkeypatch.setattr(execcontext, "CURSOR_MOVERS", (
            CursorMoverRule("m.Gone.buf", "tpu-x", "stale"),))
        diags = check_durability_ordering(_ctx(m="x = 1\n"))
        assert any("m.Gone.buf" in d.message and "rotted" in d.message
                   for d in diags)
        monkeypatch.setattr(execcontext, "CURSOR_MOVERS", (
            CursorMoverRule("m.Gone.buf", "tpu-x", "seed", demo=True),))
        assert check_durability_ordering(_ctx(m="x = 1\n")) == []


class TestForkSafety:
    def test_os_fork_flagged(self):
        diags = check_fork_safety(_ctx(a="""
import os


def f():
    os.fork()
"""))
        assert len(diags) == 1
        assert "os.fork" in diags[0].message

    def test_multiprocessing_flagged(self):
        diags = check_fork_safety(_ctx(a="""
import multiprocessing


def f():
    return multiprocessing.Process(target=print)
"""))
        assert any("multiprocessing.Process" in d.message for d in diags)

    def test_import_time_thread_and_fd_flagged(self):
        diags = check_fork_safety(_ctx(a="""
import socket
import threading

_t = threading.Thread(target=print, name='tpu-x', daemon=True)
_s = socket.socket()
"""))
        assert any("thread created at import time" in d.message
                   for d in diags)
        assert any("socket created at import time" in d.message
                   for d in diags)

    def test_function_scoped_creation_clean(self):
        assert check_fork_safety(_ctx(a="""
import socket
import threading


def start():
    t = threading.Thread(target=print, name='tpu-x', daemon=True)
    s = socket.socket()
    return t, s
""")) == []

    def test_inventory_shape_and_retention(self):
        m = build_model(_trees(a="""
import mmap
import socket
import threading

_lock = threading.Lock()


class S:
    def __init__(self):
        self._sock = socket.socket()
        self._r, self._w = socket.socketpair()
        transient = socket.socket()
        transient.close()

    def start(self):
        self._t = threading.Thread(target=self._run, name='tpu-s',
                                   daemon=True)
        self._t.start()

    def _run(self):
        pass
"""))
        inv = fork_inventory(m)
        assert [t["role"] for t in inv["threads"]] == ["tpu-s"]
        assert inv["threads"][0]["entry"] == "a.S._run"
        assert [lk["key"] for lk in inv["locks"]] == ["a._lock"]
        by_retained = {k["retained_as"]: k for k in inv["kernel_objects"]}
        assert by_retained["self._sock"]["kind"] == "socket"
        assert by_retained["self._r, self._w"]["kind"] == "socketpair"
        assert "<transient>" in by_retained
        # Stable identities only — no line numbers anywhere.
        assert all("line" not in rec
                   for section in ("threads", "locks", "kernel_objects")
                   for rec in inv[section])


class TestLoopWitness:
    def test_install_swaps_and_uninstall_restores_probe(self):
        from tpu_pod_exporter import server
        before = server.LOOP_PROBE
        lw = witness.LoopWitness(stall_ms=100)
        with lw:
            assert server.LOOP_PROBE == lw._observe
        assert server.LOOP_PROBE is before
        # Idempotent uninstall.
        lw.uninstall()
        assert server.LOOP_PROBE is before

    def test_threshold_splits_stalls_from_aggregates(self):
        lw = witness.LoopWitness(stall_ms=50)

        def cb():
            pass

        lw._observe("pending", cb, 0.010)   # 10 ms: aggregate only
        lw._observe("pending", cb, 0.200)   # 200 ms: stall
        doc = lw.report()
        assert doc["meta"]["callbacks"] == 1
        [rec] = doc["callbacks"]
        assert rec["count"] == 2
        assert rec["max_ms"] == 200.0
        assert rec["kinds"] == ["pending"]
        [stall] = doc["stalls"]
        assert stall["ms"] == 200.0
        assert stall["qualname"].endswith("cb")

    def test_identity_unwraps_partials_and_bound_methods(self):
        lw = witness.LoopWitness(stall_ms=1000)

        class C:
            def m(self):
                pass

        bound = C().m
        lw._observe("timer", functools.partial(bound), 0.001)
        [(module, qualname, line)] = list(lw.callbacks)
        assert qualname.endswith("C.m")
        assert line == C.m.__code__.co_firstlineno
        assert module == __name__

    def test_dump_round_trips_through_loader(self, tmp_path):
        lw = witness.LoopWitness(stall_ms=10)
        lw._observe("read", len, 0.5)
        out = tmp_path / "loop-witness.json"
        lw.dump(str(out))
        doc = witness.load_dump(str(out))
        assert doc["meta"]["kind"] == "loop-witness"
        assert doc["meta"]["stalls"] == 1

    def test_real_dispatch_is_timed_through_probe(self):
        # End to end through the real server seam: _invoke must route
        # every callback through LOOP_PROBE while installed.
        from tpu_pod_exporter import server
        loop = server._EventLoopServer.__new__(server._EventLoopServer)
        ran = []
        with witness.LoopWitness(stall_ms=1000) as lw:
            server._EventLoopServer._invoke(
                loop, "pending", lambda: ran.append(1))
        assert ran == [1]
        assert len(lw.callbacks) == 1


class TestCrossCheckLoop:
    def _loop_model(self):
        return build_model(_trees(server=_LOOP_SRC + """

def _cb():
    return 1


def _register(loop):
    loop.call_soon(_cb)
"""))

    def test_clean_dump_passes(self):
        m = self._loop_model()
        dump = {"meta": {}, "stalls": [], "callbacks": [{
            "module": "tpu_pod_exporter.server", "qualname": "_cb",
            "line": 1, "count": 3,
        }]}
        assert cross_check_loop(m, dump) == []

    def test_stall_is_a_problem(self):
        problems = cross_check_loop(self._loop_model(), {
            "meta": {"threshold_ms": 500}, "callbacks": [],
            "stalls": [{"qualname": "_cb", "kind": "timer", "ms": 900}],
        })
        assert len(problems) == 1
        assert "stall" in problems[0]

    def test_unknown_callback_is_model_rot(self):
        problems = cross_check_loop(self._loop_model(), {
            "meta": {}, "stalls": [], "callbacks": [{
                "module": "tpu_pod_exporter.server",
                "qualname": "_ghost", "line": 1,
            }]})
        assert len(problems) == 1
        assert "no static identity" in problems[0]

    def test_unroled_callback_is_propagation_rot(self):
        # _orphan exists in the tree but nothing loop-registers it.
        m = build_model(_trees(server=_LOOP_SRC + """

def _orphan():
    return 1
"""))
        problems = cross_check_loop(m, {
            "meta": {}, "stalls": [], "callbacks": [{
                "module": "tpu_pod_exporter.server",
                "qualname": "_orphan", "line": 1,
            }]})
        assert len(problems) == 1
        assert "not loop-role-tagged" in problems[0]

    def test_out_of_package_callbacks_skipped(self):
        assert cross_check_loop(self._loop_model(), {
            "meta": {}, "stalls": [], "callbacks": [
                {"module": "selectors", "qualname": "x", "line": 1},
                {"module": "tests.test_server", "qualname": "y", "line": 1},
            ]}) == []

    def test_runtime_qualname_mapping(self):
        fn = execcontext._static_qualname
        assert fn("tpu_pod_exporter.server",
                  "A.f.<locals>.g.<locals>.<lambda>", 42) \
            == "server.A.f.<g>.<lambda@42>"
        assert fn("tpu_pod_exporter", "top", 1) == "top"
        assert fn("othermod", "x", 1) is None


class TestRealTree:
    def test_real_tree_clean_under_exec_families(self):
        findings = [
            d for d in lint_package(_REPO_ROOT)
            if d.rule in ("loop-blocking", "durability-ordering",
                          "fork-safety")
        ]
        assert findings == []

    def test_scrape_fast_path_is_inspected_and_loop_legal(self):
        ctx = build_context(_REPO_ROOT)
        em = get_exec_model(ctx)
        # The inline fast path IS under the loop role (so the rule covers
        # it) — and it survives the rule (previous test): cached bytes
        # only, encode/gzip happen off-loop.
        assert "server._EventLoopServer._metrics_response" in em.loop_funcs
        assert "server._EventLoopServer._try_write" in em.loop_funcs

    def test_worker_pool_submit_launders_real_defer(self):
        ctx = build_context(_REPO_ROOT)
        em = get_exec_model(ctx)
        m = em.model
        # The deferred closure runs on a worker, never the loop...
        run = "server._EventLoopServer._defer.<run>"
        assert run not in em.loop_funcs
        assert any("worker" in role for role in m.roles.get(run, {}))
        # ...while its completion callback posts BACK to the loop.
        assert f"{run}.<fail>" in em.loop_funcs

    def test_cursor_movers_are_sender_thread_only(self):
        ctx = build_context(_REPO_ROOT)
        em = get_exec_model(ctx)
        assert set(em.buffers) == {
            "egress.RemoteWriteShipper.buffer",
            "alerting.AlertNotifier.buffer",
            "store.FleetStore.*",
        }
        declared = {r.buffer: r.role for r in execcontext.CURSOR_MOVERS}
        for ident, sites in em.mover_sites.items():
            for fq, _line, _path, roles in sites:
                for role in roles:
                    assert role == declared[ident], (ident, fq, role)

    def test_committed_fork_inventory_matches_model(self):
        ctx = build_context(_REPO_ROOT)
        em = get_exec_model(ctx)
        committed = json.loads(
            (Path(_REPO_ROOT) / "deploy" / "fork-inventory.json")
            .read_text())
        assert committed == fork_inventory(em.model), (
            "deploy/fork-inventory.json is stale — run `make "
            "fork-inventory` and review the pre-fork surface change")

    def test_loop_witness_dump_cross_checks_against_real_model(self):
        # Drive the real dispatch seam once and cross-check the witness's
        # record against the real tree's static model — the same join CI
        # performs on the full tier-1 replay.
        import socket

        from tpu_pod_exporter import server
        ctx = build_context(_REPO_ROOT)
        em = get_exec_model(ctx)
        loop = server._EventLoopServer.__new__(server._EventLoopServer)
        r, w = socket.socketpair()
        r.setblocking(False)
        loop._wake_r = r
        try:
            with witness.LoopWitness(stall_ms=10_000) as lw:
                loop._invoke("wake", loop._drain_wake)
        finally:
            r.close()
            w.close()
        problems = cross_check_loop(em.model, lw.report())
        assert problems == []
