"""End-to-end poll tracing (tpu_pod_exporter.trace).

Covers the PR's acceptance criteria directly:

- a chaos-injected wedge produces a trace whose device span is
  ``abandoned`` with attached profiler stacks naming the hung frame;
- the aggregator's round trace links to the node-side scrape span via the
  propagated ``traceparent`` context;
- the slow-poll sampler attaches collapsed stacks and STOPS when the poll
  ends;
- ``/debug/trace`` output validates against the Chrome trace_event shape,
  is size-bounded, and is gated by the loopback-only /debug/* policy;
- JSON log lines and RateLimitedLogger suppression tallies carry trace ids.
"""

import json
import logging
import time
import urllib.request

import pytest

from tpu_pod_exporter import trace as trace_mod
from tpu_pod_exporter.attribution.fake import FakeAttribution
from tpu_pod_exporter.backend.fake import FakeBackend
from tpu_pod_exporter.collector import Collector
from tpu_pod_exporter.metrics import SnapshotStore
from tpu_pod_exporter.trace import (
    StackSampler,
    Tracer,
    TraceStore,
    format_traceparent,
    parse_traceparent,
    render_trace,
    to_chrome_trace,
)


def get(url, headers=None):
    req = urllib.request.Request(url, headers=headers or {})
    try:
        resp = urllib.request.urlopen(req, timeout=5)
        return resp.status, dict(resp.headers), resp.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


def wait_for(predicate, timeout_s=5.0):
    """Poll until the predicate returns truthy. The node-side scrape span
    is recorded by the handler thread AFTER the response body is on the
    wire, so a client that just read the body can observe it a beat later."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        out = predicate()
        if out:
            return out
        time.sleep(0.01)
    return predicate()


def traced_collector(chips=2, slow_poll_s=30.0, sampler=None, **kw):
    store = TraceStore()
    tracer = Tracer(store, slow_poll_s=slow_poll_s, sampler=sampler)
    collector = Collector(
        FakeBackend(chips=chips), FakeAttribution(), SnapshotStore(),
        tracer=tracer, **kw,
    )
    return collector, tracer, store


def validate_chrome_trace(doc):
    """The subset of the trace_event contract chrome://tracing/Perfetto
    require: every event is a complete ("X") event with name/ts/dur/pid/tid,
    and the whole document JSON-serializes cleanly."""
    json.dumps(doc)  # strict-parser safe (no NaN, no cycles)
    assert "traceEvents" in doc
    for ev in doc["traceEvents"]:
        for key in ("name", "ph", "ts", "dur", "pid", "tid"):
            assert key in ev, f"event missing {key}: {ev}"
        assert ev["ph"] == "X"
        assert isinstance(ev["ts"], (int, float))
        assert ev["dur"] >= 0
        assert ev["args"]["trace_id"]


class TestTraceparent:
    def test_round_trip(self):
        tid, sid = trace_mod.new_trace_id(), trace_mod.new_span_id()
        assert parse_traceparent(format_traceparent(tid, sid)) == (tid, sid)

    @pytest.mark.parametrize("bad", [
        "", "garbage", "00-short-short-01",
        "00-" + "0" * 32 + "-" + "1" * 16 + "-01",   # all-zero trace id
        "00-" + "1" * 32 + "-" + "0" * 16 + "-01",   # all-zero span id
        "00-" + "x" * 32 + "-" + "1" * 16 + "-01",   # non-hex
        "00-" + "1" * 31 + "-" + "1" * 16 + "-01",   # short trace id
        # int(s, 16) would happily parse all of these (signs, underscores,
        # whitespace) — strict hex must not:
        "00-+" + "a" * 31 + "-" + "b" * 16 + "-01",
        "00-" + "a" * 32 + "-+" + "b" * 15 + "-01",
        "00-" + "a_b" + "a" * 29 + "-" + "b" * 16 + "-01",
        "00- " + "a" * 30 + " -" + "b" * 16 + "-01",
    ])
    def test_malformed_rejected(self, bad):
        assert parse_traceparent(bad) is None

    def test_unknown_version_and_extra_fields_parse(self):
        tid, sid = "a" * 32, "b" * 16
        assert parse_traceparent(f"cc-{tid}-{sid}-01-extra") == (tid, sid)


class TestPollSpans:
    def test_every_phase_becomes_a_span(self):
        collector, tracer, store = traced_collector()
        stats = collector.poll_once()
        t = store.last(1)[0]
        names = [s.name for s in t.spans]
        assert names[0] == "poll"
        for phase in ("device_read", "attribution", "join", "publish"):
            assert phase in names
        assert stats.trace_id == t.trace_id
        root = t.root
        assert root.dur_s is not None and root.status == "ok"
        dev = next(s for s in t.spans if s.name == "device_read")
        assert dev.status == "ok"
        assert dev.attrs["chips"] == 2
        assert dev.parent_id == root.span_id
        pub = next(s for s in t.spans if s.name == "publish")
        assert pub.attrs["series"] > 0
        tracer.close()

    def test_untraced_collector_records_nothing(self):
        collector = Collector(FakeBackend(chips=1), FakeAttribution(),
                              SnapshotStore())
        stats = collector.poll_once()
        assert stats.trace_id == ""
        assert trace_mod.current_ids() == (None, None)

    def test_tls_context_cleared_after_poll(self):
        collector, tracer, _ = traced_collector()
        collector.poll_once()
        assert trace_mod.current_ids() == (None, None)
        tracer.close()

    def test_device_error_marks_span_err(self):
        collector, tracer, store = traced_collector()
        collector._backend.fail_next(1)
        collector.poll_once()
        dev = next(s for s in store.last(1)[0].spans
                   if s.name == "device_read")
        assert dev.status == "err"
        tracer.close()

    def test_trace_metrics_published(self):
        snap_store = SnapshotStore()
        store = TraceStore()
        tracer = Tracer(store, slow_poll_s=30.0)
        collector = Collector(FakeBackend(chips=1), FakeAttribution(),
                              snap_store, tracer=tracer)
        collector.poll_once()
        collector.poll_once()
        snap = snap_store.current()
        # One poll behind: the second snapshot sees the first poll's trace.
        assert snap.value("tpu_exporter_traces", ()) >= 1.0
        assert snap.value("tpu_exporter_trace_spans", ()) >= 5.0
        assert snap.value("tpu_exporter_slow_polls_total", ()) == 0.0
        tracer.close()


class TestTraceStore:
    def test_bounded_ring_evicts_oldest(self):
        store = TraceStore(max_traces=2)
        tracer = Tracer(store, slow_poll_s=0)
        ids = []
        for _ in range(3):
            t = tracer.start_poll()
            ids.append(t.trace_id)
            tracer.finish(t)
        st = store.stats()
        assert st["traces"] == 2 and st["traces_total"] == 3
        kept = [t.trace_id for t in store.last(10)]
        assert kept == ids[1:]
        # span accounting survives eviction (1 root span per trace here)
        assert st["spans"] == 2

    def test_scrape_span_ring(self):
        store = TraceStore(max_scrape_spans=4)
        for i in range(6):
            store.record_scrape("a" * 32, "b" * 16, 0.0, 0.001, client=str(i))
        scrapes = store.scrapes(100)
        assert len(scrapes) == 4
        assert store.stats()["scrape_spans_total"] == 6
        assert scrapes[-1].attrs["client"] == "5"

    def test_scrape_record_rate_cap(self):
        # The recording is driven by a client-supplied header on the
        # unauthenticated /metrics path: a forged-traceparent storm must
        # not churn genuine aggregator join spans out of the ring.
        class Clock:
            t = 0.0

            def __call__(self):
                return self.t

        clock = Clock()
        store = TraceStore(clock=clock)
        cap = TraceStore.SCRAPE_RECORDS_PER_WINDOW
        for _ in range(cap):
            assert store.record_scrape("a" * 32, "b" * 16, 0.0, 0.001)
        assert store.record_scrape("a" * 32, "b" * 16, 0.0, 0.001) is None
        st = store.stats()
        assert st["scrape_spans_total"] == cap
        assert st["scrape_spans_dropped"] == 1
        clock.t = TraceStore.SCRAPE_RECORD_WINDOW_S + 0.1
        assert store.record_scrape("a" * 32, "b" * 16, 0.0, 0.001)


class TestSlowPollProfiler:
    def _slow_backend(self, delay_s):
        inner = FakeBackend(chips=1)

        class Slow:
            name = "slow"

            def sample(self):
                time.sleep(delay_s)
                return inner.sample()

            def close(self):
                pass

        return Slow()

    def test_attaches_collapsed_stacks_and_stops_at_poll_end(self):
        store = TraceStore()
        sampler = StackSampler(hz=200.0)
        tracer = Tracer(store, slow_poll_s=0.05, sampler=sampler)
        collector = Collector(self._slow_backend(0.25), FakeAttribution(),
                              SnapshotStore(), tracer=tracer)
        collector.poll_once()
        t = store.last(1)[0]
        assert t.slow
        assert t.profile, "no stacks attached to the slow poll"
        assert t.profile_samples > 0
        # The poll thread was inside the backend's sample() sleep: the
        # collapsed stack must name the frame.
        all_stacks = [st for stacks in t.profile.values() for st in stacks]
        assert any("sample" in st for st in all_stacks), all_stacks
        # Sampler must stop once the poll ends: no further mutation.
        n = t.profile_samples
        assert not sampler.armed
        time.sleep(0.1)
        assert t.profile_samples == n
        tracer.close()

    def test_fast_poll_not_profiled(self):
        store = TraceStore()
        sampler = StackSampler(hz=200.0)
        tracer = Tracer(store, slow_poll_s=5.0, sampler=sampler)
        collector = Collector(FakeBackend(chips=1), FakeAttribution(),
                              SnapshotStore(), tracer=tracer)
        collector.poll_once()
        t = store.last(1)[0]
        assert not t.slow and t.profile is None
        assert store.stats()["slow_polls"] == 0
        tracer.close()

    def test_sample_cap_disarms(self):
        store = TraceStore()
        sampler = StackSampler(hz=1000.0, max_samples=3)
        tracer = Tracer(store, slow_poll_s=0.01, sampler=sampler)
        collector = Collector(self._slow_backend(0.2), FakeAttribution(),
                              SnapshotStore(), tracer=tracer)
        collector.poll_once()
        t = store.last(1)[0]
        assert t.profile_samples <= 3
        tracer.close()

    def test_render_trace_includes_profile(self):
        store = TraceStore()
        tracer = Tracer(store, slow_poll_s=0.02, sampler=StackSampler(hz=200))
        collector = Collector(self._slow_backend(0.1), FakeAttribution(),
                              SnapshotStore(), tracer=tracer)
        collector.poll_once()
        text = render_trace(store.last(1)[0])
        assert "[SLOW]" in text and "profile:" in text
        assert "device_read" in text
        tracer.close()


class TestWedgeAcceptance:
    """ISSUE acceptance: a chaos-injected device wedge produces a trace in
    which the device span is ``abandoned`` with profiler stacks naming the
    hung frame (the supervised worker is parked inside the chaos sleep, so
    the ``tpu-sup-device-*`` stack must name chaos._invoke)."""

    def test_wedged_device_trace(self):
        from tpu_pod_exporter.app import ExporterApp
        from tpu_pod_exporter.config import ExporterConfig

        cfg = ExporterConfig(
            port=0, host="127.0.0.1", interval_s=0.1,
            backend="fake", fake_chips=2, attribution="none",
            phase_deadline_s=0.3, breaker_failures=2,
            chaos_spec="hang:device:1:5s:x1", chaos_seed=1,
            history_retention_s=0.0, trace_slow_poll_s=0.05,
        )
        app = ExporterApp(cfg)
        app.start()  # first poll is synchronous: it IS the wedged poll
        try:
            wedged = next(
                t for t in app.trace.last(50)
                for s in t.spans
                if s.name == "device_read" and s.status == "abandoned"
            )
            dev = next(s for s in wedged.spans if s.name == "device_read")
            events = " | ".join(m for _dt, m in dev.events or ())
            assert "chaos: injected hang" in events
            assert "deadline" in events and "fenced" in events
            assert wedged.slow and wedged.profile
            worker_stacks = [
                st
                for label, stacks in wedged.profile.items()
                if label.startswith("tpu-sup-device")
                for st in stacks
            ]
            assert worker_stacks, f"no worker stacks in {wedged.profile}"
            assert any("chaos._invoke" in st for st in worker_stacks), (
                worker_stacks
            )
            # /debug/vars carries the join key for the last poll.
            _, _, body = get(f"http://127.0.0.1:{app.port}/debug/vars")
            assert json.loads(body)["last_poll"]["trace_id"]
        finally:
            app.stop()


class TestTraceparentJoin:
    """ISSUE acceptance: the aggregator's round trace links to the node
    scrape span via the propagated trace context."""

    def test_round_trace_joins_node_scrape_span(self):
        from tpu_pod_exporter.aggregate import SliceAggregator
        from tpu_pod_exporter.app import ExporterApp
        from tpu_pod_exporter.config import ExporterConfig

        cfg = ExporterConfig(port=0, host="127.0.0.1", backend="fake",
                             fake_chips=2, attribution="none",
                             history_retention_s=0.0)
        app = ExporterApp(cfg)
        app.start()
        agg = None
        try:
            ts = TraceStore()
            tracer = Tracer(ts, slow_poll_s=0.0, root_name="round")
            agg = SliceAggregator((f"127.0.0.1:{app.port}",), SnapshotStore(),
                                  tracer=tracer)
            agg.poll_once()
            rt = ts.last(1)[0]
            assert rt.root.name == "round"
            scrape = next(s for s in rt.spans if s.name == "scrape")
            assert scrape.status == "ok"
            assert scrape.attrs["bytes"] > 0
            match = wait_for(lambda: [
                s for s in app.trace.scrapes(10)
                if s.trace_id == rt.trace_id
                and s.parent_id == scrape.span_id
            ])
            assert match, (
                f"node recorded no scrape span under the round trace "
                f"(have {[(s.trace_id, s.parent_id) for s in app.trace.scrapes(10)]})"
            )
            assert match[0].dur_s > 0
        finally:
            if agg is not None:
                agg.close()
            app.stop()

    def test_injected_two_arg_fetch_still_works(self):
        # Tests and ReplayFetch inject (target, timeout_s) fetches; the
        # tracer must not force a signature change on them.
        from tpu_pod_exporter.aggregate import SliceAggregator

        seen = {}

        def fetch(target, timeout_s):
            seen["target"] = target
            return 'tpu_chip_info{chip_id="0",host="h"} 1\n'

        ts = TraceStore()
        agg = SliceAggregator(("h0:8000",), SnapshotStore(), fetch=fetch,
                              tracer=Tracer(ts, slow_poll_s=0,
                                            root_name="round"))
        try:
            agg.poll_once()
        finally:
            agg.close()
        assert seen["target"] == "h0:8000"
        scrape = next(s for s in ts.last(1)[0].spans if s.name == "scrape")
        assert scrape.status == "ok"

    def test_default_fetch_sends_traceparent_header(self):
        from tpu_pod_exporter.app import ExporterApp
        from tpu_pod_exporter.aggregate import default_fetch
        from tpu_pod_exporter.config import ExporterConfig

        cfg = ExporterConfig(port=0, host="127.0.0.1", backend="fake",
                             fake_chips=1, attribution="none",
                             history_retention_s=0.0)
        app = ExporterApp(cfg)
        app.start()
        try:
            tid, sid = "c" * 32, "d" * 16
            default_fetch(f"127.0.0.1:{app.port}", 5.0,
                          traceparent=format_traceparent(tid, sid))
            assert wait_for(lambda: [
                s for s in app.trace.scrapes(10)
                if s.trace_id == tid and s.parent_id == sid
            ])
            # A plain scrape (no header) records nothing new.
            n = len(app.trace.scrapes(100))
            default_fetch(f"127.0.0.1:{app.port}", 5.0)
            time.sleep(0.05)  # give the handler thread its post-write beat
            assert len(app.trace.scrapes(100)) == n
        finally:
            app.stop()


class TestDebugTraceEndpoint:
    @pytest.fixture
    def served(self):
        from tpu_pod_exporter.server import MetricsServer

        collector, tracer, tstore = traced_collector()
        for _ in range(5):
            collector.poll_once()
        store = SnapshotStore()
        server = MetricsServer(store, host="127.0.0.1", port=0, trace=tstore)
        server.start()
        yield tstore, f"http://127.0.0.1:{server.port}"
        server.stop()
        tracer.close()

    def test_valid_chrome_trace_event_json(self, served):
        _, base = served
        status, headers, body = get(base + "/debug/trace")
        assert status == 200
        assert headers["Content-Type"] == "application/json"
        doc = json.loads(body)
        validate_chrome_trace(doc)
        names = {ev["name"] for ev in doc["traceEvents"]}
        assert {"poll", "device_read", "publish"} <= names

    def test_size_bounded(self, served):
        _, base = served
        one = json.loads(get(base + "/debug/trace?last=1")[2])
        all_ = json.loads(get(base + "/debug/trace?last=9999")[2])
        assert len(one["traceEvents"]) < len(all_["traceEvents"])
        # 5 traces x ~5 spans: the clamped "everything" ask stays small.
        assert len(all_["traceEvents"]) <= 5 * 8

    @pytest.mark.parametrize("q", ["last=0", "last=-3", "last=abc"])
    def test_bad_last_is_400(self, served, q):
        _, base = served
        status, _, body = get(base + f"/debug/trace?{q}")
        assert status == 400
        assert json.loads(body)["status"] == "error"

    def test_gated_by_debug_loopback_policy(self, served, monkeypatch):
        # The satellite contract: off-loopback clients get 403 by default.
        # The policy function itself is covered in test_history
        # (TestDebugLoopbackPolicy); here we assert /debug/trace routes
        # through it by forcing the policy to deny.
        import tpu_pod_exporter.server as server_mod

        _, base = served
        monkeypatch.setattr(server_mod, "debug_client_allowed",
                            lambda ip, addr: False)
        status, _, body = get(base + "/debug/trace")
        assert status == 403
        assert b"loopback-only" in body

    def test_404_when_tracing_disabled(self):
        from tpu_pod_exporter.server import MetricsServer

        server = MetricsServer(SnapshotStore(), host="127.0.0.1", port=0)
        server.start()
        try:
            status, _, body = get(
                f"http://127.0.0.1:{server.port}/debug/trace"
            )
            assert status == 404
            assert b"tracing disabled" in body
        finally:
            server.stop()

    def test_trace_off_app_has_no_trace_surface(self):
        from tpu_pod_exporter.app import ExporterApp
        from tpu_pod_exporter.config import ExporterConfig

        cfg = ExporterConfig(port=0, host="127.0.0.1", backend="fake",
                             fake_chips=1, attribution="none",
                             history_retention_s=0.0, trace=False)
        app = ExporterApp(cfg)
        assert app.trace is None and app.tracer is None
        app.start()
        try:
            assert get(f"http://127.0.0.1:{app.port}/debug/trace")[0] == 404
            assert app.collector.last_stats.trace_id == ""
        finally:
            app.stop()


class TestSupervisorContextPropagation:
    def test_worker_annotations_land_on_phase_span(self):
        from tpu_pod_exporter.supervisor import SourceSupervisor

        store = TraceStore()
        tracer = Tracer(store, slow_poll_s=0)

        def fn():
            trace_mod.annotate("from the worker thread")
            return 42

        sup = SourceSupervisor("device", fn, deadline_s=2.0)
        t = tracer.start_poll()
        t.begin("device_read")
        try:
            assert sup.call() == 42
            t.end("ok")
        finally:
            tracer.finish(t)
            sup.shutdown()
        dev = next(s for s in t.spans if s.name == "device_read")
        assert any("from the worker thread" in m for _dt, m in dev.events)

    def test_worker_tls_restored_between_calls(self):
        from tpu_pod_exporter.supervisor import SourceSupervisor

        seen = []

        def fn():
            seen.append(trace_mod.current_ids()[0])
            return 1

        sup = SourceSupervisor("device", fn, deadline_s=2.0)
        tracer = Tracer(TraceStore(), slow_poll_s=0)
        t = tracer.start_poll()
        t.begin("device_read")
        sup.call()
        t.end("ok")
        tracer.finish(t)
        sup.call()  # outside any trace: worker must see no stale context
        sup.shutdown()
        assert seen[0] == t.trace_id
        assert seen[1] is None


class TestLogCorrelation:
    def _capture(self, logger):
        records = []

        class H(logging.Handler):
            def emit(self, record):
                records.append(record)

        h = H()
        logger.addHandler(h)
        logger.setLevel(logging.DEBUG)
        return records, h

    def test_json_log_lines_carry_trace_ids(self):
        from tpu_pod_exporter.utils import JsonLogFormatter

        fmt = JsonLogFormatter()
        rec = logging.LogRecord("t", logging.WARNING, "f.py", 1, "msg",
                                (), None)
        tracer = Tracer(TraceStore(), slow_poll_s=0)
        t = tracer.start_poll()
        try:
            out = json.loads(fmt.format(rec))
            assert out["trace_id"] == t.trace_id
            assert out["span_id"] == t.root.span_id
        finally:
            tracer.finish(t)
        out = json.loads(fmt.format(rec))
        assert "trace_id" not in out and "span_id" not in out

    def test_suppression_tally_counts_current_trace(self):
        from tpu_pod_exporter.utils import RateLimitedLogger

        logger = logging.getLogger("test_trace.rlog")
        records, handler = self._capture(logger)

        class Clock:
            t = 0.0

            def __call__(self):
                return self.t

        clock = Clock()
        rl = RateLimitedLogger(logger, min_interval_s=30.0, clock=clock)
        tracer = Tracer(TraceStore(), slow_poll_s=0)
        t = tracer.start_poll()
        try:
            rl.warning("k", "boom")         # emits
            rl.warning("k", "boom")         # suppressed (in trace)
            rl.warning("k", "boom")         # suppressed (in trace)
            clock.t = 31.0
            rl.warning("k", "boom")         # emits with per-trace tally
        finally:
            tracer.finish(t)
            logger.removeHandler(handler)
        msgs = [r.getMessage() for r in records]
        assert msgs[0] == "boom"
        assert msgs[1] == (
            f"boom (+2 similar suppressed, 2 in trace {t.trace_id[:8]})"
        )

    def test_suppression_tally_falls_back_to_dominant_trace(self):
        # Production shape: at 1 poll/s the suppression window spans ~30
        # traces and the emission happens inside a FRESH trace — the tally
        # must then name the trace that actually suppressed the most
        # lines, not silently report nothing.
        from tpu_pod_exporter.utils import RateLimitedLogger

        logger = logging.getLogger("test_trace.rlog3")
        records, handler = self._capture(logger)

        class Clock:
            t = 0.0

            def __call__(self):
                return self.t

        clock = Clock()
        rl = RateLimitedLogger(logger, min_interval_s=30.0, clock=clock)
        tracer = Tracer(TraceStore(), slow_poll_s=0)
        t1 = tracer.start_poll()
        try:
            rl.warning("k", "boom")     # emits under trace 1
            rl.warning("k", "boom")     # suppressed under trace 1
            rl.warning("k", "boom")     # suppressed under trace 1
        finally:
            tracer.finish(t1)
        t2 = tracer.start_poll()        # the fresh trace doing the emitting
        try:
            clock.t = 31.0
            rl.warning("k", "boom")
        finally:
            tracer.finish(t2)
            logger.removeHandler(handler)
        assert records[-1].getMessage() == (
            f"boom (+2 similar suppressed, 2 in trace {t1.trace_id[:8]})"
        )

    def test_suppression_tally_unchanged_outside_traces(self):
        from tpu_pod_exporter.utils import RateLimitedLogger

        logger = logging.getLogger("test_trace.rlog2")
        records, handler = self._capture(logger)

        class Clock:
            t = 0.0

            def __call__(self):
                return self.t

        clock = Clock()
        rl = RateLimitedLogger(logger, min_interval_s=30.0, clock=clock)
        try:
            rl.warning("k", "boom")
            rl.warning("k", "boom")
            clock.t = 31.0
            rl.warning("k", "boom")
        finally:
            logger.removeHandler(handler)
        assert [r.getMessage() for r in records] == [
            "boom", "boom (+1 similar suppressed)",
        ]


class TestChromeExport:
    def test_scrape_spans_exported_with_remote_context(self):
        store = TraceStore()
        store.record_scrape("a" * 32, "b" * 16, 1000.0, 0.002, client="10.0.0.9")
        doc = to_chrome_trace([], store.scrapes(10))
        validate_chrome_trace(doc)
        (ev,) = doc["traceEvents"]
        assert ev["name"] == "scrape" and ev["cat"] == "scrape"
        assert ev["args"]["trace_id"] == "a" * 32
        assert ev["args"]["parent_id"] == "b" * 16
        assert ev["args"]["client"] == "10.0.0.9"

    def test_profile_and_events_ride_the_export(self):
        collector, tracer, store = traced_collector()
        t = tracer.start_poll()
        t.begin("device_read")
        trace_mod.annotate("something happened")
        t.end("err")
        tracer.finish(t)
        doc = to_chrome_trace(store.last(1))
        dev = next(e for e in doc["traceEvents"]
                   if e["name"] == "device_read")
        assert dev["args"]["status"] == "err"
        assert dev["args"]["events"][0][1] == "something happened"
        tracer.close()

    def test_span_event_cap(self):
        tracer = Tracer(TraceStore(), slow_poll_s=0)
        t = tracer.start_poll()
        t.begin("device_read")
        for i in range(50):
            trace_mod.annotate(f"e{i}")
        t.end("ok")
        tracer.finish(t)
        dev = next(s for s in t.spans if s.name == "device_read")
        assert len(dev.events) == trace_mod.MAX_SPAN_EVENTS + 1
        assert dev.events[-1][1] == "…more events dropped"


class TestDemoAndOverheadCli:
    def test_trace_demo_replay(self, capsys):
        from tpu_pod_exporter.trace import main

        rc = main(["--replay", "tests/fixtures/real-trace-r5.jsonl"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "trace " in out and "device_read" in out and "publish" in out

    @pytest.mark.slow
    def test_overhead_check_runs(self, capsys):
        from tpu_pod_exporter.trace import main

        # Functional smoke only (tiny run; CI enforces the real budget with
        # a dedicated step): the check must run and report.
        rc = main(["--overhead-check", "--polls", "30", "--chips", "8",
                   "--budget", "5.0"])
        assert rc == 0
        assert "overhead" in capsys.readouterr().out
