"""Record/replay backend tests (SURVEY.md §7: the third backend seam)."""

import json
from pathlib import Path

import pytest

from tpu_pod_exporter.backend import BackendError
from tpu_pod_exporter.backend.fake import FakeBackend, FakeChipScript
from tpu_pod_exporter.backend.recorded import (
    RecordedBackend,
    RecordingBackend,
    sample_from_dict,
    sample_to_dict,
)


class TestRoundtrip:
    def test_sample_dict_roundtrip(self):
        backend = FakeBackend(
            chips=2,
            script=FakeChipScript(
                hbm_total_bytes=1000, hbm_used_bytes=100,
                duty_cycle_percent=50.0, ici_link_count=2, ici_bytes_per_step=10,
            ),
        )
        original = backend.sample()
        restored = sample_from_dict(sample_to_dict(original))
        assert restored == original

    def test_none_duty_preserved(self):
        backend = FakeBackend(chips=1, script=FakeChipScript(duty_cycle_percent=None))
        restored = sample_from_dict(sample_to_dict(backend.sample()))
        assert restored.chips[0].tensorcore_duty_cycle_percent is None

    def test_dcn_links_roundtrip(self):
        # dcn_links was silently dropped by record/replay when added —
        # the full-equality roundtrip above only covers DCN-less samples.
        backend = FakeBackend(
            chips=1,
            script=FakeChipScript(
                ici_link_count=1, ici_bytes_per_step=10,
                dcn_link_count=2, dcn_bytes_per_step=7,
            ),
        )
        original = backend.sample()
        assert original.chips[0].dcn_links  # fixture sanity
        restored = sample_from_dict(sample_to_dict(original))
        assert restored == original

    def test_numeric_link_ids_replay_in_numeric_order(self):
        # The live libtpu backend orders links numerically (_link_sort_key);
        # a lexicographic replay would shuffle ids >= 10 and feed the
        # collector's layout fast path a different sequence than the
        # backend being reproduced (code-review r5).
        doc = {
            "chips": [{
                "chip_id": 0, "hbm_used": 1.0, "hbm_total": 2.0,
                "duty": None,
                "ici": {str(i): float(i) for i in range(12)},
                "dcn": {"10": 1.0, "2": 2.0, "dcnx": 3.0},
            }]
        }
        chip = sample_from_dict(doc).chips[0]
        assert [l.link for l in chip.ici_links] == [str(i) for i in range(12)]
        # Numeric ids first (numerically), non-numeric after.
        assert [l.link for l in chip.dcn_links] == ["2", "10", "dcnx"]

    def test_dcn_key_omitted_without_dcn_links(self):
        # Old replayers must not see an unknown key for DCN-less chips.
        backend = FakeBackend(chips=1)
        doc = sample_to_dict(backend.sample())
        assert "dcn" not in doc["chips"][0]


class TestRecordReplay:
    def test_record_then_replay(self, tmp_path):
        path = str(tmp_path / "trace.jsonl")
        inner = FakeBackend(
            chips=2,
            script=FakeChipScript(hbm_used_bytes=lambda step: float(step * 100)),
        )
        rec = RecordingBackend(inner, path)
        originals = [rec.sample() for _ in range(3)]
        rec.close()
        assert inner.closed

        replay = RecordedBackend(path, loop=True)
        assert len(replay) == 3
        for orig in originals:
            assert replay.sample() == orig
        # loops back to the start
        assert replay.sample() == originals[0]

    def test_hold_last_when_not_looping(self, tmp_path):
        path = str(tmp_path / "t.jsonl")
        rec = RecordingBackend(FakeBackend(chips=1), path)
        rec.sample()
        rec.close()
        replay = RecordedBackend(path, loop=False)
        first = replay.sample()
        assert replay.sample() == first

    def test_empty_recording_raises(self, tmp_path):
        p = tmp_path / "empty.jsonl"
        p.write_text("")
        with pytest.raises(BackendError):
            RecordedBackend(str(p))

    def test_corrupt_line_raises_with_location(self, tmp_path):
        p = tmp_path / "bad.jsonl"
        p.write_text('{"chips": []}\n{broken\n')
        with pytest.raises(BackendError, match=":2"):
            RecordedBackend(str(p))

    def test_missing_file_raises(self, tmp_path):
        with pytest.raises(BackendError):
            RecordedBackend(str(tmp_path / "nope.jsonl"))

    def test_recording_passes_through_errors(self, tmp_path):
        inner = FakeBackend(chips=1)
        inner.fail_next(1)
        rec = RecordingBackend(inner, str(tmp_path / "t.jsonl"))
        with pytest.raises(BackendError):
            rec.sample()
        rec.sample()  # recovers; only good samples recorded
        rec.close()
        lines = (tmp_path / "t.jsonl").read_text().strip().splitlines()
        assert len(lines) == 1


class TestAppIntegration:
    def test_cli_config_wires_recorded_backend(self, tmp_path):
        from tpu_pod_exporter.app import build_backend
        from tpu_pod_exporter.config import ExporterConfig

        path = str(tmp_path / "trace.jsonl")
        rec = RecordingBackend(FakeBackend(chips=2), path)
        rec.sample()
        rec.close()
        cfg = ExporterConfig(backend="recorded", recording_path=path)
        backend = build_backend(cfg)
        assert backend.name == "recorded"
        assert len(backend.sample().chips) == 2

    def test_record_to_wraps_backend(self, tmp_path):
        from tpu_pod_exporter.app import ExporterApp
        from tpu_pod_exporter.attribution.fake import FakeAttribution
        from tpu_pod_exporter.config import ExporterConfig

        path = str(tmp_path / "out.jsonl")
        cfg = ExporterConfig(
            port=0, host="127.0.0.1", interval_s=5.0, record_to=path
        )
        app = ExporterApp(cfg, backend=FakeBackend(chips=1), attribution=FakeAttribution())
        app.start()  # first poll records one sample
        app.stop()
        lines = [json.loads(l) for l in open(path)]
        assert lines and lines[0]["chips"][0]["chip_id"] == 0


class TestRealHardwareFixture:
    """The committed real-TPU trace (round 4, tests/fixtures/real-trace.jsonl
    — 71 polls of the tunneled v5 lite chip) drives the full pipeline in CI:
    the one place real-silicon data exercises collector + registry with zero
    hardware.

    Encoding note: the trace was captured minutes BEFORE the None-able HBM
    fields landed, so its records carry the then-current encoding of "HBM
    unreadable" — hbm 0.0 alongside a 'memory_stats returned None' partial
    error. The raw-replay test asserts that historical encoding verbatim
    (the artifact is evidence, never edited); the normalized test maps it
    to today's encoding and proves the absent-beats-fake-zero pipeline
    against the real capture."""

    FIXTURE = Path(__file__).resolve().parent / "fixtures" / "real-trace.jsonl"

    def test_replays_through_collector(self):
        from tpu_pod_exporter.attribution.fake import FakeAttribution
        from tpu_pod_exporter.backend.recorded import RecordedBackend
        from tpu_pod_exporter.collector import Collector
        from tpu_pod_exporter.metrics import SnapshotStore

        backend = RecordedBackend(str(self.FIXTURE))
        sample = backend.sample()
        (chip,) = sample.chips
        assert chip.info.device_kind == "TPU v5 lite"
        assert chip.info.coords == "0,0,0"
        # Recorded through the tunnel: memory_stats was None every poll.
        assert any("memory_stats" in e for e in sample.partial_errors)

        store = SnapshotStore()
        c = Collector(backend, FakeAttribution(), store)
        c.poll_once()
        snap = store.current()
        text = snap.encode().decode()
        # Real chip identity flows to the exposition...
        assert 'device_kind="TPU v5 lite"' in text
        # ...and the recorded partial error is counted, not hidden.
        assert snap.value(
            "tpu_exporter_poll_errors_total", {"source": "device_partial"}
        ) == 1.0
        # Historical encoding, asserted verbatim (see class docstring):
        # pre-None-fields capture carries hbm 0.0, which replays as 0.0.
        assert chip.hbm_used_bytes == 0.0
        assert "tpu_hbm_used_bytes{" in text

    def test_normalized_replay_proves_absent_hbm_on_real_capture(self, tmp_path):
        """Re-encode the capture the way today's jaxdev would have written
        it (memory_stats None → hbm fields null) and replay: the real
        trace must then drive the absent-beats-fake-zero path end to end."""
        import json as json_mod

        from tpu_pod_exporter.attribution.fake import FakeAttribution
        from tpu_pod_exporter.backend.recorded import RecordedBackend
        from tpu_pod_exporter.collector import Collector
        from tpu_pod_exporter.metrics import SnapshotStore

        normalized = tmp_path / "real-trace-normalized.jsonl"
        with normalized.open("w") as out:
            for line in self.FIXTURE.read_text().splitlines():
                rec = json_mod.loads(line)
                assert any("memory_stats" in e for e in rec["partial_errors"])
                for c in rec["chips"]:
                    assert c["hbm_used"] == 0.0  # the old encoding, every poll
                    c["hbm_used"] = None
                    c["hbm_total"] = None
                out.write(json_mod.dumps(rec) + "\n")

        store = SnapshotStore()
        c = Collector(RecordedBackend(str(normalized)), FakeAttribution(), store)
        c.poll_once()
        text = store.current().encode().decode()
        assert 'device_kind="TPU v5 lite"' in text
        assert "tpu_chip_info{" in text       # presence survives
        assert "tpu_hbm_used_bytes{" not in text   # absent, not fake-zero
        assert "tpu_hbm_total_bytes{" not in text
        assert "tpu_hbm_used_percent{" not in text

    def test_fixture_covers_many_polls(self):
        _assert_full_capture(self.FIXTURE, min_lines=60)


def _assert_full_capture(fixture: Path, min_lines: int) -> None:
    """Shared guard for the committed real-trace fixtures: the file is a
    real multi-minute capture (not a stub) and the replayer accepts every
    record, not just the first."""
    from tpu_pod_exporter.backend.recorded import RecordedBackend

    lines = fixture.read_text().count("\n")
    assert lines >= min_lines
    backend = RecordedBackend(str(fixture), loop=False)
    for _ in range(lines):
        assert backend.sample().chips


class TestRound5RealHardwareFixture:
    """The round-5 capture (tests/fixtures/real-trace-r5.jsonl, 100 polls
    during the 05:33Z tunnel window) is the first NATIVELY post-fix real
    trace: jaxdev recorded ``hbm_used: null`` directly, so replaying it
    raw — no normalization step — must drive the absent-beats-fake-zero
    pipeline end to end. The round-4 class above keeps the historical
    pre-fix encoding as evidence; this one proves today's encoding is what
    real hardware actually produces."""

    FIXTURE = (
        Path(__file__).resolve().parent / "fixtures" / "real-trace-r5.jsonl"
    )

    def test_raw_replay_drives_absent_hbm_pipeline(self):
        from tpu_pod_exporter.attribution.fake import FakeAttribution
        from tpu_pod_exporter.backend.recorded import RecordedBackend
        from tpu_pod_exporter.collector import Collector
        from tpu_pod_exporter.metrics import SnapshotStore

        backend = RecordedBackend(str(self.FIXTURE))
        sample = backend.sample()
        (chip,) = sample.chips
        assert chip.info.device_kind == "TPU v5 lite"
        assert chip.hbm_used_bytes is None  # recorded null, not 0.0
        assert chip.hbm_total_bytes is None
        assert any("memory_stats" in e for e in sample.partial_errors)

        store = SnapshotStore()
        c = Collector(backend, FakeAttribution(), store)
        c.poll_once()
        snap = store.current()
        text = snap.encode().decode()
        assert 'device_kind="TPU v5 lite"' in text
        assert "tpu_chip_info{" in text            # presence survives
        assert "tpu_hbm_used_bytes{" not in text   # absent, not fake-zero
        assert "tpu_hbm_total_bytes{" not in text
        assert "tpu_hbm_used_percent{" not in text
        assert snap.value(
            "tpu_exporter_poll_errors_total", {"source": "device_partial"}
        ) == 1.0

    def test_fixture_covers_many_polls(self):
        _assert_full_capture(self.FIXTURE, min_lines=100)  # the full capture


def test_structurally_wrong_value_reports_path_and_line(tmp_path):
    # float() on a list / .items() on a scalar raise TypeError/AttributeError,
    # which must surface as the documented BackendError with path:line, not
    # a raw traceback (code-review r5).
    import pytest

    from tpu_pod_exporter.backend import BackendError
    from tpu_pod_exporter.backend.recorded import RecordedBackend

    for bad in (
        '{"chips": [{"chip_id": 0, "hbm_used": 1, "hbm_total": 2, '
        '"duty": null, "ici": {}, "dcn": {"0": [1, 2]}}]}',
        '{"chips": [{"chip_id": 0, "hbm_used": 1, "hbm_total": 2, '
        '"duty": null, "ici": 5}]}',
    ):
        p = tmp_path / "bad.jsonl"
        p.write_text(bad + "\n")
        with pytest.raises(BackendError, match="bad.jsonl:1"):
            RecordedBackend(str(p))
