"""Runtime lock witness (analysis/witness.py).

The witness is itself part of the CI gate (tier-1 runs under it in the
concurrency leg), so its own behavior is pinned here: deterministic
inversion detection, RLock re-entry NOT flagged, dump round-trip, and a
measured overhead budget on the 256-chip poll-loop shape.
"""

import json
import threading
from pathlib import Path

import pytest

from tpu_pod_exporter.analysis.witness import (
    LockWitness,
    load_dump,
)

_TESTS_DIR = str(Path(__file__).resolve().parent)
_REPO_ROOT = str(Path(__file__).resolve().parent.parent)


def _make_witness(**kw):
    """A witness scoped to THIS test file (the default scope is the
    package; tests create their locks here)."""
    return LockWitness(include=(_TESTS_DIR,), root=_REPO_ROOT, **kw)


class TestInversionDetection:
    def test_two_lock_inversion_detected_single_thread(self):
        """Lockdep semantics: A->B then B->A is an inversion even with no
        actual deadlock on this run — two threads interleaving those
        paths can deadlock."""
        w = _make_witness()
        with w:
            a = threading.Lock()
            b = threading.Lock()
        with a:
            with b:
                pass
        assert w.inversions == []  # one order is just an edge
        with b:
            with a:
                pass
        assert len(w.inversions) == 1
        inv = w.inversions[0]
        assert inv["kind"] == "order-inversion"
        assert "test_witness.py" in inv["detail"]

    def test_consistent_order_never_flags(self):
        w = _make_witness()
        with w:
            a = threading.Lock()
            b = threading.Lock()
        for _ in range(5):
            with a:
                with b:
                    pass
        assert w.inversions == []
        assert len(w.edges) == 1

    def test_transitive_inversion_detected(self):
        """A->B, B->C, then C->A closes a 3-cycle."""
        w = _make_witness()
        with w:
            a = threading.Lock()
            b = threading.Lock()
            c = threading.Lock()
        with a:
            with b:
                pass
        with b:
            with c:
                pass
        assert w.inversions == []
        with c:
            with a:
                pass
        assert len(w.inversions) == 1
        assert "already-witnessed order" in w.inversions[0]["detail"]

    def test_cross_thread_edges_merge(self):
        """Edges recorded on different threads land in one graph — the
        classic two-thread AB/BA deadlock candidate is caught."""
        w = _make_witness()
        with w:
            a = threading.Lock()
            b = threading.Lock()

        def t1():
            with a:
                with b:
                    pass

        def t2():
            with b:
                with a:
                    pass

        th1 = threading.Thread(target=t1, name="w-t1", daemon=True)
        th1.start()
        th1.join(timeout=5)
        th2 = threading.Thread(target=t2, name="w-t2", daemon=True)
        th2.start()
        th2.join(timeout=5)
        assert len(w.inversions) == 1

    def test_self_deadlock_noted_on_blocking_reacquire(self):
        """Blocking re-acquire of a non-reentrant lock already held by
        this thread is recorded BEFORE the thread parks (here the timeout
        keeps the test finite)."""
        w = _make_witness()
        with w:
            a = threading.Lock()
        a.acquire()
        try:
            assert a.acquire(True, 0.01) is False
        finally:
            a.release()
        assert len(w.inversions) == 1
        assert w.inversions[0]["kind"] == "self-deadlock"


class TestReentrancy:
    def test_rlock_reentry_not_flagged(self):
        w = _make_witness()
        with w:
            r = threading.RLock()
        with r:
            with r:
                with r:
                    pass
        assert w.inversions == []
        assert w.edges == {}

    def test_rlock_reentry_records_no_self_edge_but_real_edges_stay(self):
        """Re-entry is invisible; a DIFFERENT lock acquired under the
        RLock still edges normally."""
        w = _make_witness()
        with w:
            r = threading.RLock()
            b = threading.Lock()
        with r:
            with r:
                with b:
                    pass
        assert w.inversions == []
        assert len(w.edges) == 1
        (src, dst), = w.edges.keys()
        assert src != dst

    def test_sibling_instances_of_one_site_do_not_self_edge(self):
        """Two locks born at the same creation site (one list
        comprehension) nest without a self-edge — the static model keys
        by site and cannot order instances."""
        w = _make_witness()
        with w:
            pair = [threading.Lock() for _ in range(2)]
        with pair[0]:
            with pair[1]:
                pass
        assert w.edges == {}
        assert w.inversions == []


class TestDumpRoundTrip:
    def test_dump_round_trips_and_is_cross_check_shaped(self, tmp_path):
        w = _make_witness()
        with w:
            a = threading.Lock()
            b = threading.Lock()
        with a:
            with b:
                pass
        out = tmp_path / "witness.json"
        written = w.dump(str(out))
        loaded = load_dump(str(out))
        assert loaded == json.loads(json.dumps(written))
        # The shapes --check-witness consumes:
        assert loaded["meta"]["edges"] == 1
        (lock_a, lock_b) = loaded["locks"]
        for rec in (lock_a, lock_b):
            assert rec["path"].startswith("tests/")
            assert rec["site"] == f"{rec['path']}:{rec['line']}"
            assert rec["kind"] == "lock"
            assert rec["created"] == 1
        edge = loaded["edges"][0]
        assert edge["from"] == lock_a["site"]
        assert edge["to"] == lock_b["site"]
        assert edge["count"] == 1
        assert "thread" in edge["example"]
        assert loaded["inversions"] == []

    def test_long_holds_recorded_against_threshold(self):
        fake_now = [0.0]
        w = _make_witness(hold_warn_ms=10.0, clock=lambda: fake_now[0])
        with w:
            a = threading.Lock()
        a.acquire()
        fake_now[0] += 0.05  # 50 ms "held"
        a.release()
        assert len(w.long_holds) == 1
        assert w.long_holds[0]["held_ms"] == pytest.approx(50.0)
        assert w.max_hold_ms[w.long_holds[0]["site"]] == pytest.approx(50.0)


class TestScoping:
    def test_locks_created_outside_include_paths_stay_raw(self):
        w = LockWitness(include=("/nonexistent-prefix",), root=_REPO_ROOT)
        with w:
            a = threading.Lock()
        assert type(a).__name__ != "_WitnessLock"
        assert w.lock_sites == {}

    def test_uninstall_restores_previous_factory(self):
        before = threading.Lock
        w = _make_witness()
        w.install()
        assert threading.Lock is not before
        w.uninstall()
        assert threading.Lock is before

    def test_wrapped_lock_supports_condition(self):
        """threading.Condition(threading.Lock()) is a live idiom
        (server._WorkerPool._cv) — the wrapper must survive Condition's
        acquire/release/_is_owned dance, including wait timeouts."""
        w = _make_witness()
        with w:
            cv = threading.Condition(threading.Lock())
        with cv:
            assert cv.wait(timeout=0.01) is False
            cv.notify_all()
        # wait() releases and re-acquires through the wrapper: balanced.
        assert w.inversions == []


class TestOverheadBudget:
    @pytest.mark.slow
    def test_poll_loop_overhead_within_budget(self):
        """Witnessed vs raw poll-loop CPU at 256 chips, interleaved
        segments (the trace-overhead methodology: whole-run A/B drowns
        in scheduler drift). The witness wraps every package lock the
        poll path touches; budget is deliberately generous — this is a
        regression tripwire for accidental O(n) work in the acquire
        path, not a microbenchmark."""
        from tpu_pod_exporter import utils
        from tpu_pod_exporter.attribution.fake import FakeAttribution
        from tpu_pod_exporter.backend.fake import FakeBackend
        from tpu_pod_exporter.collector import Collector
        from tpu_pod_exporter.metrics import SnapshotStore

        def make() -> Collector:
            c = Collector(FakeBackend(chips=256), FakeAttribution(),
                          SnapshotStore())
            for _ in range(10):
                c.poll_once()
            return c

        off = make()  # raw locks: built before any witness install
        w = LockWitness()  # default scope: the package itself
        with w:
            on = make()  # every lock in this collector is witnessed

        def segment(c: Collector, n: int) -> float:
            c0 = utils.process_cpu_seconds()
            for _ in range(n):
                c.poll_once()
            return utils.process_cpu_seconds() - c0

        t_off = t_on = 0.0
        for seg in range(8):
            if seg % 2:
                t_on += segment(on, 15)
                t_off += segment(off, 15)
            else:
                t_off += segment(off, 15)
                t_on += segment(on, 15)
        assert w.acquisitions > 0, "witness saw no poll-path locks"
        overhead = t_on / t_off - 1.0 if t_off > 0 else 0.0
        assert overhead < 0.50, (
            f"witness overhead {overhead:+.1%} over budget (off "
            f"{t_off:.3f}s, on {t_on:.3f}s, "
            f"{w.acquisitions} acquisitions)")

    def test_acquire_release_fast_path_bounded(self):
        """Absolute per-op ceiling on the uncontended acquire/release
        fast path — catches accidental edge-graph work per acquisition
        (edges must only pay on FIRST sighting)."""
        import time

        w = _make_witness()
        with w:
            a = threading.Lock()
            b = threading.Lock()
        with a:
            with b:
                pass  # edge recorded once, up front
        n = 20_000
        t0 = time.perf_counter()
        for _ in range(n):
            with a:
                with b:
                    pass
        per_op_us = (time.perf_counter() - t0) / (2 * n) * 1e6
        assert per_op_us < 50.0, f"{per_op_us:.1f} µs per acquire/release"
