"""Streaming dashboard plane (ISSUE 15).

Covers the hub's delta machinery with injected poll functions (no
sockets): snapshot-then-delta row replacement, removed-series keys,
full-sync cadence, heartbeats, seq continuity; the seeded churn property
sweep asserting delta replay reproduces the polled answer exactly; the
HTTP transports (SSE registration + pushes, long-poll cursor flow) through
a real MetricsServer; admission (cap 429) and shedding (pressure rung,
slow-subscriber buffer cap); the replica source proxy; and the pump/attach
wiring the CLIs use.
"""

import json
import random
import socket
import threading
import time
import urllib.error
import urllib.request

import pytest

from tpu_pod_exporter.metrics import SnapshotBuilder, SnapshotStore, schema
from tpu_pod_exporter.pressure import PressureGovernor, register_stream_rung
from tpu_pod_exporter.server import MetricsServer
from tpu_pod_exporter.shard import ReplicaSourceProxy
from tpu_pod_exporter.stream import (
    HubFull,
    QueryShape,
    SseParser,
    StreamClient,
    StreamDisabled,
    StreamHub,
    StreamPump,
    StreamReplay,
    attach_stream,
    row_key,
    rows_map,
    stream_path,
)


def env_of(rows, partial=False):
    return {
        "status": "ok", "partial": partial, "source": "live",
        "data": {"result": [dict(r) for r in rows]},
        "fleet": {"targets": 4, "ok": 4},
        "took_s": 0.001,
    }


def make_world(rows=None):
    """Mutable fake backend: world['rows'] is what poll_fn answers."""
    world = {
        "gen": 1,
        "rows": rows if rows is not None else [
            {"metric": "m", "labels": {"h": "a"}, "value": 1.0},
            {"metric": "m", "labels": {"h": "b"}, "value": 2.0},
        ],
        "polls": 0,
    }

    def poll_fn(shape, gen):
        world["polls"] += 1
        return env_of(world["rows"])

    world["poll_fn"] = poll_fn
    return world


def make_hub(world, **kw):
    kw.setdefault("heartbeat_s", 3600.0)
    kw.setdefault("full_sync_s", 3600.0)
    return StreamHub(world["poll_fn"], lambda: world["gen"], **kw)


class Capture:
    """In-process subscriber: writer captures bytes, frames() parses."""

    def __init__(self):
        self.chunks = []
        self.parser = SseParser()
        self.closed = False
        self.replay = StreamReplay()

    def writer(self, payload):
        self.chunks.append(payload)

    def closer(self):
        self.closed = True

    def drain(self):
        frames = []
        for chunk in self.chunks:
            frames.extend(self.parser.feed(chunk))
        self.chunks = []
        for f in frames:
            self.replay.apply(f)
        return frames


WS = QueryShape(route="window_stats", metric="m", window_s=30.0)


class TestQueryShape:
    def test_defaults_and_key_identity(self):
        a = QueryShape.from_params({"metric": "m"}.get, {"slice_name": "s"})
        b = QueryShape.from_params(
            {"metric": "m", "window": "60"}.get, {"slice_name": "s"})
        assert a.key == b.key  # default window == explicit default
        assert a.route == "window_stats"

    @pytest.mark.parametrize("params,needle", [
        ({"route": "bogus"}, "route"),
        ({}, "metric"),
        ({"metric": "m", "window": "0"}, "window"),
        ({"metric": "m", "window": "inf"}, "window"),
        ({"route": "query_range", "metric": "m", "step": "-1"}, "step"),
        # Streams require a grid: step=0 would re-anchor at every round's
        # wall clock (full-body "deltas", zero cache hits).
        ({"route": "query_range", "metric": "m"}, "step > 0"),
        ({"route": "query_range", "metric": "m", "window": "100000",
          "step": "0.001"}, "resolution"),
        ({"route": "query_range", "metric": "m", "step": "15",
          "agg": "median"}, "agg"),
    ])
    def test_validation_errors_name_the_token(self, params, needle):
        with pytest.raises(ValueError, match=needle):
            QueryShape.from_params(params.get, {})

    def test_series_shape_ignores_metric(self):
        s = QueryShape.from_params({"route": "series"}.get, {})
        assert s.route == "series" and s.metric == ""


class TestHubDeltas:
    def test_snapshot_then_delta_changed_and_removed(self):
        world = make_world()
        hub = make_hub(world)
        cap = Capture()
        sub, first = hub.subscribe(WS, cap.writer, cap.closer)
        cap.writer(first)
        frames = cap.drain()
        assert [f["type"] for f in frames] == ["snapshot"]
        assert len(cap.replay.rows) == 2

        world["rows"] = [
            {"metric": "m", "labels": {"h": "a"}, "value": 5.0},  # changed
            {"metric": "m", "labels": {"h": "c"}, "value": 9.0},  # added
        ]  # b removed
        world["gen"] = 2
        hub.on_round(2)
        frames = cap.drain()
        assert [f["type"] for f in frames] == ["delta"]
        delta = frames[0]
        assert len(delta["changed"]) == 2
        assert len(delta["removed"]) == 1
        assert cap.replay.rows_by_key() == rows_map(
            "window_stats", env_of(world["rows"]))
        assert cap.replay.gaps == 0 and cap.replay.dups == 0

    def test_unchanged_round_ships_nothing(self):
        world = make_world()
        hub = make_hub(world)
        cap = Capture()
        _sub, first = hub.subscribe(WS, cap.writer, cap.closer)
        cap.writer(first)
        cap.drain()
        hub.on_round(2)
        hub.on_round(3)
        assert cap.drain() == []

    def test_one_evaluation_shared_by_many_subscribers(self):
        world = make_world()
        hub = make_hub(world)
        caps = [Capture() for _ in range(8)]
        for cap in caps:
            _s, first = hub.subscribe(WS, cap.writer, cap.closer)
            cap.writer(first)
            cap.drain()
        polls_before = world["polls"]
        world["rows"][0]["value"] = 42.0
        hub.on_round(2)
        # ONE poll for 8 subscribers (the fan-out inversion's cost model).
        assert world["polls"] == polls_before + 1
        for cap in caps:
            frames = cap.drain()
            assert [f["type"] for f in frames] == ["delta"]

    def test_full_sync_cadence(self):
        world = make_world()
        wall = {"t": 1000.0}
        hub = StreamHub(world["poll_fn"], lambda: world["gen"],
                        heartbeat_s=3600.0, full_sync_s=10.0,
                        wallclock=lambda: wall["t"])
        cap = Capture()
        _s, first = hub.subscribe(WS, cap.writer, cap.closer)
        cap.writer(first)
        cap.drain()
        world["rows"][0]["value"] = 2.0
        wall["t"] += 5
        hub.on_round(2)
        assert [f["type"] for f in cap.drain()] == ["delta"]
        wall["t"] += 6  # past full_sync_s since subscribe
        hub.on_round(3)  # even with NO changes, a full sync ships
        frames = cap.drain()
        assert [f["type"] for f in frames] == ["full_sync"]
        assert cap.replay.rows_by_key() == rows_map(
            "window_stats", env_of(world["rows"]))

    def test_heartbeat_only_when_quiet(self):
        world = make_world()
        wall = {"t": 1000.0}
        hub = StreamHub(world["poll_fn"], lambda: world["gen"],
                        heartbeat_s=5.0, full_sync_s=3600.0,
                        wallclock=lambda: wall["t"])
        cap = Capture()
        _s, first = hub.subscribe(WS, cap.writer, cap.closer)
        cap.writer(first)
        cap.drain()
        hub.tick()
        assert cap.drain() == []  # quiet but not past heartbeat_s yet
        wall["t"] += 6
        hub.tick()
        frames = cap.drain()
        assert [f["type"] for f in frames] == ["heartbeat"]
        assert frames[0]["seq"] == 0  # heartbeats never consume a seq

    def test_detach_stops_pushes_and_counts(self):
        world = make_world()
        hub = make_hub(world)
        cap = Capture()
        sub, first = hub.subscribe(WS, cap.writer, cap.closer)
        cap.writer(first)
        assert hub.subscribers == 1
        hub.detach(sub)
        assert hub.subscribers == 0
        world["rows"][0]["value"] = 7.0
        hub.on_round(2)
        cap.drain()
        assert cap.replay.data_frames == 1  # snapshot only

    def test_cap_rejects_and_counts(self):
        world = make_world()
        hub = make_hub(world, max_subscribers=2)
        caps = [Capture() for _ in range(2)]
        for cap in caps:
            hub.subscribe(WS, cap.writer, cap.closer)
        with pytest.raises(HubFull):
            hub.subscribe(WS, Capture().writer, Capture().closer)
        b = SnapshotBuilder()
        hub.emit(b)
        snap = b.build(timestamp=1.0)
        assert snap.value("tpu_stream_rejects_total", ("cap",)) == 1.0
        assert snap.value("tpu_stream_subscribers") == 2.0

    def test_shed_oldest_sends_shed_frame_and_frees_slots(self):
        world = make_world()
        hub = make_hub(world, max_subscribers=4)
        caps = [Capture() for _ in range(4)]
        for cap in caps:
            _s, first = hub.subscribe(WS, cap.writer, cap.closer)
            cap.writer(first)
            cap.drain()
        shed = hub.shed_oldest(0.5, reason="pressure")
        assert shed == 2 and hub.subscribers == 2
        # The OLDEST two got the shed frame + close; the newest two none.
        for cap in caps[:2]:
            cap.drain()
            assert cap.replay.shed_reason == "pressure"
            assert cap.closed
        for cap in caps[2:]:
            cap.drain()
            assert cap.replay.shed_reason is None

    def test_pressure_rung_sheds_and_halves_cap_then_recovers(self):
        world = make_world()
        hub = make_hub(world, max_subscribers=8)
        caps = [Capture() for _ in range(6)]
        for cap in caps:
            hub.subscribe(WS, cap.writer, cap.closer)
        gov = PressureGovernor(memory_budget_bytes=1)  # everything is over
        register_stream_rung(gov, hub)
        gov.tick()
        assert hub.subscribers == 3
        assert hub.max_subscribers == 4  # halved effective cap
        b = SnapshotBuilder()
        hub.emit(b)
        snap = b.build(timestamp=1.0)
        assert snap.value("tpu_stream_sheds_total", ("pressure",)) == 3.0
        # Recovery restores the configured cap (drive the ladder down).
        gov.set_memory_budget_bytes(1 << 30)
        hub.release_pressure()
        assert hub.max_subscribers == 8

    def test_bad_shape_evaluation_does_not_kill_the_round(self):
        calls = {"n": 0}

        def poll_fn(shape, gen):
            calls["n"] += 1
            if shape.metric == "bad":
                raise RuntimeError("backend exploded")
            return env_of([{"metric": "m", "labels": {}, "value": 1.0}])

        hub = StreamHub(poll_fn, lambda: 1, heartbeat_s=3600,
                        full_sync_s=3600)
        good, bad = Capture(), Capture()
        hub.subscribe(WS, good.writer, good.closer)
        with pytest.raises(RuntimeError):
            # Registration surfaces the failure to THAT subscriber only.
            hub.subscribe(
                QueryShape(route="window_stats", metric="bad"),
                bad.writer, bad.closer)
        hub.on_round(2)  # must not raise
        assert hub.subscribers == 1


class TestReplayProperty:
    """Satellite: seeded rounds with value/layout/membership churn — the
    streamed deltas applied on top of the snapshot frame must reproduce
    the polled answer exactly (the test_render_splice sweep pattern)."""

    HOSTS = ["a", "b", "c", "d", "e", "f"]

    def _mutate(self, rng, rows):
        rows = [dict(r) for r in rows]
        action = rng.random()
        if rows and action < 0.5:  # value churn on a random subset
            for r in rng.sample(rows, k=max(1, len(rows) // 2)):
                r["value"] = round(rng.uniform(0, 100), 3)
        elif action < 0.7 and len(rows) < 12:  # membership: add
            h = rng.choice(self.HOSTS)
            c = str(rng.randrange(4))
            key = {"h": h, "chip": c}
            if not any(r["labels"] == key for r in rows):
                rows.append({"metric": "m", "labels": key,
                             "value": rng.uniform(0, 100)})
        elif action < 0.85 and len(rows) > 1:  # membership: remove
            rows.pop(rng.randrange(len(rows)))
        elif rows:  # layout churn: a label VALUE changes (new series key)
            r = rng.choice(rows)
            r["labels"] = {**r["labels"], "pod": f"p{rng.randrange(3)}"}
        return rows

    @pytest.mark.parametrize("seed", range(6))
    def test_replay_equals_polled_through_churn(self, seed):
        rng = random.Random(seed)
        rows = [{"metric": "m", "labels": {"h": h, "chip": "0"},
                 "value": 1.0} for h in self.HOSTS[:3]]
        state = {"rows": rows}
        wall = {"t": 1000.0}

        def poll_fn(shape, gen):
            return env_of(state["rows"])

        # Small full_sync period so the sweep exercises delta AND
        # full_sync replay; heartbeats interleave via tick().
        hub = StreamHub(poll_fn, lambda: 1, heartbeat_s=7.0,
                        full_sync_s=13.0, wallclock=lambda: wall["t"])
        cap = Capture()
        _s, first = hub.subscribe(WS, cap.writer, cap.closer)
        cap.writer(first)
        cap.drain()
        assert cap.replay.rows_by_key() == rows_map(
            "window_stats", env_of(state["rows"]))
        for r in range(60):
            state["rows"] = self._mutate(rng, state["rows"])
            wall["t"] += rng.choice([1.0, 2.0, 5.0])
            hub.on_round(r + 2)
            if rng.random() < 0.3:
                hub.tick()
            cap.drain()
            assert cap.replay.rows_by_key() == rows_map(
                "window_stats", env_of(state["rows"])), (
                f"replay diverged at round {r} (seed {seed})")
            assert cap.replay.gaps == 0 and cap.replay.dups == 0
            assert not cap.replay.desynced

    def test_gap_detection_and_full_sync_heal(self):
        rep = StreamReplay()
        rep.apply({"type": "snapshot", "seq": 3, "gen": 1, "rows": [],
                   "meta": {}})
        rep.apply({"type": "delta", "seq": 6, "gen": 2,
                   "changed": [], "removed": [], "meta": {}})
        assert rep.gaps == 2 and rep.desynced
        rep.apply({"type": "full_sync", "seq": 7, "gen": 3,
                   "rows": [{"metric": "m", "labels": {}, "value": 1.0}],
                   "meta": {}})
        assert not rep.desynced and len(rep.rows) == 1
        rep.apply({"type": "delta", "seq": 7, "gen": 3,
                   "changed": [], "removed": [], "meta": {}})
        assert rep.dups == 1


def start_server(hub):
    server = MetricsServer(SnapshotStore(), host="127.0.0.1", port=0,
                           stream_hub=hub)
    server.start()
    return server


def get_json(url, timeout=5.0):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read())


class TestHttpTransports:
    def test_sse_subscribe_and_push_over_the_wire(self):
        world = make_world()
        hub = make_hub(world)
        server = start_server(hub)
        try:
            client = StreamClient("127.0.0.1", server.port, WS,
                                  timeout_s=5.0)
            rep = StreamReplay()
            for f in client.frames(max_frames=1, timeout_s=3.0):
                rep.apply(f)
            assert rep.seq == 0 and len(rep.rows) == 2
            world["rows"][0]["value"] = 77.0
            hub.on_round(2)
            for f in client.frames(max_frames=1, timeout_s=3.0):
                rep.apply(f)
            assert rep.rows_by_key() == rows_map(
                "window_stats", env_of(world["rows"]))
            client.close()
            # Client EOF frees the hub slot via the loop's close path.
            deadline = time.monotonic() + 3.0
            while hub.subscribers and time.monotonic() < deadline:
                time.sleep(0.02)
            assert hub.subscribers == 0
        finally:
            server.stop()

    def test_no_hub_is_404_and_client_raises_disabled(self):
        server = MetricsServer(SnapshotStore(), host="127.0.0.1", port=0)
        server.start()
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                get_json(f"http://127.0.0.1:{server.port}/api/v1/stream"
                         f"?metric=m&transport=longpoll")
            assert ei.value.code == 404
            with pytest.raises(StreamDisabled):
                StreamClient("127.0.0.1", server.port, WS, timeout_s=3.0)
        finally:
            server.stop()

    def test_bad_params_are_400_with_the_token(self):
        world = make_world()
        hub = make_hub(world)
        server = start_server(hub)
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                get_json(f"http://127.0.0.1:{server.port}/api/v1/stream"
                         f"?route=query_range&metric=m&step=15&agg=median"
                         f"&transport=longpoll")
            assert ei.value.code == 400
            assert "agg" in json.loads(ei.value.read())["error"]
            with pytest.raises(urllib.error.HTTPError) as ei:
                get_json(f"http://127.0.0.1:{server.port}/api/v1/stream"
                         f"?metric=m&transport=carrier-pigeon")
            assert ei.value.code == 400
        finally:
            server.stop()

    def test_cap_answers_429_over_the_wire(self):
        world = make_world()
        hub = make_hub(world, max_subscribers=1)
        server = start_server(hub)
        clients = []
        try:
            clients.append(StreamClient("127.0.0.1", server.port, WS,
                                        timeout_s=5.0))
            with pytest.raises(StreamDisabled, match="429"):
                StreamClient("127.0.0.1", server.port, WS, timeout_s=5.0)
        finally:
            for c in clients:
                c.close()
            server.stop()

    def test_longpoll_cursor_flow(self):
        world = make_world()
        hub = make_hub(world)
        server = start_server(hub)
        base = f"http://127.0.0.1:{server.port}"
        try:
            doc = get_json(base + stream_path(WS, transport="longpoll"))
            assert [f["type"] for f in doc["frames"]] == ["snapshot"]
            cursor = doc["cursor"]
            # Parked request answered by the next round.
            result = {}

            def lp():
                result["doc"] = get_json(
                    base + stream_path(WS, transport="longpoll",
                                       cursor=cursor), timeout=10.0)

            t = threading.Thread(target=lp, daemon=True, name="t-lp")
            t.start()
            time.sleep(0.3)
            world["rows"][0]["value"] = 3.5
            hub.on_round(2)
            t.join(5.0)
            assert not t.is_alive()
            assert [f["type"] for f in result["doc"]["frames"]] == ["delta"]
            assert result["doc"]["cursor"] == cursor + 1
            # A stale cursor inside the ring window gets the missed
            # frames; one behind the ring gets a fresh snapshot.
            doc = get_json(base + stream_path(WS, transport="longpoll",
                                              cursor=0))
            assert [f["type"] for f in doc["frames"]] == ["delta"]
        finally:
            server.stop()

    def test_slow_subscriber_is_shed_at_buffer_cap(self):
        world = make_world()
        # Big rows so a few frames blow the tiny buffer below.
        world["rows"] = [{"metric": "m", "labels": {"h": str(i)},
                         "value": 1.0, "pad": "x" * 512}
                        for i in range(64)]
        hub = make_hub(world, full_sync_s=0.0)
        server = MetricsServer(SnapshotStore(), host="127.0.0.1", port=0,
                               stream_hub=hub,
                               stream_max_buffer_bytes=8 * 1024)
        server.start()
        try:
            sock = socket.create_connection(("127.0.0.1", server.port),
                                            timeout=5.0)
            sock.sendall(
                f"GET {stream_path(WS)} HTTP/1.1\r\n"
                f"Host: x\r\n\r\n".encode())
            sock.recv(1024)  # head+start of snapshot, then STOP reading
            # Shrink the client's receive window so pushed frames pile up
            # server-side instead of draining into kernel buffers.
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 1024)
            deadline = time.monotonic() + 10.0
            shed = 0.0
            while time.monotonic() < deadline:
                for i in range(64):
                    world["rows"][i % 64]["value"] += 1.0
                hub.on_round(int(time.monotonic() * 1000) % 100000)
                b = SnapshotBuilder()
                hub.emit(b)
                shed = b.build(timestamp=1.0).value(
                    "tpu_stream_sheds_total", ("slow",)) or 0.0
                if shed:
                    break
                time.sleep(0.02)
            assert shed >= 1.0, "stalled subscriber was never shed"
            sock.close()
        finally:
            server.stop()


class TestPumpAndWiring:
    def test_pump_runs_on_round_off_the_round_thread(self):
        world = make_world()
        hub = make_hub(world)
        cap = Capture()
        _s, first = hub.subscribe(WS, cap.writer, cap.closer)
        cap.writer(first)
        cap.drain()
        pump = StreamPump(hub)
        pump.start()
        try:
            world["rows"][0]["value"] = 11.0
            pump.notify(2)
            deadline = time.monotonic() + 5.0
            while not cap.chunks and time.monotonic() < deadline:
                time.sleep(0.01)
            assert [f["type"] for f in cap.drain()] == ["delta"]
        finally:
            pump.close()

    def test_attach_stream_wires_round_and_emit_hooks(self):
        class FakeAgg:
            rounds = 1

            def __init__(self):
                self.emit_hooks = []
                self.round_hooks = []

        agg = FakeAgg()

        class FakePlane:
            def window_stats(self, metric, match, window_s):
                return env_of([{"metric": metric, "labels": {},
                                "value": 1.0}])

        hub, pump = attach_stream(agg, FakePlane())
        try:
            assert len(agg.round_hooks) == 1
            assert len(agg.emit_hooks) == 1
            b = SnapshotBuilder()
            agg.emit_hooks[0](b)
            snap = b.build(timestamp=1.0)
            assert snap.value("tpu_stream_subscribers") == 0.0
        finally:
            pump.close()
            hub.close()


class TestReplicaSourceProxy:
    def _inner(self):
        class Inner:
            def series(self):
                return {"status": "ok", "data": []}

            def window_stats(self, metric, match, window_s):
                return env_of([{"metric": metric, "labels": {},
                                "value": 1.0}])

            def query_range(self, metric, match, start, end, step,
                            agg="last"):
                return {"status": "ok", "source": "live",
                        "data": {"resultType": "matrix", "result": []}}

            def close(self):
                pass

        return Inner()

    def test_no_root_url_400s_honestly(self):
        proxy = ReplicaSourceProxy(self._inner(), replica_id="r1")
        with pytest.raises(ValueError, match="--root-url"):
            proxy.window_stats("m", {}, window_s=30.0, source="store")
        # Live queries pass straight through.
        env = proxy.window_stats("m", {}, window_s=30.0)
        assert env["status"] == "ok"

    def test_proxies_source_queries_to_root(self):
        seen = {}

        def fetch(url, timeout_s):
            seen["url"] = url
            return {"status": "ok", "source": "store",
                    "data": {"result": []}}

        proxy = ReplicaSourceProxy(self._inner(), replica_id="r1",
                                   root_url="root:9100", fetch=fetch)
        doc = proxy.window_stats("m", {"slice_name": "s"}, window_s=30.0,
                                 source="store")
        assert doc["proxied"] is True and doc["source"] == "store"
        assert "source=store" in seen["url"]
        assert "root%3A9100" not in seen["url"]  # host not double-encoded

    def test_root_refusal_relays_as_400_and_outage_degrades(self):
        def refuse(url, timeout_s):
            raise urllib.error.HTTPError(url, 400, "bad", {}, None)

        proxy = ReplicaSourceProxy(self._inner(), root_url="root:9100",
                                   fetch=refuse)
        with pytest.raises(ValueError, match="HTTP 400"):
            proxy.series(source="store")

        def dead(url, timeout_s):
            raise ConnectionRefusedError("down")

        proxy2 = ReplicaSourceProxy(self._inner(), root_url="root:9100",
                                    fetch=dead)
        doc = proxy2.query_range("m", source="store")
        assert doc["status"] == "error" and doc["proxied"] is True

    def test_emit_publishes_identity_and_counters(self):
        def fetch(url, timeout_s):
            return {"status": "ok", "data": {"result": []}}

        proxy = ReplicaSourceProxy(self._inner(), replica_id="r7",
                                   root_url="root:9100", fetch=fetch)
        proxy.window_stats("m", {}, window_s=30.0, source="store")
        b = SnapshotBuilder()
        proxy.emit(b)
        snap = b.build(timestamp=1.0)
        assert snap.value("tpu_replica_info", ("r7",)) == 1.0
        assert snap.value("tpu_replica_store_proxied_total",
                          ("ok",)) == 1.0


class TestStreamExpositionSurface:
    def test_stream_metric_names_resolve_to_schema(self):
        world = make_world()
        hub = make_hub(world)
        cap = Capture()
        hub.subscribe(WS, cap.writer, cap.closer)
        hub.on_round(2)
        b = SnapshotBuilder()
        hub.emit(b)
        snap = b.build(timestamp=1.0)
        names = {spec.name for spec in snap.families()}
        for spec in schema.STREAM_SPECS:
            assert spec.name in names
        assert snap.value("tpu_stream_query_shapes") == 1.0
        assert snap.value("tpu_stream_frames_total", ("snapshot",)) == 1.0


class TestStatusWatchFallback:
    def test_split_addr_forms(self):
        from tpu_pod_exporter.status import _split_addr

        assert _split_addr("127.0.0.1:9100") == ("127.0.0.1", 9100)
        assert _split_addr("http://h:91/metrics") == ("h", 91)
        assert _split_addr("h:not-a-port") is None

    def test_watch_falls_back_when_no_stream_offered(self):
        from tpu_pod_exporter.status import _watch_fleet_stream

        server = MetricsServer(SnapshotStore(), host="127.0.0.1", port=0)
        server.start()
        try:
            # No hub on this tier: the watcher must return None (the
            # caller's polling fallback), never crash or hang.
            rc = _watch_fleet_stream(f"127.0.0.1:{server.port}", 30.0,
                                     0.1, as_json="line")
            assert rc is None
        finally:
            server.stop()


class TestScenarioDsl:
    def test_dashboard_storm_parses(self):
        from tpu_pod_exporter.scenario import SCENARIOS, parse_event

        ev = parse_event("dashboard_storm(500)@2+6")
        assert ev.count == 500 and ev.duration == 6
        # The named drill's timeline must itself parse.
        assert SCENARIOS["dashboard_storm"].events()

    @pytest.mark.parametrize("raw,needle", [
        ("dashboard_storm()@2+4", "subscription"),
        ("dashboard_storm(x)@2+4", "integer"),
        ("dashboard_storm(0)@2+4", ">= 1"),
        ("dashboard_storm(10)@2", "duration"),
    ])
    def test_dashboard_storm_parse_errors(self, raw, needle):
        from tpu_pod_exporter.scenario import parse_event

        with pytest.raises(ValueError, match=needle):
            parse_event(raw)


class TestDashboardDemoSmoke:
    def test_small_scale_end_to_end(self, tmp_path):
        """The acceptance harness at toy scale: subscriptions against one
        root + one replica over a real leaf tier, replica killed
        mid-storm, every invariant green."""
        from tpu_pod_exporter.loadgen.fleet import run_dashboard_demo

        result = run_dashboard_demo(
            n_targets=12, shards=2, chips=2, subs=16, rounds=3,
            replicas=1, state_root=str(tmp_path / "dash"),
            push_p99_budget_s=5.0, rss_cap_mb=256.0,
        )
        assert result["ok"], result["failures"]
        assert result["connected"] == 16
        assert result["gaps"] == 0 and result["dups"] == 0
        assert result["equality_failures"] == 0
        assert result["replica_kill"]["live_after"] == 16
        assert result["shed"]["counted"] == result["shed"]["shed"]
        assert result["pull_baseline"]["qps_one_client"] > 0


class TestReviewHardening:
    """Regression pins for the PR-15 review findings."""

    def test_deferred_activate_catches_up_rounds_committed_mid_setup(self):
        # A round committed between subscribe(auto_start=False) and
        # activate() must arrive via the ring catch-up — not be dropped
        # into the pre-transport window as a permanent seq gap.
        world = make_world()
        hub = make_hub(world)
        cap = Capture()
        sub, first = hub.subscribe(WS, cap.writer, cap.closer,
                                   auto_start=False)
        world["rows"][0]["value"] = 99.0
        hub.on_round(2)  # commits seq 1 while the transport is not ready
        assert cap.chunks == []  # nothing pushed to an unstarted sub
        catchup = hub.activate(sub)
        cap.writer(first + catchup)
        cap.drain()
        assert cap.replay.seq == 1
        assert cap.replay.gaps == 0 and not cap.replay.desynced
        assert cap.replay.rows_by_key() == rows_map(
            "window_stats", env_of(world["rows"]))
        # And pushes flow normally after activation.
        world["rows"][0]["value"] = 100.0
        hub.on_round(3)
        cap.drain()
        assert cap.replay.seq == 2 and cap.replay.gaps == 0

    def test_longpoll_waiter_answered_with_heartbeats_disabled(self):
        world = make_world()
        mono = {"t": 100.0}
        hub = StreamHub(world["poll_fn"], lambda: 1, heartbeat_s=0.0,
                        full_sync_s=3600.0, clock=lambda: mono["t"])
        answers = []
        parked = hub.poll_frames(
            QueryShape(route="window_stats", metric="m", window_s=30.0),
            cursor=0, callback=answers.append, wait_s=None)
        assert parked is None  # cursor == seq: held
        mono["t"] += 30.0  # past the disabled-heartbeat fallback hold
        hub.tick()
        assert answers and answers[0]["frames"][0]["type"] == "heartbeat"

    def test_shed_frame_reaches_the_viewer_before_close(self):
        # Flush-then-close: the final labeled shed frame must arrive over
        # the wire (the RUNBOOK contract), then the connection ends.
        world = make_world()
        hub = make_hub(world)
        server = start_server(hub)
        try:
            client = StreamClient("127.0.0.1", server.port, WS,
                                  timeout_s=5.0)
            rep = StreamReplay()
            for f in client.frames(max_frames=1, timeout_s=3.0):
                rep.apply(f)
            assert hub.shed_oldest(1.0, reason="pressure") == 1
            for f in client.frames(timeout_s=5.0):
                rep.apply(f)
            assert rep.shed_reason == "pressure"
            assert client.eof
        finally:
            server.stop()

    def test_full_frames_carry_per_target_status_meta(self):
        def poll_fn(shape, gen):
            env = env_of([{"metric": "m", "labels": {}, "value": 1.0}])
            env["targets"] = {"t1": {"state": "quarantined"}}
            return env

        hub = StreamHub(poll_fn, lambda: 1, heartbeat_s=3600,
                        full_sync_s=3600)
        cap = Capture()
        _s, first = hub.subscribe(WS, cap.writer, cap.closer)
        cap.writer(first)
        cap.drain()
        assert cap.replay.meta["targets"]["t1"]["state"] == "quarantined"

    def test_build_serving_governor_sheds_cache_then_viewers(self):
        # The production CLI wiring (aggregate/root/replica
        # --memory-budget-mb): the query result cache sheds FIRST,
        # oldest subscriptions LAST — and the governor actually exists
        # outside test harnesses (review finding: the rung used to be
        # wired only in loadgen).
        from tpu_pod_exporter.pressure import build_serving_governor

        class FakePlane:
            enabled = True

            def cache_bytes(self):
                return 4096

            def set_cache_enabled(self, on):
                self.enabled = on

        world = make_world()
        hub = make_hub(world, max_subscribers=8)
        caps = [Capture() for _ in range(4)]
        for cap in caps:
            hub.subscribe(WS, cap.writer, cap.closer)
        plane = FakePlane()
        # Pre-built, never started: deterministic manual ticks (the CLI
        # path passes governor=None and gets a started thread instead).
        base = PressureGovernor(memory_budget_bytes=0)
        gov = build_serving_governor(1, cache_plane=plane, hub=hub,
                                     governor=base)
        assert gov is base  # extends, never duplicates
        try:
            gov.tick()  # rung 1: cache
            assert plane.enabled is False
            assert hub.subscribers == 4
            gov.tick()  # rung 2: stream_shed
            assert hub.subscribers == 2
            assert hub.max_subscribers == 4
        finally:
            gov.close()
        # No budget + no existing governor ⇒ nothing built.
        assert build_serving_governor(0, cache_plane=plane,
                                      hub=hub) is None
