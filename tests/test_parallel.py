"""SP/PP/EP sharded programs vs single-device ground truth (virtual 8-device
CPU mesh from conftest; SURVEY.md §2.8 — the distributed dimension as
instrument).

The numeric bodies live in ``tpu_pod_exporter.loadgen.selftest.CHECKS`` —
the same functions the driver's sanitized-subprocess gate runs — so the
pytest suite and the driver gate can never drift apart.
"""

import pytest

from tests.conftest import require_jax
from tpu_pod_exporter.loadgen import selftest

N = 8


@pytest.mark.parametrize("name", sorted(selftest.CHECKS))
def test_check(name):
    require_jax()
    result = selftest.CHECKS[name](N)
    assert result.get("ok"), f"{name}: {result}"


def test_dryrun_checks_subset():
    assert set(selftest.DRYRUN_CHECKS) <= set(selftest.CHECKS)


def test_run_checks_reports_failures():
    """A raising check must surface as ok=False with the error, not crash."""
    saved = dict(selftest.CHECKS)
    try:
        selftest.CHECKS["boom"] = lambda n: (_ for _ in ()).throw(ValueError("x"))
        results = selftest.run_checks(2, ["boom"])
    finally:
        selftest.CHECKS.clear()
        selftest.CHECKS.update(saved)
    assert results["boom"]["ok"] is False
    assert "ValueError" in results["boom"]["error"]
