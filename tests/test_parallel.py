"""SP/PP/EP sharded programs vs single-device ground truth (virtual 8-device
CPU mesh from conftest; SURVEY.md §2.8 — the distributed dimension as
instrument)."""

import numpy as np
import pytest

from tests.conftest import require_jax

N = 8


@pytest.fixture(scope="module", autouse=True)
def _need_jax():
    require_jax()


def test_ring_attention_matches_full_attention():
    import jax
    import jax.numpy as jnp

    from tpu_pod_exporter.loadgen.parallel import (
        make_1d_mesh, reference_attention, ring_attention_fn,
    )

    mesh = make_1d_mesh(N, "seq")
    fn, sharding = ring_attention_fn(mesh)
    t, d = 4 * N, 16
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(7), 3)
    q = jax.random.normal(k1, (t, d), jnp.float32)
    k = jax.random.normal(k2, (t, d), jnp.float32)
    v = jax.random.normal(k3, (t, d), jnp.float32)
    out = fn(
        jax.device_put(q, sharding),
        jax.device_put(k, sharding),
        jax.device_put(v, sharding),
    )
    ref = reference_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_ring_attention_uneven_values_stay_stable():
    # Large score magnitudes exercise the running-max renormalization.
    import jax
    import jax.numpy as jnp

    from tpu_pod_exporter.loadgen.parallel import (
        make_1d_mesh, reference_attention, ring_attention_fn,
    )

    mesh = make_1d_mesh(N, "seq")
    fn, sharding = ring_attention_fn(mesh)
    t, d = 2 * N, 4
    q = 30.0 * jax.random.normal(jax.random.PRNGKey(0), (t, d), jnp.float32)
    k = 30.0 * jax.random.normal(jax.random.PRNGKey(1), (t, d), jnp.float32)
    v = jax.random.normal(jax.random.PRNGKey(2), (t, d), jnp.float32)
    out = np.asarray(fn(*(jax.device_put(a, sharding) for a in (q, k, v))))
    assert np.isfinite(out).all()
    np.testing.assert_allclose(
        out, np.asarray(reference_attention(q, k, v)), rtol=1e-4, atol=1e-4
    )


def test_pipeline_matches_sequential_stages():
    import jax
    import jax.numpy as jnp

    from tpu_pod_exporter.loadgen.parallel import (
        make_1d_mesh, pipeline_forward_fn, reference_pipeline,
    )

    mesh = make_1d_mesh(N, "stage")
    n_micro, mb, width = 2 * N, 4, 8
    fn, w_sharding = pipeline_forward_fn(mesh)
    stage_w = 0.5 * jax.random.normal(
        jax.random.PRNGKey(3), (N, width, width), jnp.float32
    )
    xs = jax.random.normal(jax.random.PRNGKey(4), (n_micro, mb, width), jnp.float32)
    out = fn(jax.device_put(stage_w, w_sharding), xs)
    ref = reference_pipeline(stage_w, xs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)


def test_moe_matches_position_routed_reference():
    import jax
    import jax.numpy as jnp

    from tpu_pod_exporter.loadgen.parallel import (
        make_1d_mesh, moe_forward_fn, reference_moe,
    )

    mesh = make_1d_mesh(N, "expert")
    fn, w_sharding, x_sharding = moe_forward_fn(mesh)
    d = 8
    tokens = N * N * 2  # local count divisible by expert count
    expert_w = 0.5 * jax.random.normal(jax.random.PRNGKey(5), (N, d, d), jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(6), (tokens, d), jnp.float32)
    out = fn(jax.device_put(expert_w, w_sharding), jax.device_put(x, x_sharding))
    ref = reference_moe(expert_w, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)


def test_parallelism_dryrun_finite():
    from tpu_pod_exporter.loadgen.parallel import run_parallelism_dryrun

    results = run_parallelism_dryrun(4)
    assert set(results) == {"ring_attention", "pipeline", "moe"}
    for name, val in results.items():
        assert val == val, f"{name} produced NaN"
