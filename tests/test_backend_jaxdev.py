"""JAX device backend tests — run on whatever JAX exposes locally (CPU
devices in CI; the tunneled TPU chip when present). memory_stats() may be
None/empty/raise off-TPU; the backend must mark HBM unreadable (None) with
a partial error, never crash and never publish a fake zero — the reference
never exports a value it didn't read (main.go:129-132)."""

import pytest

from tests.conftest import require_jax
from tpu_pod_exporter.backend import BackendError
from tpu_pod_exporter.backend.jaxdev import JaxDeviceBackend


@pytest.fixture(autouse=True)
def _needs_jax():
    require_jax()


class TestJaxDeviceBackend:
    def test_sample_any_platform(self):
        backend = JaxDeviceBackend(platform=None)
        sample = backend.sample()
        assert len(sample.chips) >= 1
        for chip in sample.chips:
            # Off-TPU the fields are None (unreadable), on TPU non-negative.
            assert chip.hbm_used_bytes is None or chip.hbm_used_bytes >= 0
            assert chip.hbm_total_bytes is None or chip.hbm_total_bytes >= 0
            assert chip.info.device_ids == (str(chip.info.chip_id),)

    def test_unknown_platform_raises_backend_error(self):
        backend = JaxDeviceBackend(platform="nonexistent_platform")
        with pytest.raises(BackendError):
            backend.sample()


class _StubDevice:
    """Duck-typed jax.Device: just enough surface for sample()."""

    def __init__(self, stats):
        self.id = 0
        self.device_kind = "TPU v5 lite"
        self.coords = (0, 0, 0)
        self._stats = stats

    def memory_stats(self):
        if isinstance(self._stats, Exception):
            raise self._stats
        return self._stats


def _backend_with(stats):
    backend = JaxDeviceBackend(platform=None)
    backend._devices = [_StubDevice(stats)]
    return backend


class TestMemoryStatsDegradation:
    """The live tunnel serves EMPTY memory_stats (HWCHECK.json,
    tests/fixtures/real-trace.jsonl): that must surface as a partial error
    and absent HBM, indistinguishable from neither a crash nor idle-zero."""

    @pytest.mark.parametrize("stats", [None, {}])
    def test_missing_stats_yield_none_hbm_and_partial_error(self, stats):
        sample = _backend_with(stats).sample()
        (chip,) = sample.chips
        assert chip.hbm_used_bytes is None
        assert chip.hbm_total_bytes is None
        assert len(sample.partial_errors) == 1
        assert "memory_stats" in sample.partial_errors[0]

    def test_raising_stats_yield_none_hbm_and_partial_error(self):
        sample = _backend_with(RuntimeError("no stats here")).sample()
        (chip,) = sample.chips
        assert chip.hbm_used_bytes is None
        assert chip.hbm_total_bytes is None
        assert "unavailable" in sample.partial_errors[0]

    def test_real_stats_parse(self):
        sample = _backend_with(
            {"bytes_in_use": 123, "bytes_limit": 1000, "peak_bytes_in_use": 456}
        ).sample()
        (chip,) = sample.chips
        assert chip.hbm_used_bytes == 123.0
        assert chip.hbm_total_bytes == 1000.0
        assert chip.hbm_peak_bytes == 456.0
        assert sample.partial_errors == ()

    def test_collector_publishes_no_hbm_series_for_unreadable_chip(self):
        """End-to-end: an unreadable chip contributes chip_info but NO
        tpu_hbm_* series — absent beats fake-zero."""
        from tpu_pod_exporter.attribution.fake import FakeAttribution
        from tpu_pod_exporter.collector import Collector
        from tpu_pod_exporter.metrics import SnapshotStore

        store = SnapshotStore()
        collector = Collector(_backend_with({}), FakeAttribution(), store)
        collector.poll_once()
        text = store.current().encode().decode()
        assert "tpu_chip_info{" in text
        assert "tpu_hbm_used_bytes{" not in text
        assert "tpu_hbm_total_bytes{" not in text
        assert "tpu_hbm_used_percent{" not in text
