"""JAX device backend tests — run on whatever JAX exposes locally (CPU
devices in CI; the tunneled TPU chip when present). memory_stats() may be
None/raise off-TPU; the backend must degrade to zeroed HBM, never crash."""

import pytest

from tests.conftest import require_jax
from tpu_pod_exporter.backend import BackendError
from tpu_pod_exporter.backend.jaxdev import JaxDeviceBackend


@pytest.fixture(autouse=True)
def _needs_jax():
    require_jax()


class TestJaxDeviceBackend:
    def test_sample_any_platform(self):
        backend = JaxDeviceBackend(platform=None)
        sample = backend.sample()
        assert len(sample.chips) >= 1
        for chip in sample.chips:
            assert chip.hbm_used_bytes >= 0
            assert chip.hbm_total_bytes >= 0
            assert chip.info.device_ids == (str(chip.info.chip_id),)

    def test_unknown_platform_raises_backend_error(self):
        backend = JaxDeviceBackend(platform="nonexistent_platform")
        with pytest.raises(BackendError):
            backend.sample()
