"""exporter-lint: per-rule fixtures, disable/baseline mechanics, and the
real-tree self-check (ISSUE 5 acceptance: seeding a lock-scoped
``json.dumps`` or an unregistered metric name into ``collector.py`` must
fail the gate naming the rule, file, and line)."""

import json
import shutil
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

from tpu_pod_exporter.analysis import (
    Diagnostic,
    LintContext,
    lint_package,
    lint_source,
    parse_disables,
)
from tpu_pod_exporter.analysis.engine import (
    SchemaRegistry,
    apply_baseline,
    baseline_document,
    build_context,
    build_registry,
    load_baseline,
)

REPO_ROOT = Path(__file__).resolve().parent.parent


def ctx_for(src_path: str = "tpu_pod_exporter/mod.py") -> LintContext:
    """Minimal context: two registered gauges + one histogram family."""
    registry = SchemaRegistry(
        schema_names={"TPU_HBM_USED_BYTES", "ALL_SPECS", "MetricSpec"},
        metric_names={
            "tpu_hbm_used_bytes", "tpu_exporter_up",
            "tpu_exporter_poll_phase_duration_seconds",
            "tpu_exporter_poll_phase_duration_seconds_bucket",
        },
    )
    return LintContext(registry=registry)


def findings(src: str, path: str = "tpu_pod_exporter/mod.py") -> list[Diagnostic]:
    return lint_source(textwrap.dedent(src), path, ctx_for())


def rules_of(ds: list[Diagnostic]) -> set[str]:
    return {d.rule for d in ds}


# ------------------------------------------------------------------ lock-io


class TestLockIo:
    def test_json_dumps_under_lock(self):
        ds = findings("""
            import json
            def f(self):
                with self._lock:
                    return json.dumps({"a": 1})
        """)
        assert rules_of(ds) == {"lock-io"}
        assert ds[0].line == 5
        assert "json.dumps" in ds[0].message

    def test_logging_fsync_gzip_sendall_under_lock(self):
        ds = findings("""
            import gzip, os
            def f(self, sock):
                with STATE_LOCK:
                    log.warning("x")
                    os.fsync(self.fd)
                    gzip.compress(b"x")
                    sock.sendall(b"x")
        """)
        assert [d.rule for d in ds] == ["lock-io"] * 4

    def test_clean_copy_under_lock(self):
        ds = findings("""
            def f(self):
                with self._lock:
                    snap = dict(self._data)
                return snap
        """)
        assert ds == []

    def test_serialize_outside_lock_ok(self):
        ds = findings("""
            import json
            def f(self):
                with self._lock:
                    snap = dict(self._data)
                return json.dumps(snap)
        """)
        assert ds == []

    def test_nested_def_under_lock_not_flagged(self):
        # A callback defined under the lock runs after release.
        ds = findings("""
            import json
            def f(self):
                with self._lock:
                    def cb():
                        return json.dumps({})
                    self._cb = cb
        """)
        assert ds == []

    def test_non_lock_with_ignored(self):
        ds = findings("""
            import json
            def f(self, path):
                with open(path) as fh:
                    return json.dumps({"a": 1})
        """)
        assert ds == []


# -------------------------------------------------------------- metric-name


class TestMetricName:
    def test_unregistered_literal(self):
        ds = findings("""
            def f(counters):
                counters.inc("tpu_bogus_total", ())
        """)
        assert rules_of(ds) == {"metric-name"}
        assert "tpu_bogus_total" in ds[0].message

    def test_registered_literal_ok(self):
        ds = findings("""
            def f(counters):
                counters.inc("tpu_hbm_used_bytes", ())
        """)
        assert ds == []

    def test_histogram_child_names_ok(self):
        ds = findings("""
            NAME = "tpu_exporter_poll_phase_duration_seconds_bucket"
        """)
        assert ds == []

    def test_docstring_mention_ok(self):
        ds = findings('''
            def f():
                """Feeds tpu_totally_invented_bytes downstream."""
                return 1
        ''')
        assert ds == []

    def test_unknown_schema_attr(self):
        ds = findings("""
            from tpu_pod_exporter.metrics import schema
            def f(b):
                b.add(schema.TPU_TYPO_SPEC, 1.0)
        """)
        assert rules_of(ds) == {"metric-name"}
        assert "TPU_TYPO_SPEC" in ds[0].message

    def test_known_schema_attr_ok(self):
        ds = findings("""
            from tpu_pod_exporter.metrics import schema
            def f(b):
                b.add(schema.TPU_HBM_USED_BYTES, 1.0)
        """)
        assert ds == []

    def test_inline_spec_outside_schema(self):
        ds = findings("""
            from tpu_pod_exporter.metrics.registry import MetricSpec
            EXTRA = MetricSpec(name="tpu_hbm_used_bytes", help="h")
        """)
        assert "metric-name" in rules_of(ds)

    def test_pb2_module_string_ok(self):
        ds = findings("""
            MOD = "tpu_metric_service_pb2"
        """)
        assert ds == []


# --------------------------------------------------------------- wall-clock


class TestWallClock:
    def test_time_time_in_collector(self):
        ds = findings("""
            import time
            def f():
                return time.time()
        """, path="tpu_pod_exporter/collector.py")
        assert rules_of(ds) == {"wall-clock"}

    def test_datetime_now_in_history(self):
        ds = findings("""
            from datetime import datetime
            def f():
                return datetime.now()
        """, path="tpu_pod_exporter/history.py")
        assert rules_of(ds) == {"wall-clock"}

    def test_monotonic_ok(self):
        ds = findings("""
            import time
            def f():
                return time.monotonic()
        """, path="tpu_pod_exporter/collector.py")
        assert ds == []

    def test_default_arg_reference_ok(self):
        # ``wallclock=time.time`` (no call) is the injection idiom.
        ds = findings("""
            import time
            def f(wallclock=time.time):
                return wallclock()
        """, path="tpu_pod_exporter/supervisor.py")
        assert ds == []

    def test_other_modules_unrestricted(self):
        ds = findings("""
            import time
            def f():
                return time.time()
        """, path="tpu_pod_exporter/server.py")
        assert ds == []


# ------------------------------------------------------------- join-timeout


class TestJoinTimeout:
    def test_zero_arg_join(self):
        ds = findings("""
            def f(t):
                t.join()
        """)
        assert rules_of(ds) == {"join-timeout"}

    def test_none_timeout(self):
        ds = findings("""
            def f(t):
                t.join(timeout=None)
        """)
        assert rules_of(ds) == {"join-timeout"}

    def test_timeout_ok(self):
        ds = findings("""
            def f(t, timeout):
                t.join(timeout)
                t.join(timeout=5.0)
        """)
        assert ds == []

    def test_str_join_ok(self):
        ds = findings("""
            def f(parts):
                return ",".join(parts)
        """)
        assert ds == []


# --------------------------------------------------------- thread-discipline


class TestThreadDiscipline:
    def test_unnamed_thread(self):
        ds = findings("""
            import threading
            def f():
                threading.Thread(target=f, daemon=True).start()
        """)
        assert rules_of(ds) == {"thread-discipline"}
        assert "name=" in ds[0].message

    def test_non_daemon_thread(self):
        ds = findings("""
            import threading
            def f():
                threading.Thread(target=f, name="tpu-x").start()
        """)
        assert rules_of(ds) == {"thread-discipline"}
        assert "daemon" in ds[0].message

    def test_named_daemon_ok(self):
        ds = findings("""
            import threading
            def f():
                threading.Thread(target=f, name="tpu-x", daemon=True).start()
        """)
        assert ds == []


# -------------------------------------------------------------- bare-except


class TestBareExcept:
    def test_bare(self):
        ds = findings("""
            def f():
                try:
                    g()
                except:
                    pass
        """)
        assert rules_of(ds) == {"bare-except"}

    def test_base_exception_swallowed(self):
        ds = findings("""
            def f():
                try:
                    g()
                except BaseException:
                    pass
        """)
        assert rules_of(ds) == {"bare-except"}

    def test_base_exception_reraised_ok(self):
        ds = findings("""
            def f():
                try:
                    g()
                except BaseException:
                    note()
                    raise
        """)
        assert ds == []

    def test_plain_exception_ok(self):
        ds = findings("""
            def f():
                try:
                    g()
                except Exception:
                    pass
        """)
        assert ds == []


# --------------------------------------------------------------- debug-gate


class TestDebugGate:
    def test_ungated_route(self):
        ds = findings("""
            def route(self, path):
                if path == "/debug/secrets":
                    return self.serve()
        """)
        assert rules_of(ds) == {"debug-gate"}

    def test_gated_route_ok(self):
        ds = findings("""
            def route(self, path):
                if path.startswith("/debug/") and not debug_client_allowed(
                    self.ip, self.addr
                ):
                    return self.deny()
                if path == "/debug/vars":
                    return self.serve()
        """)
        assert ds == []

    def test_log_mention_ok(self):
        ds = findings("""
            def f():
                log.warning("see GET /debug/trace for the profile")
        """)
        assert ds == []


# ------------------------------------------------------------ unused-import


class TestUnusedImport:
    def test_unused(self):
        ds = findings("""
            import os
            import sys
            print(sys.argv)
        """)
        assert rules_of(ds) == {"unused-import"}
        assert "'os'" in ds[0].message
        # Diagnostics must carry the severity their Rule declares —
        # unused-import/flag-read/flag-doc are the warning class.
        assert ds[0].severity == "warning"

    def test_all_used_ok(self):
        ds = findings("""
            import os
            print(os.getpid())
        """)
        assert ds == []

    def test_future_and_lazy_imports_ok(self):
        ds = findings("""
            from __future__ import annotations
            def f():
                import gzip
                return gzip
        """)
        assert ds == []


# ------------------------------------------------ disable comments


class TestDisable:
    def test_parse(self):
        got = parse_disables(
            "x = 1  # lint: disable=lock-io(lazy cache),wall-clock(stamp)"
        )
        assert got == {"lock-io": "lazy cache", "wall-clock": "stamp"}

    def test_reason_mandatory(self):
        assert parse_disables("x  # lint: disable=lock-io") == {}
        assert parse_disables("x  # lint: disable=lock-io()") == {}

    def test_reason_may_contain_parentheses(self):
        got = parse_disables(
            "x  # lint: disable=lock-io(lazy cache (cold path only))"
        )
        assert got == {"lock-io": "lazy cache (cold path only)"}

    def test_suppresses_on_line(self):
        ds = findings("""
            import json
            def f(self):
                with self._lock:
                    return json.dumps({})  # lint: disable=lock-io(test reason)
        """)
        assert ds == []

    def test_wrong_rule_does_not_suppress(self):
        ds = findings("""
            import json
            def f(self):
                with self._lock:
                    return json.dumps({})  # lint: disable=wall-clock(nope)
        """)
        assert rules_of(ds) == {"lock-io"}


# ---------------------------------------------------------- schema registry


class TestRegistryExtraction:
    def test_real_schema(self):
        src = (REPO_ROOT / "tpu_pod_exporter/metrics/schema.py").read_text()
        reg = build_registry(src)
        assert "tpu_hbm_used_bytes" in reg.metric_names
        assert "tpu_exporter_up" in reg.metric_names
        # Histogram children derive from HistogramSpec declarations.
        assert "tpu_exporter_poll_phase_duration_seconds_bucket" in reg.metric_names
        assert "tpu_aggregator_round_seconds_sum" in reg.metric_names
        assert "TPU_HBM_USED_BYTES" in reg.schema_names
        assert "ALL_SPECS" in reg.schema_names
        assert "hbm_used_percent" in reg.schema_names


# ----------------------------------------------------------------- baseline


class TestBaseline:
    def test_roundtrip_and_multiset(self, tmp_path):
        root = tmp_path
        mod = root / "tpu_pod_exporter" / "mod.py"
        mod.parent.mkdir(parents=True)
        mod.write_text("def f(t):\n    t.join()\n")
        d = Diagnostic("join-timeout", "error",
                       "tpu_pod_exporter/mod.py", 2, "m")
        doc = baseline_document([d], str(root))
        path = root / "baseline.json"
        path.write_text(json.dumps(doc))
        entries = load_baseline(str(path))
        fresh, suppressed = apply_baseline([d], entries, str(root))
        assert fresh == [] and suppressed == 1
        # Multiset: a second live instance of the same fingerprint is NEW.
        fresh, suppressed = apply_baseline([d, d], entries, str(root))
        assert len(fresh) == 1 and suppressed == 1

    def test_committed_baseline_loads(self):
        entries = load_baseline(str(REPO_ROOT / ".exporter-lint-baseline.json"))
        assert isinstance(entries, list)


# ------------------------------------------------------ real-tree self-check


class TestRealTree:
    def test_tree_clean_with_committed_baseline(self):
        findings = lint_package(str(REPO_ROOT))
        entries = load_baseline(str(REPO_ROOT / ".exporter-lint-baseline.json"))
        fresh, _ = apply_baseline(findings, entries, str(REPO_ROOT))
        assert fresh == [], "\n".join(d.format() for d in fresh)

    def test_real_context_flags_extracted(self):
        ctx = build_context(str(REPO_ROOT))
        names = {n for n, _ in ctx.config_fields}
        assert {"interval_s", "state_dir", "trace", "debug_addr"} <= names
        assert ctx.docs_text  # README + RUNBOOK loaded


# --------------------------------------- acceptance: seeded violations


@pytest.fixture()
def seeded_tree(tmp_path):
    """A copy of the real package with violations seeded into collector.py
    (the ISSUE 5 acceptance shape)."""
    pkg = tmp_path / "tpu_pod_exporter"
    shutil.copytree(
        REPO_ROOT / "tpu_pod_exporter", pkg,
        ignore=shutil.ignore_patterns("__pycache__"),
    )
    for doc in ("README.md",):
        shutil.copy(REPO_ROOT / doc, tmp_path / doc)
    (tmp_path / "deploy").mkdir()
    shutil.copy(REPO_ROOT / "deploy/RUNBOOK.md", tmp_path / "deploy/RUNBOOK.md")
    target = pkg / "collector.py"
    base_lines = target.read_text().count("\n")
    target.write_text(target.read_text() + textwrap.dedent("""

        def _seeded(self):
            import json
            with self._restart_lock:
                body = json.dumps({"seeded": True})
            self._counters.inc("tpu_exporter_seeded_bogus_total", ())
            return body
    """))
    return tmp_path, base_lines


class TestSeededAcceptance:
    def test_lock_scoped_dumps_and_bogus_metric_fail_the_gate(self, seeded_tree):
        root, base_lines = seeded_tree
        findings = lint_package(str(root))
        by_rule = {d.rule: d for d in findings}
        assert "lock-io" in by_rule and "metric-name" in by_rule
        for d in (by_rule["lock-io"], by_rule["metric-name"]):
            # Names the file and a line inside the seeded block.
            assert d.path == "tpu_pod_exporter/collector.py"
            assert d.line > base_lines
        assert "json.dumps" in by_rule["lock-io"].message
        assert "tpu_exporter_seeded_bogus_total" in by_rule["metric-name"].message

    def test_cli_exits_nonzero_naming_rule_file_line(self, seeded_tree):
        root, _ = seeded_tree
        proc = subprocess.run(
            [sys.executable, "-m", "tpu_pod_exporter.analysis",
             "--root", str(root), "--no-baseline"],
            capture_output=True, text=True, cwd=str(REPO_ROOT),
        )
        assert proc.returncode == 1
        assert "lock-io" in proc.stdout and "metric-name" in proc.stdout
        assert "tpu_pod_exporter/collector.py:" in proc.stdout


class TestCli:
    def test_clean_tree_exits_zero(self):
        proc = subprocess.run(
            [sys.executable, "-m", "tpu_pod_exporter.analysis"],
            capture_output=True, text=True, cwd=str(REPO_ROOT),
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "clean" in proc.stdout

    def test_json_format(self):
        proc = subprocess.run(
            [sys.executable, "-m", "tpu_pod_exporter.analysis",
             "--format", "json"],
            capture_output=True, text=True, cwd=str(REPO_ROOT),
        )
        assert proc.returncode == 0
        doc = json.loads(proc.stdout)
        assert doc["findings"] == []

    def test_sarif_format_clean_tree(self):
        proc = subprocess.run(
            [sys.executable, "-m", "tpu_pod_exporter.analysis",
             "--format", "sarif"],
            capture_output=True, text=True, cwd=str(REPO_ROOT),
        )
        assert proc.returncode == 0
        doc = json.loads(proc.stdout)
        assert doc["version"] == "2.1.0"
        run = doc["runs"][0]
        assert run["results"] == []
        # Rule metadata rides the driver so annotations resolve ids.
        ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
        for rule in ("lock-io", "metric-name", "lock-order",
                     "lock-ownership", "lock-io-chain"):
            assert rule in ids

    def test_sarif_from_findings_list(self):
        """to_sarif renders the SAME findings list the text/JSON paths
        consume: severity maps to SARIF level, location carries the
        repo-relative path + 1-based line."""
        from tpu_pod_exporter.analysis.diagnostics import (
            ERROR, WARNING, Diagnostic, to_sarif,
        )
        from tpu_pod_exporter.analysis.rules import ALL_RULES
        findings = [
            Diagnostic("lock-io", ERROR,
                       "tpu_pod_exporter/collector.py", 42, "bad"),
            Diagnostic("flag-doc", WARNING,
                       "tpu_pod_exporter/config.py", 7, "undocumented"),
        ]
        doc = to_sarif(findings, ALL_RULES)
        results = doc["runs"][0]["results"]
        assert [r["level"] for r in results] == ["error", "warning"]
        loc = results[0]["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"] == \
            "tpu_pod_exporter/collector.py"
        assert loc["region"]["startLine"] == 42
        assert results[0]["ruleId"] == "lock-io"

    def test_sarif_seeded_tree_carries_findings(self, seeded_tree):
        root, _ = seeded_tree
        proc = subprocess.run(
            [sys.executable, "-m", "tpu_pod_exporter.analysis",
             "--root", str(root), "--no-baseline", "--format", "sarif"],
            capture_output=True, text=True, cwd=str(REPO_ROOT),
        )
        assert proc.returncode == 1
        doc = json.loads(proc.stdout)
        rules_hit = {r["ruleId"] for r in doc["runs"][0]["results"]}
        assert {"lock-io", "metric-name"} <= rules_hit

    def test_list_rules(self):
        proc = subprocess.run(
            [sys.executable, "-m", "tpu_pod_exporter.analysis",
             "--list-rules"],
            capture_output=True, text=True, cwd=str(REPO_ROOT),
        )
        assert proc.returncode == 0
        for rule in ("lock-io", "metric-name", "wall-clock", "join-timeout",
                     "thread-discipline", "bare-except", "debug-gate",
                     "unused-import", "flag-read", "flag-doc"):
            assert rule in proc.stdout

    def test_demo_catches_seeded_violations(self):
        proc = subprocess.run(
            [sys.executable, "-m", "tpu_pod_exporter.analysis", "--demo"],
            capture_output=True, text=True, cwd=str(REPO_ROOT),
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "PASS" in proc.stdout
