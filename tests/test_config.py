"""Config/flag system tests (SURVEY.md §5)."""

from tpu_pod_exporter.config import ExporterConfig


class TestDefaults:
    def test_defaults(self):
        cfg = ExporterConfig.from_args([])
        assert cfg.port == 8000
        assert cfg.interval_s == 1.0
        assert cfg.backend == "auto"
        assert cfg.resource_name == "google.com/tpu"


class TestFlags:
    def test_flags_override(self):
        cfg = ExporterConfig.from_args(
            ["--port", "9100", "--interval-s", "0.5", "--backend", "fake",
             "--fake-chips", "4", "--accelerator", "v5p-64"]
        )
        assert cfg.port == 9100
        assert cfg.interval_s == 0.5
        assert cfg.backend == "fake"
        assert cfg.fake_chips == 4
        assert cfg.accelerator == "v5p-64"


class TestEnvFallback:
    def test_env_used_when_no_flag(self, monkeypatch):
        monkeypatch.setenv("TPE_PORT", "9200")
        monkeypatch.setenv("TPE_BACKEND", "fake")
        monkeypatch.setenv("TPE_INTERVAL_S", "2.5")
        cfg = ExporterConfig.from_args([])
        assert cfg.port == 9200
        assert cfg.backend == "fake"
        assert cfg.interval_s == 2.5

    def test_flag_beats_env(self, monkeypatch):
        monkeypatch.setenv("TPE_PORT", "9200")
        cfg = ExporterConfig.from_args(["--port", "9300"])
        assert cfg.port == 9300
