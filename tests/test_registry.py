"""Unit tests for the snapshot registry (SURVEY.md §4.1)."""

import math

import pytest
from prometheus_client.parser import text_string_to_metric_families

from tpu_pod_exporter.metrics.registry import (
    COUNTER,
    CounterStore,
    HistogramSpec,
    HistogramStore,
    MetricSpec,
    SnapshotBuilder,
    SnapshotStore,
    escape_label_value,
    format_value,
)

G = MetricSpec(name="test_gauge", help="a gauge", label_names=("a", "b"))
PLAIN = MetricSpec(name="test_plain", help="no labels")


class TestMetricSpec:
    def test_valid(self):
        MetricSpec(name="tpu_hbm_used_bytes", help="h", label_names=("pod",))

    @pytest.mark.parametrize("bad", ["", "1abc", "a-b", "a b", "abé"])
    def test_invalid_name(self, bad):
        with pytest.raises(ValueError):
            MetricSpec(name=bad, help="h")

    @pytest.mark.parametrize("bad", ["", "__reserved", "1a", "a-b"])
    def test_invalid_label(self, bad):
        with pytest.raises(ValueError):
            MetricSpec(name="ok", help="h", label_names=(bad,))

    def test_duplicate_labels(self):
        with pytest.raises(ValueError):
            MetricSpec(name="ok", help="h", label_names=("x", "x"))

    def test_bad_type(self):
        with pytest.raises(ValueError):
            MetricSpec(name="ok", help="h", type="summary")


class TestFormatting:
    def test_format_value(self):
        assert format_value(0.0) == "0"
        assert format_value(42.0) == "42"
        assert format_value(1.5) == "1.5"
        assert format_value(math.nan) == "NaN"
        assert format_value(math.inf) == "+Inf"
        assert format_value(-math.inf) == "-Inf"
        assert format_value(2**60) == str(float(2**60)) or "e" in format_value(2**60)

    def test_escape(self):
        assert escape_label_value('a"b\\c\nd') == 'a\\"b\\\\c\\nd'


class TestSnapshotBuilder:
    def test_roundtrip_via_prometheus_parser(self):
        b = SnapshotBuilder()
        b.add(G, 1.25, {"a": "x", "b": 'quo"te'})
        b.add(G, 2.0, ("y", "line\nbreak"))
        b.add(PLAIN, 7)
        snap = b.build()
        text = snap.encode().decode()
        fams = {f.name: f for f in text_string_to_metric_families(text)}
        assert fams["test_gauge"].type == "gauge"
        samples = {tuple(sorted(s.labels.items())): s.value for s in fams["test_gauge"].samples}
        assert samples[(("a", "x"), ("b", 'quo"te'))] == 1.25
        assert samples[(("a", "y"), ("b", "line\nbreak"))] == 2.0
        assert fams["test_plain"].samples[0].value == 7

    def test_counter_type_rendered(self):
        c = MetricSpec(name="test_total", help="h", type=COUNTER)
        b = SnapshotBuilder()
        b.add(c, 3)
        text = b.build().encode().decode()
        assert "# TYPE test_total counter" in text

    def test_duplicate_label_set_last_wins(self):
        b = SnapshotBuilder()
        b.add(G, 1, ("x", "y"))
        b.add(G, 2, ("x", "y"))
        assert b.build().value("test_gauge", ("x", "y")) == 2

    def test_label_arity_mismatch(self):
        b = SnapshotBuilder()
        with pytest.raises(ValueError):
            b.add(G, 1, ("only-one",))

    def test_unknown_label_rejected(self):
        b = SnapshotBuilder()
        with pytest.raises(ValueError):
            b.add(G, 1, {"a": "x", "b": "y", "zzz": "?"})

    def test_missing_label_rejected(self):
        b = SnapshotBuilder()
        with pytest.raises(ValueError):
            b.add(G, 1, {"a": "x"})

    def test_conflicting_redeclare(self):
        b = SnapshotBuilder()
        b.add(G, 1, ("x", "y"))
        other = MetricSpec(name="test_gauge", help="different", label_names=("a", "b"))
        with pytest.raises(ValueError):
            b.declare(other)

    def test_declared_family_appears_without_samples(self):
        b = SnapshotBuilder()
        b.declare(G)
        text = b.build().encode().decode()
        assert "# HELP test_gauge" in text
        assert b.build().series_count == 0

    def test_series_count(self):
        b = SnapshotBuilder()
        b.add(G, 1, ("x", "y"))
        b.add(G, 1, ("x", "z"))
        b.add(PLAIN, 1)
        assert b.build().series_count == 3

    def test_encode_cached(self):
        b = SnapshotBuilder()
        b.add(PLAIN, 1)
        snap = b.build()
        assert snap.encode() is snap.encode()


class TestSnapshotStore:
    def test_swap_and_current(self):
        store = SnapshotStore()
        assert store.current().series_count == 0
        b = SnapshotBuilder()
        b.add(PLAIN, 5)
        snap = b.build()
        store.swap(snap)
        assert store.current() is snap
        # swap pre-renders so the scrape path never encodes
        assert snap._text is not None


class TestSnapshotStoreConcurrency:
    def test_swap_current_race(self):
        """All cross-thread state is one locked reference (SURVEY.md §5 race
        strategy): hammer swap() and current() from threads; every observed
        snapshot must be complete and internally consistent."""
        import threading

        store = SnapshotStore()
        stop = threading.Event()
        failures: list[str] = []

        def writer():
            i = 0
            while not stop.is_set():
                i += 1
                b = SnapshotBuilder()
                # generation encoded in both value and series: a torn
                # snapshot would disagree with itself
                b.add(G, i, (str(i), "x"))
                b.add(PLAIN, i)
                store.swap(b.build())

        def reader():
            while not stop.is_set():
                snap = store.current()
                text = snap.encode()
                if snap.series_count == 0:
                    continue
                plain = snap.value("test_plain")
                gen = int(plain)
                if snap.value("test_gauge", (str(gen), "x")) != gen:
                    failures.append(f"torn snapshot at gen {gen}")
                if text != snap.encode():  # cached render must be stable
                    failures.append("encode not stable")

        threads = [threading.Thread(target=writer)] + [
            threading.Thread(target=reader) for _ in range(3)
        ]
        for t in threads:
            t.start()
        import time

        time.sleep(0.5)
        stop.set()
        for t in threads:
            t.join(timeout=5)
        assert not failures, failures[:5]


class TestCounterStore:
    def test_inc(self):
        c = CounterStore()
        assert c.inc("n", ("a",)) == 1
        assert c.inc("n", ("a",), 2.5) == 3.5
        assert c.get("n", ("a",)) == 3.5
        assert c.get("n", ("other",)) == 0

    def test_negative_delta_ignored(self):
        c = CounterStore()
        c.inc("n", (), 5)
        assert c.inc("n", (), -3) == 5

    def test_observe_total_monotonic(self):
        c = CounterStore()
        assert c.observe_total("n", (), 100) == 100
        assert c.observe_total("n", (), 150) == 150
        # device counter reset: exported value holds, then resumes
        assert c.observe_total("n", (), 10) == 150
        assert c.observe_total("n", (), 60) == 200

    def test_prune(self):
        c = CounterStore()
        c.inc("n", ("a",))
        c.inc("n", ("b",))
        assert c.prune({("n", ("a",))}) == 1
        assert c.get("n", ("b",)) == 0
        assert c.get("n", ("a",)) == 1

    def test_items_for(self):
        c = CounterStore()
        c.inc("n", ("a",))
        c.inc("m", ("b",))
        assert c.items_for("n") == [(("a",), 1.0)]


HIST = HistogramSpec(
    name="test_duration_seconds",
    help="a histogram",
    buckets=(0.1, 1.0, 10.0),
    label_names=("phase",),
)


class TestHistogramSpec:
    def test_bad_buckets(self):
        with pytest.raises(ValueError):
            HistogramSpec(name="h", help="h", buckets=())
        with pytest.raises(ValueError):
            HistogramSpec(name="h", help="h", buckets=(1.0, 1.0))
        with pytest.raises(ValueError):
            HistogramSpec(name="h", help="h", buckets=(2.0, 1.0))
        with pytest.raises(ValueError):
            HistogramSpec(name="h", help="h", buckets=(1.0, math.inf))

    def test_le_label_reserved(self):
        with pytest.raises(ValueError):
            HistogramSpec(name="h", help="h", buckets=(1.0,), label_names=("le",))

    def test_le_values_include_inf(self):
        assert HIST.le_values == ("0.1", "1", "10", "+Inf")


def _render(store):
    b = SnapshotBuilder()
    store.emit(b)
    return b.build(timestamp=1.0).encode().decode()


class TestHistogramStore:
    def test_observe_and_emit_exact(self):
        s = HistogramStore(HIST)
        for v in (0.05, 0.1, 5.0, 100.0):  # 0.1 lands IN le="0.1" (le = <=)
            s.observe(v, ("total",))
        text = _render(s)
        want = [
            'test_duration_seconds_bucket{phase="total",le="0.1"} 2',
            'test_duration_seconds_bucket{phase="total",le="1"} 2',
            'test_duration_seconds_bucket{phase="total",le="10"} 3',
            'test_duration_seconds_bucket{phase="total",le="+Inf"} 4',
            'test_duration_seconds_count{phase="total"} 4',
            'test_duration_seconds_sum{phase="total"} 105.15',
        ]
        body = [l for l in text.splitlines() if not l.startswith("#")]
        assert body == want
        assert "# TYPE test_duration_seconds histogram" in text
        # The internal raw-lines family name must never leak into output.
        assert "_lines" not in text

    def test_openmetrics_strict_parser_and_per_labelset_grouping(self):
        from prometheus_client.openmetrics.parser import (
            text_string_to_metric_families as om_parse,
        )

        s = HistogramStore(HIST)
        s.observe(0.5, ("a",))
        s.observe(2.0, ("b",))
        s.observe(0.01, ("a",))
        b = SnapshotBuilder()
        s.emit(b)
        om = b.build(timestamp=1.0).encode_openmetrics().decode()
        fams = {f.name: f for f in om_parse(om)}
        fam = fams["test_duration_seconds"]
        assert fam.type == "histogram"
        by_name = {}
        for sample in fam.samples:
            by_name.setdefault(sample.name, []).append(sample)
        assert len(by_name["test_duration_seconds_bucket"]) == 8  # 2 sets x 4
        a_inf = [
            x for x in by_name["test_duration_seconds_bucket"]
            if x.labels == {"phase": "a", "le": "+Inf"}
        ]
        assert a_inf[0].value == 2.0

    def test_cumulative_across_emits(self):
        s = HistogramStore(HIST)
        s.observe(0.5)
        _render(s)
        s.observe(0.6)
        text = _render(s)
        assert "test_duration_seconds_count 2" in text

    def test_unlabeled_histogram_renders_bare_names(self):
        s = HistogramStore(HistogramSpec(name="h2", help="h", buckets=(1.0,)))
        s.observe(0.5)
        text = _render(s)
        assert 'h2_bucket{le="1"} 1' in text
        assert "h2_count 1" in text
        assert "h2_sum 0.5" in text

    def test_empty_store_emits_headers_only(self):
        text = _render(HistogramStore(HIST))
        assert "# TYPE test_duration_seconds histogram" in text
        assert "_bucket" not in text

    def test_thread_hammer_loses_no_observations(self):
        import threading

        s = HistogramStore(HIST)
        n_threads, per = 8, 1000

        def work():
            for i in range(per):
                s.observe(i % 20, ("t",))

        threads = [threading.Thread(target=work) for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        text = _render(s)
        assert f'test_duration_seconds_count{{phase="t"}} {n_threads * per}' in text

    def test_identical_output_with_and_without_prefix_cache(self):
        from tpu_pod_exporter.metrics.registry import PrefixCache

        s = HistogramStore(HIST)
        for v in (0.05, 0.7, 3.0, 50.0):
            s.observe(v, ("x",))
        cache = PrefixCache()
        b1 = SnapshotBuilder(prefix_cache=cache)
        s.emit(b1)
        cached_text = b1.build(timestamp=1.0).encode()
        b2 = SnapshotBuilder()
        s.emit(b2)
        plain_text = b2.build(timestamp=1.0).encode()
        assert cached_text == plain_text
        # Second emit through the same cache (layout fast path) agrees too.
        b3 = SnapshotBuilder(prefix_cache=cache)
        s.emit(b3)
        assert b3.build(timestamp=1.0).encode() == plain_text
