"""Packaging contract: every console script in pyproject.toml must resolve
to an importable callable, and the pinned deps must cover the vendored
protobuf minis' runtime (VERDICT r1 weak #5)."""

import importlib
import tomllib
from pathlib import Path

PYPROJECT = Path(__file__).resolve().parent.parent / "pyproject.toml"


def _cfg():
    with open(PYPROJECT, "rb") as f:
        return tomllib.load(f)


def test_console_script_targets_importable():
    for name, target in _cfg()["project"]["scripts"].items():
        mod, _, attr = target.partition(":")
        fn = getattr(importlib.import_module(mod), attr)
        assert callable(fn), f"{name} -> {target} is not callable"


def test_version_attr_matches_dynamic_source():
    cfg = _cfg()
    attr = cfg["tool"]["setuptools"]["dynamic"]["version"]["attr"]
    mod, _, name = attr.rpartition(".")
    assert getattr(importlib.import_module(mod), name)


def test_runtime_deps_are_pinned_ranges():
    for dep in _cfg()["project"]["dependencies"]:
        assert any(op in dep for op in ("<", "==", "~=")), (
            f"unbounded dependency pin: {dep!r}"
        )
