"""Remote-write egress: WAL-buffered push shipping (tpu_pod_exporter.egress).

The suite covers the acceptance story in-process (the subprocess version
is ``make egress-demo``): the vendored snappy/protobuf codecs round-trip;
the durable send buffer survives restarts, torn writes, and random
corruption without ever re-delivering an acked batch (the seeded fuzz
mirrors ``test_persist``'s torn-write pattern); the shipper is delta-aware
with a breaker-gated sender where 5xx/429 retry, other 4xx poison-skip,
and a receiver outage drains with zero loss and no duplicates on
recovery; and the egress phase never leaks into publish/total timings.
"""

import json
import os
import random
import threading
import time

import pytest

from tpu_pod_exporter.attribution.fake import FakeAttribution
from tpu_pod_exporter.backend.fake import FakeBackend
from tpu_pod_exporter.chaos import ChaosReceiver, parse_chaos_spec
from tpu_pod_exporter.collector import Collector
from tpu_pod_exporter.egress import (
    RemoteWriteShipper,
    aggregator_egress_metrics,
    egress_dir_summary,
    encode_write_request,
    exporter_egress_metrics,
    frame_batch,
    parse_batch,
    parse_write_request,
    snappy_compress,
    snappy_decompress,
)
from tpu_pod_exporter.metrics import SnapshotStore
from tpu_pod_exporter.persist import MAGIC, WalBuffer
from tpu_pod_exporter.supervisor import CircuitBreaker


def wait_for(predicate, timeout=10.0, interval=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


# ---------------------------------------------------------------- the codecs


class TestSnappy:
    @pytest.mark.parametrize("data", [
        b"",
        b"x",
        b"hello world",
        b"abcd" * 5000,                      # highly compressible
        bytes(range(256)) * 300,             # mildly compressible
        os.urandom(100_000),                 # incompressible
        b"a" * 70_000,                       # one long run, >64K literals
    ])
    def test_roundtrip(self, data):
        assert snappy_decompress(snappy_compress(data)) == data

    def test_compresses_repetitive_input(self):
        data = b"tpu_hbm_used_bytes" * 2000
        assert len(snappy_compress(data)) < len(data) / 5

    def test_decoder_handles_copy_elements(self):
        # 2-byte-offset copy built by the encoder itself.
        out = snappy_compress(b"0123456789" * 20)
        assert snappy_decompress(out) == b"0123456789" * 20

    def test_decoder_rejects_garbage(self):
        with pytest.raises(ValueError):
            snappy_decompress(b"\xff\xff\xff\xff\xff")
        with pytest.raises(ValueError):
            # valid preamble, truncated literal
            snappy_decompress(b"\x0a\xfc")

    def test_decoder_rejects_bad_copy_offset(self):
        # preamble len=4, copy-1 with offset 0
        with pytest.raises(ValueError):
            snappy_decompress(b"\x04" + bytes([0x01, 0x00]))


class TestRemoteWriteProto:
    def test_roundtrip(self):
        series = [
            ([("__name__", "tpu_hbm_used_bytes"), ("chip_id", "3"),
              ("host", "h0")],
             [(1234.5, 1_700_000_000_000)]),
            ([("__name__", "tpu_exporter_up")],
             [(1.0, 1_700_000_000_000), (0.0, 1_700_000_001_000)]),
        ]
        out = parse_write_request(encode_write_request(series))
        assert out[0][0] == {"__name__": "tpu_hbm_used_bytes",
                             "chip_id": "3", "host": "h0"}
        assert out[0][1] == [(1234.5, 1_700_000_000_000)]
        assert out[1][1] == [(1.0, 1_700_000_000_000),
                             (0.0, 1_700_000_001_000)]

    def test_labels_sorted_on_wire(self):
        # remote-write requires lexically sorted labels; feed them reversed
        series = [([("zebra", "1"), ("__name__", "tpu_exporter_up")],
                   [(1.0, 1)])]
        encoded = encode_write_request(series)
        # __name__ must appear before zebra in the byte stream
        assert encoded.index(b"__name__") < encoded.index(b"zebra")

    def test_batch_frame_roundtrip(self):
        proto = encode_write_request(
            [([("__name__", "tpu_exporter_up")], [(1.0, 5)])]
        )
        head, body = parse_batch(frame_batch(7, 123.5, "delta", 1, proto))
        assert head == {"seq": 7, "wall": 123.5, "kind": "delta",
                        "samples": 1, "mono": 0.0}
        assert body == proto

    def test_parse_batch_rejects_foreign(self):
        with pytest.raises(ValueError):
            parse_batch(b"S-not-a-batch")

    def test_truncated_sample_raises_valueerror_not_struct_error(self):
        encoded = encode_write_request(
            [([("__name__", "tpu_exporter_up")], [(1.0, 5)])]
        )
        # cut inside the Sample's fixed64 value: every truncation must
        # surface as the documented ValueError (the chaos receiver's 400
        # path catches exactly that), never a bare struct.error
        for cut in range(1, len(encoded)):
            try:
                parse_write_request(encoded[:cut])
            except ValueError:
                pass


# ----------------------------------------------------------- the send buffer


class TestWalBuffer:
    def test_fifo_across_segments(self, tmp_path):
        b = WalBuffer(str(tmp_path), segment_max_bytes=128)
        b.open()
        for i in range(30):
            b.append(b"p%03d" % i * 8)
        got = []
        while b.peek() is not None:
            got.append(b.peek())
            b.ack()
        assert got == [b"p%03d" % i * 8 for i in range(30)]
        b.close()

    def test_restart_resumes_pending_not_acked(self, tmp_path):
        b = WalBuffer(str(tmp_path), segment_max_bytes=128)
        b.open()
        for i in range(10):
            b.append(b"rec-%d" % i)
        for _ in range(4):
            b.ack()
        b.close()
        b2 = WalBuffer(str(tmp_path), segment_max_bytes=128)
        info = b2.open()
        assert info["pending"] == 6
        assert b2.peek() == b"rec-4"
        b2.close()

    def test_fully_acked_segments_unlinked(self, tmp_path):
        b = WalBuffer(str(tmp_path), segment_max_bytes=64)
        b.open()
        for i in range(20):
            b.append(b"x" * 40)
        while b.peek() is not None:
            b.ack()
        segs = [n for n in os.listdir(tmp_path) if n.startswith("seg-")]
        # only the active segment may remain
        assert len(segs) <= 1
        b.close()

    def test_seal_active_reclaims_acked_bytes_without_append(self, tmp_path):
        """With a large segment cap, everything acked still sits in the
        never-rotated active segment — and rotation is append-lazy, so a
        stalled producer strands those bytes forever. seal_active must
        reclaim them on demand (the disk-pressure path's fix, found by
        the scenario fuzzer's one-round disk_full windows)."""
        b = WalBuffer(str(tmp_path), segment_max_bytes=1 << 20)
        b.open()
        for i in range(20):
            b.append(b"y" * 200)
        while b.peek() is not None:
            b.ack()
        segs = [n for n in os.listdir(tmp_path) if n.startswith("seg-")]
        assert len(segs) == 1  # acked bytes stranded in the active segment
        freed = b.seal_active()
        assert freed > 0
        assert not [n for n in os.listdir(tmp_path) if n.startswith("seg-")]
        # The sealed buffer keeps working: fresh appends land and survive.
        b.append(b"fresh")
        assert b.peek() == b"fresh"
        b.close()
        b2 = WalBuffer(str(tmp_path), segment_max_bytes=1 << 20)
        assert b2.open()["pending"] == 1
        b2.close()

    def test_seal_active_keeps_pending_records(self, tmp_path):
        """Sealing must never drop or re-order unacked records."""
        b = WalBuffer(str(tmp_path), segment_max_bytes=1 << 20)
        b.open()
        for i in range(6):
            b.append(b"rec-%d" % i)
        for _ in range(2):
            b.ack()
        assert b.seal_active() == 0  # pending head pins the sealed segment
        got = []
        while b.peek() is not None:
            got.append(b.peek())
            b.ack()
        assert got == [b"rec-%d" % i for i in range(2, 6)]
        b.close()

    def test_drained_buffer_restart_does_not_swallow_new(self, tmp_path):
        b = WalBuffer(str(tmp_path))
        b.open()
        for i in range(3):
            b.append(b"old-%d" % i)
        while b.peek() is not None:
            b.ack()
        b.close()
        b2 = WalBuffer(str(tmp_path))
        assert b2.open()["pending"] == 0
        b2.append(b"fresh")
        b2.close()
        b3 = WalBuffer(str(tmp_path))
        assert b3.open()["pending"] == 1
        assert b3.peek() == b"fresh"
        b3.close()

    def test_multi_segment_advance_unlinks_all_acked(self, tmp_path):
        """One cursor advance crossing many segments (the age-cap trim
        after a long outage) must reclaim EVERY fully-acked segment now,
        not at the next boot."""
        b = WalBuffer(str(tmp_path), segment_max_bytes=64)
        b.open()
        for i in range(40):
            b.append(b"x" * 40)  # one record per segment
        segs_before = sum(1 for n in os.listdir(tmp_path)
                          if n.startswith("seg-"))
        assert segs_before >= 15  # 2 records per 64-byte segment
        assert b.drop_oldest(35) == 35
        # 5 records remain => at most 3-4 segment files may survive; all
        # the fully-acked ones must be gone NOW, not at the next boot
        segs_after = sum(1 for n in os.listdir(tmp_path)
                         if n.startswith("seg-"))
        assert segs_after <= 4
        # the survivors still drain correctly
        n = 0
        while b.peek() is not None:
            b.ack()
            n += 1
        assert n == 5
        b.close()

    def test_drop_oldest(self, tmp_path):
        b = WalBuffer(str(tmp_path))
        b.open()
        for i in range(5):
            b.append(b"d-%d" % i)
        assert b.drop_oldest(2) == 2
        assert b.peek() == b"d-2"
        assert b.pending() == 3
        b.close()

    def test_peek_last(self, tmp_path):
        b = WalBuffer(str(tmp_path), segment_max_bytes=64)
        b.open()
        for i in range(9):
            b.append(b"t-%d" % i * 6)
        assert b.peek_last() == b"t-8" * 6
        b.close()

    def test_torn_tail_keeps_prefix_and_appends_continue(self, tmp_path):
        b = WalBuffer(str(tmp_path))
        b.open()
        for i in range(6):
            b.append(b"keep-%d" % i)
        b.close()
        seg = os.path.join(tmp_path, "seg-00000000.wal")
        os.truncate(seg, os.path.getsize(seg) - 3)
        b2 = WalBuffer(str(tmp_path))
        info = b2.open()
        assert info["pending"] == 5
        assert info["corrupt_segments"] == 1
        b2.append(b"after-tear")
        drained = []
        while b2.peek() is not None:
            drained.append(b2.peek())
            b2.ack()
        assert drained == [b"keep-%d" % i for i in range(5)] + [b"after-tear"]
        b2.close()


class TestSendBufferFuzz:
    """Satellite: truncate/scramble the egress WAL at random offsets —
    the shipper-side buffer always boots, drains a clean prefix, and never
    re-delivers an acked batch (the test_persist torn-write pattern)."""

    def test_random_corruption_always_boots_prefix_only(self, tmp_path):
        payloads = [frame_batch(i + 1, 100.0 + i, "delta", 1,
                                b"proto-%02d" % i * 11)
                    for i in range(14)]
        b = WalBuffer(str(tmp_path), segment_max_bytes=256)
        b.open()
        for p in payloads:
            b.append(p)
        acked = 4
        for _ in range(acked):
            b.ack()
        b.close()
        seg_files = sorted(
            n for n in os.listdir(tmp_path) if n.startswith("seg-")
        )
        pristine = {
            n: (tmp_path / n).read_bytes() for n in seg_files
        }
        cursor = (tmp_path / "cursor.json").read_bytes()
        acked_seqs = {h["seq"] for h in
                      (parse_batch(p)[0] for p in payloads[:acked])}
        expected_rest = [parse_batch(p)[0]["seq"] for p in payloads[acked:]]

        rng = random.Random(4321)
        for trial in range(25):
            name = seg_files[rng.randrange(len(seg_files))]
            data = bytearray(pristine[name])
            offset = rng.randrange(len(MAGIC), len(data))
            if trial % 2:
                del data[offset:]
            else:
                for i in range(offset, min(offset + 6, len(data))):
                    data[i] ^= 0xA5
            (tmp_path / name).write_bytes(bytes(data))

            b2 = WalBuffer(str(tmp_path), segment_max_bytes=256)
            b2.open()  # must never raise
            got = []
            while True:
                p = b2.peek()
                if p is None:
                    break
                try:
                    got.append(parse_batch(p)[0]["seq"])
                except ValueError:
                    pass
                b2.ack()
            b2.close()
            # never re-delivers an acked batch...
            assert not (set(got) & acked_seqs), (trial, got)
            # ...and what survives is a subsequence of the pending batches
            # (corruption may drop a contiguous chunk, never reorder or
            # invent)
            it = iter(expected_rest)
            assert all(any(seq == e for e in it) for seq in got), (trial, got)
            # restore pristine state (incl. cursor — acks above moved it)
            for n, data0 in pristine.items():
                (tmp_path / n).write_bytes(data0)
            (tmp_path / "cursor.json").write_bytes(cursor)

    def test_acked_never_resent_across_corrupt_restart(self, tmp_path):
        """Deliver some batches through a real shipper, corrupt the dir,
        restart: the receiver's ledger must stay duplicate-free."""
        recv = ChaosReceiver([], seed=0)
        recv.start()
        try:
            sh = RemoteWriteShipper(recv.url, str(tmp_path), interval_s=0.0,
                                    timeout_s=2.0)
            sh.load()
            for i in range(5):
                sh.buffer.append(frame_batch(
                    i + 1, time.time(), "delta", 1,
                    encode_write_request(
                        [([("__name__", "tpu_exporter_up")], [(1.0, i)])]
                    ),
                ))
            sh.start()
            assert wait_for(lambda: sh.buffer.pending() == 0)
            sh.close()
            # scramble whatever remains on disk mid-file
            for name in os.listdir(tmp_path):
                if name.startswith("seg-"):
                    path = tmp_path / name
                    data = bytearray(path.read_bytes())
                    if len(data) > len(MAGIC) + 4:
                        data[len(MAGIC) + 2] ^= 0xFF
                        path.write_bytes(bytes(data))
            sh2 = RemoteWriteShipper(recv.url, str(tmp_path),
                                     interval_s=0.0, timeout_s=2.0)
            sh2.load()
            sh2.start()
            time.sleep(0.3)
            sh2.close()
            stats = recv.stats()
            assert stats["accepted_seqs"] == [1, 2, 3, 4, 5]
            assert not stats["duplicate_seqs"]
            assert not stats["duplicate_samples"]
        finally:
            recv.stop()


# --------------------------------------------------------------- the shipper


class FakeSnap:
    """Minimal Snapshot stand-in: samples_view + timestamps."""

    def __init__(self, ts, **families):
        self.timestamp = ts
        self.poll_timestamp = ts
        self._families = families

    def samples_view(self, name):
        return self._families.get(name)


def up_snap(ts, up=1.0, hbm=None):
    fams = {"tpu_exporter_up": {(): up}}
    if hbm is not None:
        fams["tpu_hbm_used_bytes"] = hbm
    return FakeSnap(ts, **fams)


class CollectingSend:
    def __init__(self, status=200, fail_until=0):
        self.calls = []
        self.status = status
        self.fail_until = fail_until

    def __call__(self, url, body, headers, timeout_s):
        seq = int(headers["X-Tpe-Egress-Seq"])
        if len(self.calls) < self.fail_until:
            self.calls.append(("fail", seq))
            raise ConnectionError("injected")
        self.calls.append(("ok", seq))
        self.last_series = parse_write_request(snappy_decompress(body))
        if self.status != 200:
            import urllib.error

            raise urllib.error.HTTPError(url, self.status, "injected",
                                         hdrs=None, fp=None)
        return self.status


def make_shipper(tmp_path, send, **kw):
    kw.setdefault("interval_s", 0.0)
    # Tests drive synthetic wall timestamps (100.0, ...) against the real
    # clock; the age cap would read those as hours-old and drop them.
    kw.setdefault("max_backlog_age_s", 0.0)
    kw.setdefault("breaker", CircuitBreaker(
        failure_threshold=2, backoff_base_s=0.05, backoff_max_s=0.1))
    sh = RemoteWriteShipper("http://recv.invalid/w", str(tmp_path),
                            send=send, **kw)
    sh.load()
    return sh


class TestShipperBatching:
    def test_first_batch_full_then_delta_with_heartbeat(self, tmp_path):
        send = CollectingSend()
        sh = make_shipper(tmp_path, send)
        key = ("0", "/dev/accel0", "v", "s", "h", "0", "", "", "")
        sh._write_snapshot(up_snap(100.0, hbm={key: 5.0}))
        sh._write_snapshot(up_snap(101.0, hbm={key: 5.0}))   # unchanged
        sh._write_snapshot(up_snap(102.0, hbm={key: 9.0}))   # hbm changed
        batches = []
        while True:
            p = sh.buffer.peek()
            if p is None:
                break
            batches.append(parse_batch(p))
            sh.buffer.ack()
        assert [h["kind"] for h, _ in batches] == ["full", "delta", "delta"]
        assert batches[0][0]["samples"] == 2
        # unchanged poll ships only the up heartbeat
        series = parse_write_request(batches[1][1])
        assert [s[0]["__name__"] for s in series] == ["tpu_exporter_up"]
        # changed poll ships hbm + heartbeat
        names = sorted(s[0]["__name__"]
                       for s in parse_write_request(batches[2][1]))
        assert names == ["tpu_exporter_up", "tpu_hbm_used_bytes"]
        sh.close()

    def test_layout_change_forces_full(self, tmp_path):
        sh = make_shipper(tmp_path, CollectingSend())
        k0 = ("0",) + ("",) * 8
        k1 = ("1",) + ("",) * 8
        sh._write_snapshot(up_snap(100.0, hbm={k0: 1.0}))
        sh._write_snapshot(up_snap(101.0, hbm={k0: 1.0, k1: 2.0}))
        heads = []
        while sh.buffer.peek() is not None:
            heads.append(parse_batch(sh.buffer.peek())[0])
            sh.buffer.ack()
        assert [h["kind"] for h in heads] == ["full", "full"]
        sh.close()

    def test_periodic_full_sync(self, tmp_path):
        sh = make_shipper(tmp_path, CollectingSend(), full_sync_s=10.0)
        sh._write_snapshot(up_snap(100.0))
        sh._write_snapshot(up_snap(105.0))   # inside window: delta
        sh._write_snapshot(up_snap(111.0))   # window elapsed: full again
        heads = []
        while sh.buffer.peek() is not None:
            heads.append(parse_batch(sh.buffer.peek())[0]["kind"])
            sh.buffer.ack()
        assert heads == ["full", "delta", "full"]
        sh.close()

    def test_interval_thins_batches(self, tmp_path):
        sh = make_shipper(tmp_path, CollectingSend(), interval_s=5.0)
        for ts in (100.0, 101.0, 102.0, 106.0):
            sh._write_snapshot(up_snap(ts, up=ts))  # value always changes
        assert sh.buffer.pending() == 2  # 100.0 and 106.0
        sh.close()

    def test_extra_labels_fill_only_missing(self, tmp_path):
        send = CollectingSend()
        sh = make_shipper(tmp_path, send,
                          extra_labels={"host": "me", "slice_name": "sl"})
        key = ("0", "/dev/accel0", "v", "s", "OTHER", "0", "", "", "")
        sh._write_snapshot(up_snap(100.0, hbm={key: 5.0}))
        sh.start()
        assert wait_for(lambda: sh.buffer.pending() == 0)
        sh.close()
        by_name = {s[0]["__name__"]: s[0] for s in send.last_series}
        assert by_name["tpu_exporter_up"]["host"] == "me"
        # the chip series already carries host="OTHER"; not overwritten
        assert by_name["tpu_hbm_used_bytes"]["host"] == "OTHER"


class TestShipperSending:
    def test_outage_then_recovery_zero_loss(self, tmp_path):
        send = CollectingSend(fail_until=5)
        sh = make_shipper(tmp_path, send)
        for i in range(6):
            sh._write_snapshot(up_snap(100.0 + i, up=float(i)))
        assert sh.buffer.pending() == 6
        sh.start()
        assert wait_for(lambda: sh.buffer.pending() == 0, timeout=15)
        sh.close()
        oks = [seq for kind, seq in send.calls if kind == "ok"]
        assert oks == [1, 2, 3, 4, 5, 6]
        st = sh.stats()
        assert st["failed_sends"] >= 2  # breaker throttled the rest
        assert st["sent_batches"] == 6
        assert st["breaker_state"] == "closed"

    def test_breaker_opens_on_failures(self, tmp_path):
        send = CollectingSend(fail_until=10**9)
        sh = make_shipper(tmp_path, send)
        sh._write_snapshot(up_snap(100.0))
        sh.start()
        assert wait_for(lambda: sh.breaker.state != "closed", timeout=5)
        # breaker-gated: attempts are throttled, not one per loop spin
        time.sleep(0.3)
        attempts = len(send.calls)
        assert attempts < 30
        sh.close()
        assert sh.stats()["backlog_batches"] == 1  # nothing lost

    def test_poison_4xx_skipped_not_wedged(self, tmp_path):
        class PoisonSecond(CollectingSend):
            def __call__(self, url, body, headers, timeout_s):
                seq = int(headers["X-Tpe-Egress-Seq"])
                if seq == 2:
                    import urllib.error

                    self.calls.append(("poison", seq))
                    raise urllib.error.HTTPError(url, 400, "bad", None, None)
                return super().__call__(url, body, headers, timeout_s)

        send = PoisonSecond()
        sh = make_shipper(tmp_path, send)
        for i in range(3):
            sh._write_snapshot(up_snap(100.0 + i, up=float(i)))
        sh.start()
        assert wait_for(lambda: sh.buffer.pending() == 0, timeout=10)
        sh.close()
        st = sh.stats()
        assert st["dropped"]["poison"] == 1
        assert st["sent_batches"] == 2
        assert [s for k, s in send.calls if k == "ok"] == [1, 3]
        # poison does not open the breaker: the receiver is UP
        assert st["breaker_state"] == "closed"

    def test_429_is_retried_not_dropped(self, tmp_path):
        state = {"n": 0}

        def send(url, body, headers, timeout_s):
            state["n"] += 1
            if state["n"] <= 2:
                import urllib.error

                raise urllib.error.HTTPError(url, 429, "slow down", None,
                                             None)
            return 200

        sh = make_shipper(tmp_path, send)
        sh._write_snapshot(up_snap(100.0))
        sh.start()
        assert wait_for(lambda: sh.buffer.pending() == 0, timeout=10)
        sh.close()
        st = sh.stats()
        assert st["sent_batches"] == 1
        assert st["failed_sends"] == 2
        assert st["dropped"]["poison"] == 0

    def test_backlog_byte_cap_drops_oldest(self, tmp_path):
        sh = make_shipper(tmp_path, CollectingSend(fail_until=10**9),
                          max_backlog_mb=0.0005)  # ~512 bytes
        for i in range(20):
            sh._write_snapshot(up_snap(100.0 + i, up=float(i)))
        sh._enforce_caps()  # normally the sender thread's loop does this
        st = sh.stats()
        assert st["dropped"]["backlog"] > 0
        assert st["backlog_bytes"] <= 512 + 200  # cap + one batch slack
        sh.close()

    def test_backlog_age_cap_drops_oldest(self, tmp_path):
        # Batches created by THIS process age on the MONOTONIC clock (the
        # clock-step fence: an NTP wall step must never mass-drop a
        # healthy backlog), so the outage is simulated by advancing both
        # clocks together — the honest shape of 100 s actually passing.
        clock = {"wall": 1000.0, "mono": 500.0}
        sh = make_shipper(tmp_path, CollectingSend(fail_until=10**9),
                          max_backlog_age_s=50.0,
                          wallclock=lambda: clock["wall"],
                          clock=lambda: clock["mono"])
        sh._write_snapshot(up_snap(1000.0))
        sh._peek_meta()  # sender-side head refresh (reads the mono stamp)
        clock["wall"] = 1100.0  # 100 s pass (both clocks)
        clock["mono"] = 600.0
        sh._write_snapshot(up_snap(1100.0))
        sh._enforce_caps()  # normally the sender thread's loop does this
        st = sh.stats()
        assert st["dropped"]["backlog"] == 1
        assert st["backlog_batches"] == 1
        sh.close()

    def test_wall_step_does_not_mass_drop_backlog(self, tmp_path):
        # The fence itself: a +1 h WALL step with no real time passing
        # must not age-cap-drop batches this process created.
        clock = {"wall": 1000.0, "mono": 500.0}
        sh = make_shipper(tmp_path, CollectingSend(fail_until=10**9),
                          max_backlog_age_s=50.0,
                          wallclock=lambda: clock["wall"],
                          clock=lambda: clock["mono"])
        sh._write_snapshot(up_snap(1000.0))
        sh._peek_meta()
        clock["wall"] = 1000.0 + 3600.0  # NTP step, zero monotonic time
        sh._enforce_caps()
        st = sh.stats()
        assert st["dropped"]["backlog"] == 0
        assert st["backlog_batches"] == 1
        assert st["backlog_age_s"] == 0.0  # fenced, not 3600
        sh.close()

    def test_slow_drain_backlog_age_is_true_enqueue_age(self, tmp_path):
        # A draining backlog's head age must be the time since ENQUEUE,
        # not since the batch became head: a receiver accepting slower
        # than the batch rate would otherwise report a perpetual ~0 age
        # and the age cap/alert would never see the growing staleness.
        clock = {"wall": 1000.0, "mono": 500.0}
        sh = make_shipper(tmp_path, CollectingSend(fail_until=10**9),
                          wallclock=lambda: clock["wall"],
                          clock=lambda: clock["mono"])
        sh._write_snapshot(up_snap(1000.0))
        clock["wall"] += 300.0
        clock["mono"] += 300.0
        sh._write_snapshot(up_snap(1300.0))
        sh._peek_meta()  # a drain step re-reads the head: age must hold
        assert sh.backlog_age_s() == pytest.approx(300.0)
        sh.close()

    def test_forward_step_sheds_only_genuinely_over_age(self, tmp_path):
        # The age-cap SCAN is fenced like the trigger: with a genuinely
        # over-age head AND a +1 h wall step, only the over-age prefix
        # drops — never the fresh batches behind it.
        clock = {"wall": 1000.0, "mono": 500.0}
        sh = make_shipper(tmp_path, CollectingSend(fail_until=10**9),
                          max_backlog_age_s=50.0,
                          wallclock=lambda: clock["wall"],
                          clock=lambda: clock["mono"])
        sh._write_snapshot(up_snap(1000.0))
        clock["wall"] += 55.0
        clock["mono"] += 55.0
        sh._write_snapshot(up_snap(1055.0))   # fresh batch
        clock["wall"] += 3600.0               # NTP step, no real time
        sh._peek_meta()
        sh._enforce_caps()
        st = sh.stats()
        assert st["dropped"]["backlog"] == 1  # only the 55 s-old head
        assert st["backlog_batches"] == 1
        sh.close()

    def test_backward_wall_step_does_not_stall_shipping(self, tmp_path):
        # A backward step must not park the interval gate: without the
        # clamp, `wall - last_batch_wall` stays negative until the clock
        # catches back up and egress silently stops for the step width.
        send = CollectingSend()
        sh = make_shipper(tmp_path, send, interval_s=1.0)
        sh._write_snapshot(up_snap(1000.0))
        sh._write_snapshot(up_snap(940.0))   # clock stepped -60 s
        sh._write_snapshot(up_snap(941.5))   # next poll on the new timeline
        heads = []
        while True:
            p = sh.buffer.peek()
            if p is None:
                break
            head, _ = parse_batch(p)
            heads.append(head["wall"])
            sh.buffer.ack()
        # The 941.5 batch shipped (interval met on the NEW timeline); the
        # 940.0 one re-anchored the gate and was deliberately skipped.
        assert heads == [1000.0, 941.5]
        sh.close()

    def test_half_open_probe_on_corrupt_head_never_wedges(self, tmp_path):
        """A consumed half-open probe that hits a corrupt head batch must
        record an outcome — an outcome-less return would park the breaker
        in half_open forever (decide() answers 'skip' until restart)."""
        send = CollectingSend()
        sh = make_shipper(tmp_path, send)
        sh.buffer.append(b"not-a-batch-frame")
        sh._write_snapshot(up_snap(100.0))
        # Simulate the consumed probe: decide() moved open -> half_open.
        sh.breaker.state = "open"
        sh.breaker._next_probe_at = 0.0
        assert sh.breaker.decide() == "probe"
        assert sh.breaker.state == "half_open"
        assert sh._send_one() is True   # corrupt head dropped
        assert sh.breaker.state != "half_open"  # outcome WAS recorded
        # and the breaker recovers to deliver the real batch
        deadline = time.monotonic() + 5
        while sh.buffer.pending() and time.monotonic() < deadline:
            if sh.breaker.decide() in ("call", "probe"):
                sh._send_one()
            time.sleep(0.01)
        assert [s for k, s in send.calls if k == "ok"] == [1]
        assert sh.stats()["dropped"]["corrupt"] == 1
        sh.close()

    def test_restart_resumes_seq_and_backlog(self, tmp_path):
        sh = make_shipper(tmp_path, CollectingSend(fail_until=10**9))
        for i in range(4):
            sh._write_snapshot(up_snap(100.0 + i, up=float(i)))
        sh.close()
        send = CollectingSend()
        sh2 = make_shipper(tmp_path, send)
        assert sh2.buffer.pending() == 4
        sh2._write_snapshot(up_snap(200.0, up=99.0))  # continues the seq
        sh2.start()
        assert wait_for(lambda: sh2.buffer.pending() == 0, timeout=10)
        sh2.close()
        oks = [s for k, s in send.calls if k == "ok"]
        assert oks == [1, 2, 3, 4, 5]


class TestShipperEndToEnd:
    def test_chaos_receiver_flap_exactly_once(self, tmp_path):
        recv = ChaosReceiver(
            parse_chaos_spec("err:recv:1:@2:x3,reject:recv:1:@6:x2"),
            seed=3,
        )
        recv.start()
        try:
            sh = RemoteWriteShipper(
                recv.url, str(tmp_path), interval_s=0.0, timeout_s=2.0,
                breaker=CircuitBreaker(failure_threshold=2,
                                       backoff_base_s=0.05,
                                       backoff_max_s=0.1),
            )
            sh.load()
            sh.start()
            base = time.time()
            for i in range(10):
                sh._q.put(up_snap(base + 0.001 * i, up=float(i)))
            # One batch per snapshot (values change every time); wait on
            # the RECEIVER's ledger — buffer-empty races the writer thread.
            assert wait_for(lambda: recv.accepted_batches() >= 10,
                            timeout=20)
            sh.close()
            stats = recv.stats()
            seqs = stats["accepted_seqs"]
            assert sorted(seqs) == list(range(1, max(seqs) + 1))
            assert not stats["duplicate_seqs"]
            assert not stats["duplicate_samples"]
            assert {k for _i, k in stats["injected"]} == {"err", "reject"}
        finally:
            recv.stop()

    def test_truncate_mid_body_is_retried(self, tmp_path):
        recv = ChaosReceiver(parse_chaos_spec("truncate:recv:1:x1"), seed=1)
        recv.start()
        try:
            sh = RemoteWriteShipper(
                recv.url, str(tmp_path), interval_s=0.0, timeout_s=2.0,
                breaker=CircuitBreaker(failure_threshold=3,
                                       backoff_base_s=0.05,
                                       backoff_max_s=0.1),
            )
            sh.load()
            sh.start()
            sh._q.put(up_snap(time.time()))
            assert wait_for(lambda: recv.accepted_batches() >= 1,
                            timeout=10)
            sh.close()
            stats = recv.stats()
            assert stats["accepted_seqs"] == [1]
            assert not stats["duplicate_seqs"]
            assert stats["injected"] == [(0, "truncate")]
        finally:
            recv.stop()


# ------------------------------------------------------ collector integration


class TestCollectorIntegration:
    def test_egress_excluded_from_publish_and_total(self):
        called = {"n": 0}

        class SlowShipper:
            @staticmethod
            def on_snapshot(snap):
                called["n"] += 1
                time.sleep(0.5)
                return 1

            @staticmethod
            def emit(b):
                pass

        collector = Collector(
            FakeBackend(chips=2), FakeAttribution(), SnapshotStore(),
            shipper=SlowShipper(),
        )
        stats = collector.poll_once()
        assert called["n"] == 1
        # the 500 ms egress sleep must not appear in any poll phase
        # timing (generous thresholds: full-suite CPU contention has made
        # a 4-chip publish run tens of ms — the assertion is about the
        # SLEEP leaking, not about absolute publish speed)
        assert stats.publish_s < 0.4
        assert stats.total_s < 0.4

    def test_poll_survives_broken_shipper(self):
        class BrokenShipper:
            @staticmethod
            def on_snapshot(snap):
                raise OSError("receiver on fire")

            @staticmethod
            def emit(b):
                raise OSError("still on fire")

        collector = Collector(
            FakeBackend(chips=2), FakeAttribution(), SnapshotStore(),
            shipper=BrokenShipper(),
        )
        stats = collector.poll_once()
        assert stats.ok

    def test_egress_specs_in_exposition(self, tmp_path):
        store = SnapshotStore()
        sh = make_shipper(tmp_path, CollectingSend())
        collector = Collector(
            FakeBackend(chips=2), FakeAttribution(), store, shipper=sh,
        )
        collector.poll_once()
        collector.poll_once()
        snap = store.current()
        assert snap.value("tpu_exporter_egress_breaker_state") == 0.0
        assert snap.value("tpu_exporter_egress_backlog_batches") is not None
        assert snap.value("tpu_exporter_egress_dropped_total",
                          {"reason": "poison"}) == 0.0
        body = snap.encode().decode()
        assert "# TYPE tpu_exporter_egress_send_seconds histogram" in body
        sh.close()

    def test_no_shipper_no_egress_series(self):
        store = SnapshotStore()
        collector = Collector(FakeBackend(chips=2), FakeAttribution(), store)
        collector.poll_once()
        assert store.current().value(
            "tpu_exporter_egress_breaker_state") is None


# -------------------------------------------------------------- chaos grammar


class TestChaosRecvGrammar:
    def test_recv_rules_parse(self):
        rules = parse_chaos_spec(
            "hang:recv:1:2s,err:recv:0.5,reject:recv:1:x2,"
            "truncate:recv:1:@3"
        )
        assert [r.kind for r in rules] == ["hang", "err", "reject",
                                           "truncate"]
        assert all(r.source == "recv" for r in rules)

    def test_receiver_only_kinds_rejected_for_sources(self):
        with pytest.raises(ValueError, match="only\\s+valid for the recv"):
            parse_chaos_spec("reject:device:1")
        with pytest.raises(ValueError, match="only\\s+valid for the recv"):
            parse_chaos_spec("truncate:procscan:1")

    def test_source_only_kinds_rejected_for_recv(self):
        with pytest.raises(ValueError, match="not\\s+valid for the recv"):
            parse_chaos_spec("kill:recv:1")
        with pytest.raises(ValueError, match="not\\s+valid for the recv"):
            parse_chaos_spec("garbage:recv:1")

    def test_schedule_is_seeded_deterministic(self):
        for _ in range(2):
            recv = ChaosReceiver(parse_chaos_spec("err:recv:0.5"), seed=9)
            drawn = [recv._draw(i) is not None for i in range(20)]
            if _ == 0:
                first = drawn
        assert drawn == first


# ------------------------------------------------------------- status footer


class TestStatusFooter:
    def test_egress_line_missing_dir(self, tmp_path):
        from tpu_pod_exporter.status import egress_line

        line = egress_line("http://r/w", str(tmp_path / "nope"))
        assert "missing" in line

    def test_egress_line_renders_status(self, tmp_path):
        from tpu_pod_exporter.status import egress_line

        (tmp_path / "egress-status.json").write_text(json.dumps({
            "wall": time.time(), "breaker": "open",
            "backlog_batches": 7, "backlog_bytes": 12345,
            "last_send_latency_s": 0.01,
            "last_send_ok_wall": time.time() - 5,
            "last_error": "HTTP 503",
        }))
        line = egress_line("http://r/w", str(tmp_path))
        assert "breaker open" in line
        assert "7 batch(es)" in line
        assert "HTTP 503" in line

    def test_dir_summary(self, tmp_path):
        b = WalBuffer(str(tmp_path))
        b.open()
        b.append(b"xyz")
        b.close()
        s = egress_dir_summary(str(tmp_path))
        assert s["exists"] and s["segments"] == 1
        assert s["segment_bytes"] > 0


# ------------------------------------------------------------- metric wiring


class TestMetricSets:
    def test_exporter_set_is_the_tracked_set(self):
        from tpu_pod_exporter.history import HISTORY_TRACKED_METRICS

        assert set(exporter_egress_metrics()) == set(HISTORY_TRACKED_METRICS)

    def test_aggregator_set_is_the_rollup_surface(self):
        names = aggregator_egress_metrics()
        assert "tpu_slice_hbm_used_bytes" in names
        assert "tpu_aggregator_target_up" in names
        # plumbing counters stay out
        assert "tpu_aggregator_scrape_errors_total" not in names

    def test_degraded_predicate(self, tmp_path):
        sh = make_shipper(tmp_path, CollectingSend())
        assert not sh.degraded
        sh.breaker.state = "open"
        sh.breaker.reopens = 3
        assert sh.degraded
        detail = sh.ready_detail()
        assert detail["degraded"] is True
        sh.close()
