"""End-to-end integration: full app over real HTTP with fakes (SURVEY.md §4.3).

Covers baseline configs 1 (0 devices) and 2 (v4-8, one pod), plus the
CollectorLoop cadence and clean shutdown.
"""

import time
import urllib.request

import pytest
from prometheus_client.parser import text_string_to_metric_families

from tpu_pod_exporter.app import ExporterApp
from tpu_pod_exporter.attribution.fake import FakeAttribution, simple_allocation
from tpu_pod_exporter.backend.fake import FakeBackend, FakeChipScript
from tpu_pod_exporter.config import ExporterConfig


def scrape(port: int) -> str:
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics", timeout=5) as r:
        return r.read().decode()


def make_app(backend, attribution, interval_s=0.05, **cfg_kw) -> ExporterApp:
    cfg = ExporterConfig(
        port=0,
        host="127.0.0.1",
        interval_s=interval_s,
        accelerator=cfg_kw.pop("accelerator", "v4-8"),
        node_name=cfg_kw.pop("node_name", "testhost"),
        worker_id="0",
        slice_name="test-slice",
        **cfg_kw,
    )
    return ExporterApp(cfg, backend=backend, attribution=attribution)


@pytest.fixture
def app_factory():
    apps = []

    def factory(*args, **kw):
        app = make_app(*args, **kw)
        apps.append(app)
        app.start()
        return app

    yield factory
    for app in apps:
        app.stop()


class TestConfig1ZeroDevices:
    def test_smoke(self, app_factory):
        app = app_factory(FakeBackend(chips=0), FakeAttribution())
        text = scrape(app.port)
        fams = {f.name: f for f in text_string_to_metric_families(text)}
        assert fams["tpu_exporter_up"].samples[0].value == 1
        # full schema present even with zero devices
        assert "tpu_hbm_used_bytes" in fams
        assert not fams["tpu_hbm_used_bytes"].samples

    def test_readyz_immediately_after_start(self, app_factory):
        app = app_factory(FakeBackend(chips=0), FakeAttribution())
        with urllib.request.urlopen(
            f"http://127.0.0.1:{app.port}/readyz", timeout=5
        ) as r:
            assert r.status == 200


class TestConfig2SingleHostOnePod:
    def test_per_chip_series_with_attribution(self, app_factory):
        backend = FakeBackend(
            chips=4,
            script=FakeChipScript(
                hbm_total_bytes=32 * 1024**3, hbm_used_bytes=8 * 1024**3,
                duty_cycle_percent=90.0,
            ),
        )
        attr = FakeAttribution(
            [simple_allocation("train-0", ["0", "1", "2", "3"], namespace="ml")]
        )
        app = app_factory(backend, attr)
        text = scrape(app.port)
        fams = {f.name: f for f in text_string_to_metric_families(text)}
        used = fams["tpu_hbm_used_bytes"].samples
        assert len(used) == 4
        for s in used:
            assert s.labels["pod"] == "train-0"
            assert s.labels["namespace"] == "ml"
            assert s.labels["accelerator"] == "v4-8"
            assert s.labels["host"] == "testhost"
            assert s.value == 8 * 1024**3
        perc = {s.labels["chip_id"]: s.value for s in fams["tpu_hbm_used_percent"].samples}
        assert perc == {"0": 25.0, "1": 25.0, "2": 25.0, "3": 25.0}
        pod_count = fams["tpu_pod_chip_count"].samples
        assert len(pod_count) == 1 and pod_count[0].value == 4


class TestDebugVars:
    def test_debug_vars_endpoint(self, app_factory):
        import json

        app = app_factory(FakeBackend(chips=2), FakeAttribution())
        with urllib.request.urlopen(
            f"http://127.0.0.1:{app.port}/debug/vars", timeout=5
        ) as r:
            doc = json.load(r)
        assert doc["last_poll"]["ok"] is True
        assert doc["config"]["backend"] == "fake"
        assert doc["series"] > 0
        assert doc["snapshot_age_s"] >= 0


class TestLoopCadence:
    def test_background_polling_advances(self, app_factory):
        backend = FakeBackend(chips=1)
        app = app_factory(backend, FakeAttribution(), interval_s=0.02)
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if backend.sample_calls >= 5:
                break
            time.sleep(0.01)
        assert backend.sample_calls >= 5

    def test_stop_is_clean_and_closes_backends(self):
        backend = FakeBackend(chips=1)
        attr = FakeAttribution()
        app = make_app(backend, attr, interval_s=0.02)
        app.start()
        app.stop()
        assert backend.closed and attr.closed
        calls_after_stop = backend.sample_calls
        time.sleep(0.1)
        assert backend.sample_calls == calls_after_stop

    def test_scrape_during_churn_always_consistent(self, app_factory):
        """Scrapes racing the poll loop must always parse and be complete."""
        backend = FakeBackend(chips=4)
        attr = FakeAttribution([simple_allocation("a", ["0", "1", "2", "3"])])
        app = app_factory(backend, attr, interval_s=0.01)
        for i in range(20):
            attr.set_allocations([simple_allocation(f"pod-{i}", ["0", "1", "2", "3"])])
            fams = {f.name: f for f in text_string_to_metric_families(scrape(app.port))}
            assert len(fams["tpu_hbm_used_bytes"].samples) == 4
            pods = {s.labels["pod"] for s in fams["tpu_hbm_used_bytes"].samples}
            assert len(pods) == 1  # never a half-applied attribution
