"""RateLimitedLogger tests (SURVEY.md §5: leveled, rate-limited logging)."""

import logging

from tpu_pod_exporter.utils import RateLimitedLogger


def make(clock_value, min_interval=30.0):
    records = []

    class Capture(logging.Handler):
        def emit(self, record):
            records.append(record.getMessage())

    logger = logging.getLogger(f"test-rl-{id(records)}")
    logger.setLevel(logging.DEBUG)
    logger.addHandler(Capture())
    logger.propagate = False
    rl = RateLimitedLogger(logger, min_interval_s=min_interval, clock=lambda: clock_value[0])
    return rl, records


class TestRateLimitedLogger:
    def test_first_emits_repeats_suppressed(self):
        now = [0.0]
        rl, records = make(now)
        for _ in range(10):
            rl.warning("k", "backend down: %s", "err")
        assert records == ["backend down: err"]

    def test_suppressed_count_reported_after_window(self):
        now = [0.0]
        rl, records = make(now)
        for _ in range(5):
            rl.warning("k", "boom")
        now[0] = 31.0
        rl.warning("k", "boom")
        assert records == ["boom", "boom (+4 similar suppressed)"]

    def test_stale_counts_not_attributed_to_new_incident(self):
        now = [0.0]
        rl, records = make(now)
        for _ in range(5):
            rl.warning("k", "old incident")
        now[0] = 100000.0  # days later, unrelated fault
        rl.warning("k", "new incident")
        assert records == ["old incident", "new incident"]

    def test_distinct_keys_independent(self):
        now = [0.0]
        rl, records = make(now)
        rl.warning("a", "a-msg")
        rl.warning("b", "b-msg")
        assert records == ["a-msg", "b-msg"]

    def test_recovery_bypasses_fault_rate_limit(self):
        """ISSUE 2 satellite: an incident's recovery must log (at WARNING)
        even deep inside the fault lines' suppression window — operators
        must see the end of an incident, not just its start."""
        now = [0.0]
        rl, records = make(now)
        for _ in range(5):
            rl.warning("k", "source down")
        now[0] = 10.0  # deep inside the fault key's 30 s window
        rl.recovery("k", "source healthy again after %d failures", 4)
        assert records == [
            "source down",
            "source healthy again after 4 failures",
        ]

    def test_recovery_logs_at_warning_level(self):
        now = [0.0]
        records = []

        class Capture(logging.Handler):
            def emit(self, record):
                records.append((record.levelno, record.getMessage()))

        logger = logging.getLogger(f"test-rl-lvl-{id(records)}")
        logger.setLevel(logging.DEBUG)
        logger.addHandler(Capture())
        logger.propagate = False
        rl = RateLimitedLogger(logger, clock=lambda: now[0])
        rl.recovery("k", "healthy again")
        assert records == [(logging.WARNING, "healthy again")]

    def test_repeated_recoveries_are_themselves_throttled(self):
        now = [0.0]
        rl, records = make(now)
        rl.recovery("k", "recovered")
        now[0] = 10.0
        rl.recovery("k", "recovered")  # inside the recovery window
        now[0] = 45.0
        rl.recovery("k", "recovered")
        assert records == [
            "recovered",
            "recovered (+1 similar suppressed)",
        ]

    def test_flapping_source_does_not_spam_through_recovery(self):
        """A fail→recover flap every tick must stay throttled: the fault
        window is untouched by recoveries and the recovery line rides its
        own window, instead of two unthrottled WARNINGs per flap cycle."""
        now = [0.0]
        rl, records = make(now)
        for _ in range(20):  # 20 flap cycles inside one 30 s window
            rl.warning("k", "down")
            now[0] += 0.5
            rl.recovery("k", "up again")
            now[0] += 0.5
        # One fault line + one recovery line for the whole window.
        assert records == ["down", "up again"]
        # Next window: one more of each, carrying the suppressed tallies.
        now[0] = 45.0
        rl.warning("k", "down")
        rl.recovery("k", "up again")
        assert records[2:] == [
            "down (+19 similar suppressed)",
            "up again (+19 similar suppressed)",
        ]

    def test_levels(self):
        now = [0.0]
        rl, records = make(now)
        rl.info("i", "info-msg")
        rl.error("e", "error-msg")
        assert records == ["info-msg", "error-msg"]


class TestJsonLogging:
    def _record(self, logger="t", level=logging.WARNING, msg="hello %s",
                args=("world",), exc_info=None):
        return logging.LogRecord(
            logger, level, "f.py", 1, msg, args, exc_info
        )

    def test_json_lines_are_valid_and_cloud_shaped(self):
        import json

        from tpu_pod_exporter.utils import JsonLogFormatter

        line = JsonLogFormatter().format(self._record())
        obj = json.loads(line)
        assert obj["severity"] == "WARNING"  # the key GKE promotes
        assert obj["message"] == "hello world"
        assert obj["logger"] == "t"
        assert "time" in obj
        assert "\n" not in line  # one line per record, always

    def test_hostile_message_cannot_break_line_framing(self):
        import json

        from tpu_pod_exporter.utils import JsonLogFormatter

        nasty = 'pod "a\nb\\c"   died'
        line = JsonLogFormatter().format(
            self._record(msg="%s", args=(nasty,))
        )
        assert "\n" not in line
        assert json.loads(line)["message"] == nasty

    def test_exception_info_included(self):
        import json
        import sys

        from tpu_pod_exporter.utils import JsonLogFormatter

        try:
            raise ValueError("boom")
        except ValueError:
            rec = self._record(msg="failed", args=(), exc_info=sys.exc_info())
        obj = json.loads(JsonLogFormatter().format(rec))
        assert "ValueError: boom" in obj["exception"]

    def test_time_field_is_rfc3339_utc(self):
        import json
        import re

        from tpu_pod_exporter.utils import JsonLogFormatter

        obj = json.loads(JsonLogFormatter().format(self._record()))
        # Strict Cloud Logging parsers need a colon in the offset and
        # benefit from sub-second precision for burst ordering.
        assert re.fullmatch(
            r"\d{4}-\d{2}-\d{2}T\d{2}:\d{2}:\d{2}\.\d+\+00:00", obj["time"]
        ), obj["time"]

    def test_setup_logging_json_branch_installs_formatter(self, monkeypatch):
        from tpu_pod_exporter import utils as U

        captured = {}
        monkeypatch.setattr(
            logging, "basicConfig", lambda **kw: captured.update(kw)
        )
        U.setup_logging("warning", "json")
        assert captured["level"] == logging.WARNING
        (handler,) = captured["handlers"]
        assert isinstance(handler.formatter, U.JsonLogFormatter)
        # Case-insensitive accept; unknown value is a loud startup error,
        # never a silent fallback to text (code-review r5).
        captured.clear()
        U.setup_logging("info", "JSON")
        assert "handlers" in captured
        import pytest as _pytest

        with _pytest.raises(ValueError, match="log-format"):
            U.setup_logging("info", "jsonl")
        with _pytest.raises(ValueError, match="log-level"):
            U.setup_logging("verbose", "json")
        # A non-int module attribute (logging.BASIC_FORMAT is a str) must
        # not slip through the getattr lookup as if it were a level.
        with _pytest.raises(ValueError, match="log-level"):
            U.setup_logging("basic_format", "text")
        # NOTSET (0) silently means effective-WARNING on the root logger —
        # reject it rather than drop debug/info without a word.
        with _pytest.raises(ValueError, match="log-level"):
            U.setup_logging("notset", "text")

    def test_setup_logging_json_emits_parseable_lines(self):
        import io
        import json

        from tpu_pod_exporter.utils import JsonLogFormatter

        # Drive a real handler pipeline (not basicConfig, which pytest's
        # root logger would fight over): formatter + stream end to end.
        # The setup_logging branch itself is covered above; the CLI e2e
        # path is covered by the subprocess smoke in test_integration.
        stream = io.StringIO()
        h = logging.StreamHandler(stream)
        h.setFormatter(JsonLogFormatter())
        lg = logging.getLogger("tpe-json-test")
        lg.addHandler(h)
        lg.setLevel(logging.INFO)
        try:
            lg.info("round %d done", 7)
        finally:
            lg.removeHandler(h)
        (line,) = stream.getvalue().splitlines()
        assert json.loads(line)["message"] == "round 7 done"
