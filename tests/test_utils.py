"""RateLimitedLogger tests (SURVEY.md §5: leveled, rate-limited logging)."""

import logging

from tpu_pod_exporter.utils import RateLimitedLogger


def make(clock_value, min_interval=30.0):
    records = []

    class Capture(logging.Handler):
        def emit(self, record):
            records.append(record.getMessage())

    logger = logging.getLogger(f"test-rl-{id(records)}")
    logger.setLevel(logging.DEBUG)
    logger.addHandler(Capture())
    logger.propagate = False
    rl = RateLimitedLogger(logger, min_interval_s=min_interval, clock=lambda: clock_value[0])
    return rl, records


class TestRateLimitedLogger:
    def test_first_emits_repeats_suppressed(self):
        now = [0.0]
        rl, records = make(now)
        for _ in range(10):
            rl.warning("k", "backend down: %s", "err")
        assert records == ["backend down: err"]

    def test_suppressed_count_reported_after_window(self):
        now = [0.0]
        rl, records = make(now)
        for _ in range(5):
            rl.warning("k", "boom")
        now[0] = 31.0
        rl.warning("k", "boom")
        assert records == ["boom", "boom (+4 similar suppressed)"]

    def test_stale_counts_not_attributed_to_new_incident(self):
        now = [0.0]
        rl, records = make(now)
        for _ in range(5):
            rl.warning("k", "old incident")
        now[0] = 100000.0  # days later, unrelated fault
        rl.warning("k", "new incident")
        assert records == ["old incident", "new incident"]

    def test_distinct_keys_independent(self):
        now = [0.0]
        rl, records = make(now)
        rl.warning("a", "a-msg")
        rl.warning("b", "b-msg")
        assert records == ["a-msg", "b-msg"]

    def test_levels(self):
        now = [0.0]
        rl, records = make(now)
        rl.info("i", "info-msg")
        rl.error("e", "error-msg")
        assert records == ["info-msg", "error-msg"]
