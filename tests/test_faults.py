"""Fault injection at the app level (SURVEY.md §4.5): backends erroring and
timing out mid-poll must degrade the exporter, never kill it — the inversion
of the reference's log.Fatalf-in-loop behavior (main.go:119-137)."""

import time
import urllib.error
import urllib.request

import pytest
from prometheus_client.parser import text_string_to_metric_families

from tpu_pod_exporter.app import ExporterApp
from tpu_pod_exporter.collector import CollectorLoop
from tpu_pod_exporter.attribution.fake import FakeAttribution, simple_allocation
from tpu_pod_exporter.backend import BackendError
from tpu_pod_exporter.backend.fake import FakeBackend
from tpu_pod_exporter.config import ExporterConfig


def scrape(port):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics", timeout=5) as r:
        return r.read().decode()


def fams_of(port):
    return {f.name: f for f in text_string_to_metric_families(scrape(port))}


@pytest.fixture
def app_with_fakes():
    backend = FakeBackend(chips=2)
    attr = FakeAttribution([simple_allocation("p", ["0", "1"])])
    # Breaker backoff scaled to the 0.02 s test interval (production
    # defaults are seconds): a 10-failure burst opens the breaker and must
    # still drain through half-open probes within the tests' 5 s waits.
    cfg = ExporterConfig(
        port=0, host="127.0.0.1", interval_s=0.02,
        breaker_backoff_s=0.05, breaker_backoff_max_s=0.1,
    )
    app = ExporterApp(cfg, backend=backend, attribution=attr)
    app.start()
    yield app, backend, attr
    app.stop()


def wait_polls(port, n, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fams_of(port)["tpu_exporter_polls"].samples[0].value >= n:
            return
        time.sleep(0.01)
    raise AssertionError(f"never reached {n} polls")


class TestFaultInjection:
    def test_repeated_backend_failures_then_recovery(self, app_with_fakes):
        app, backend, _ = app_with_fakes
        wait_polls(app.port, 3)
        backend.fail_next(10)
        deadline = time.monotonic() + 5
        saw_down = False
        while time.monotonic() < deadline:
            fams = fams_of(app.port)
            if fams["tpu_exporter_up"].samples[0].value == 0:
                saw_down = True
                break
            time.sleep(0.01)
        assert saw_down, "up never dropped during failure burst"
        # exporter keeps serving during the outage
        assert scrape(app.port)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            fams = fams_of(app.port)
            if fams["tpu_exporter_up"].samples[0].value == 1:
                break
            time.sleep(0.01)
        else:
            raise AssertionError("never recovered")
        errs = {
            s.labels["source"]: s.value
            for s in fams["tpu_exporter_poll_errors"].samples
        }
        assert errs.get("device_read", 0) >= 10

    def test_slow_backend_does_not_block_scrapes(self, app_with_fakes):
        app, backend, _ = app_with_fakes

        class SlowSample:
            def __init__(self, inner):
                self.inner = inner

            def __call__(self):
                time.sleep(0.5)
                return self.inner()

        backend.sample = SlowSample(backend.sample)  # type: ignore[method-assign]
        t0 = time.monotonic()
        scrape(app.port)
        assert time.monotonic() - t0 < 0.3, "scrape blocked behind slow poll"

    def test_attribution_flaps(self, app_with_fakes):
        app, _, attr = app_with_fakes
        for _ in range(5):
            attr.fail_next(2)
            time.sleep(0.05)
        fams = fams_of(app.port)
        assert fams["tpu_exporter_up"].samples[0].value == 1
        used = fams["tpu_hbm_used_bytes"].samples
        # last-good attribution still applied through the flaps
        assert all(s.labels["pod"] == "p" for s in used)

    def test_wedged_backend_abandoned_at_phase_deadline(self):
        """A backend that HANGS (not errors) must not park the poll loop:
        the supervised call is abandoned at --phase-deadline-s, up drops,
        scrapes stay fast, and recovery follows once the wedge clears."""
        import threading

        backend = FakeBackend(chips=2)
        cfg = ExporterConfig(
            port=0, host="127.0.0.1", interval_s=0.02,
            phase_deadline_s=0.15,
            breaker_failures=2, breaker_backoff_s=0.05,
            breaker_backoff_max_s=0.1,
        )
        app = ExporterApp(cfg, backend=backend, attribution=FakeAttribution())
        app.start()
        try:
            release = threading.Event()
            inner = backend.sample

            def wedged():
                release.wait(5.0)
                return inner()

            backend.sample = wedged  # type: ignore[method-assign]
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                fams = fams_of(app.port)
                if fams["tpu_exporter_up"].samples[0].value == 0:
                    break
                time.sleep(0.01)
            else:
                raise AssertionError("up never dropped during the wedge")
            # Scrapes serve the stale snapshot instantly.
            t0 = time.monotonic()
            scrape(app.port)
            assert time.monotonic() - t0 < 0.15
            abandoned = {
                s.labels["source"]: s.value
                for s in fams_of(app.port)[
                    "tpu_exporter_source_calls_abandoned"
                ].samples
            }
            assert abandoned.get("device", 0) >= 1
            # Clear the wedge; the breaker probes and the exporter recovers.
            release.set()
            backend.sample = inner  # type: ignore[method-assign]
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                if fams_of(app.port)["tpu_exporter_up"].samples[0].value == 1:
                    break
                time.sleep(0.01)
            else:
                raise AssertionError("never recovered after the wedge cleared")
        finally:
            app.stop()

    def test_poison_backend_exception_type(self, app_with_fakes):
        """Non-BackendError exceptions are still contained by the loop."""
        app, backend, _ = app_with_fakes

        calls = {"n": 0}
        real = backend.sample

        def poison():
            calls["n"] += 1
            if calls["n"] % 2:
                raise ValueError("not a BackendError")
            return real()

        backend.sample = poison  # type: ignore[method-assign]
        time.sleep(0.2)
        fams = fams_of(app.port)
        # exporter alive, errors counted, and good polls still publish
        assert fams["tpu_exporter_polls"].samples[0].value > 0
        errs = {
            s.labels["source"]: s.value
            for s in fams["tpu_exporter_poll_errors"].samples
        }
        assert errs.get("device_read", 0) >= 1
        assert scrape(app.port)


class TestPollLoopThreadDeath:
    """Regression (ISSUE 2 satellite): per-iteration containment catches
    Exception, but a BaseException escaping poll_once kills the loop thread.
    The loop supervisor restarts it ONCE; a second death marks the loop dead
    and /healthz must go 503 immediately (not after health_max_age_s)."""

    def _healthz(self, port):
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=5
            ) as r:
                return r.status, r.read().decode()
        except urllib.error.HTTPError as e:
            return e.code, e.read().decode()

    def test_loop_death_restarts_once_then_healthz_503(self, app_with_fakes):
        app, _, _ = app_with_fakes
        wait_polls(app.port, 2)
        assert self._healthz(app.port)[0] == 200

        def die():
            raise SystemExit("poll thread killed")  # BaseException: escapes containment

        app.collector.poll_once = die  # type: ignore[method-assign]
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            status, body = self._healthz(app.port)
            if status == 503:
                break
            time.sleep(0.01)
        else:
            raise AssertionError("healthz never went 503 after loop death")
        assert "poll loop dead" in body
        assert app.loop.restarts == 1  # exactly one supervised restart
        assert app.loop.dead
        # The exporter still serves (stale) metrics and debug surface.
        assert scrape(app.port)

    def test_single_death_recovers_via_restart(self):
        backend = FakeBackend(chips=1)
        cfg = ExporterConfig(port=0, host="127.0.0.1", interval_s=0.02)
        app = ExporterApp(cfg, backend=backend, attribution=FakeAttribution())
        app.start()
        try:
            real = app.collector.poll_once
            fired = {"n": 0}

            def die_once():
                if fired["n"] == 0:
                    fired["n"] = 1
                    raise SystemExit("one-shot death")
                return real()

            app.collector.poll_once = die_once  # type: ignore[method-assign]
            start_polls = fams_of(app.port)["tpu_exporter_polls"].samples[0].value
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                fams = fams_of(app.port)
                if fams["tpu_exporter_polls"].samples[0].value > start_polls + 2:
                    break
                time.sleep(0.01)
            else:
                raise AssertionError("loop never resumed after one death")
            assert app.loop.restarts == 1
            assert not app.loop.dead
            assert self._healthz(app.port)[0] == 200
        finally:
            app.stop()


class TestBootCrashBackoff:
    """Regression (ISSUE 9 satellite): a crash loop BEFORE the first poll
    ever completed retries with a small exponential delay up to
    boot_max_restarts instead of restart-once-then-dead — a transient
    boot-time device wedge must not turn into a kubelet restart loop."""

    class _Collector:
        def __init__(self, die_first_n: int) -> None:
            self.die_first_n = die_first_n
            self.calls = 0
            self.polls = 0

        def poll_once(self) -> None:
            self.calls += 1
            if self.calls <= self.die_first_n:
                raise SystemExit("boot-time wedge")  # BaseException: escapes
            self.polls += 1

    def _wait(self, pred, timeout=5.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if pred():
                return True
            time.sleep(0.01)
        return False

    def test_transient_boot_wedge_retries_with_backoff(self):
        col = self._Collector(die_first_n=2)
        loop = CollectorLoop(col, interval_s=0.02,
                             boot_restart_backoff_s=0.02)
        t0 = time.monotonic()
        loop.start()
        try:
            assert self._wait(lambda: col.polls >= 3)
            assert not loop.dead
            # Two boot deaths consumed two boot restarts, with the
            # exponential delay actually applied (0.02 + 0.04 s minimum).
            assert time.monotonic() - t0 >= 0.06
            # Recovery resets the budget: the steady-state contract
            # (restart once, then dead) starts fresh after boot clears.
            assert loop.restarts == 0
            assert loop.first_iteration_done
        finally:
            loop.stop()

    def test_persistent_boot_crash_exhausts_budget_then_dead(self):
        col = self._Collector(die_first_n=10**9)
        loop = CollectorLoop(col, interval_s=0.02,
                             boot_restart_backoff_s=0.01)
        loop.start()
        try:
            assert self._wait(lambda: loop.dead)
            assert loop.restarts == loop.boot_max_restarts
            assert not loop.first_iteration_done
        finally:
            loop.stop()

    def test_steady_state_contract_unchanged(self, app_with_fakes):
        # After ANY completed iteration the budget is MAX_RESTARTS (1):
        # the two TestPollLoopThreadDeath tests above pin the behavior;
        # this just pins the selector flag.
        app, _, _ = app_with_fakes
        wait_polls(app.port, 2)
        assert app.loop.first_iteration_done
        assert app.loop.boot_max_restarts == CollectorLoop.BOOT_MAX_RESTARTS
