"""Fault injection at the app level (SURVEY.md §4.5): backends erroring and
timing out mid-poll must degrade the exporter, never kill it — the inversion
of the reference's log.Fatalf-in-loop behavior (main.go:119-137)."""

import time
import urllib.request

import pytest
from prometheus_client.parser import text_string_to_metric_families

from tpu_pod_exporter.app import ExporterApp
from tpu_pod_exporter.attribution.fake import FakeAttribution, simple_allocation
from tpu_pod_exporter.backend import BackendError
from tpu_pod_exporter.backend.fake import FakeBackend
from tpu_pod_exporter.config import ExporterConfig


def scrape(port):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics", timeout=5) as r:
        return r.read().decode()


def fams_of(port):
    return {f.name: f for f in text_string_to_metric_families(scrape(port))}


@pytest.fixture
def app_with_fakes():
    backend = FakeBackend(chips=2)
    attr = FakeAttribution([simple_allocation("p", ["0", "1"])])
    cfg = ExporterConfig(port=0, host="127.0.0.1", interval_s=0.02)
    app = ExporterApp(cfg, backend=backend, attribution=attr)
    app.start()
    yield app, backend, attr
    app.stop()


def wait_polls(port, n, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fams_of(port)["tpu_exporter_polls"].samples[0].value >= n:
            return
        time.sleep(0.01)
    raise AssertionError(f"never reached {n} polls")


class TestFaultInjection:
    def test_repeated_backend_failures_then_recovery(self, app_with_fakes):
        app, backend, _ = app_with_fakes
        wait_polls(app.port, 3)
        backend.fail_next(10)
        deadline = time.monotonic() + 5
        saw_down = False
        while time.monotonic() < deadline:
            fams = fams_of(app.port)
            if fams["tpu_exporter_up"].samples[0].value == 0:
                saw_down = True
                break
            time.sleep(0.01)
        assert saw_down, "up never dropped during failure burst"
        # exporter keeps serving during the outage
        assert scrape(app.port)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            fams = fams_of(app.port)
            if fams["tpu_exporter_up"].samples[0].value == 1:
                break
            time.sleep(0.01)
        else:
            raise AssertionError("never recovered")
        errs = {
            s.labels["source"]: s.value
            for s in fams["tpu_exporter_poll_errors"].samples
        }
        assert errs.get("device_read", 0) >= 10

    def test_slow_backend_does_not_block_scrapes(self, app_with_fakes):
        app, backend, _ = app_with_fakes

        class SlowSample:
            def __init__(self, inner):
                self.inner = inner

            def __call__(self):
                time.sleep(0.5)
                return self.inner()

        backend.sample = SlowSample(backend.sample)  # type: ignore[method-assign]
        t0 = time.monotonic()
        scrape(app.port)
        assert time.monotonic() - t0 < 0.3, "scrape blocked behind slow poll"

    def test_attribution_flaps(self, app_with_fakes):
        app, _, attr = app_with_fakes
        for _ in range(5):
            attr.fail_next(2)
            time.sleep(0.05)
        fams = fams_of(app.port)
        assert fams["tpu_exporter_up"].samples[0].value == 1
        used = fams["tpu_hbm_used_bytes"].samples
        # last-good attribution still applied through the flaps
        assert all(s.labels["pod"] == "p" for s in used)

    def test_poison_backend_exception_type(self, app_with_fakes):
        """Non-BackendError exceptions are still contained by the loop."""
        app, backend, _ = app_with_fakes

        calls = {"n": 0}
        real = backend.sample

        def poison():
            calls["n"] += 1
            if calls["n"] % 2:
                raise ValueError("not a BackendError")
            return real()

        backend.sample = poison  # type: ignore[method-assign]
        time.sleep(0.2)
        fams = fams_of(app.port)
        # exporter alive, errors counted, and good polls still publish
        assert fams["tpu_exporter_polls"].samples[0].value > 0
        errs = {
            s.labels["source"]: s.value
            for s in fams["tpu_exporter_poll_errors"].samples
        }
        assert errs.get("device_read", 0) >= 1
        assert scrape(app.port)
