"""Native renderer parity tests — build libtpumon.so if a toolchain exists,
then assert byte-level behavior matches the Python fallback's contract."""

import ctypes
import math
import shutil
import subprocess
from pathlib import Path

import pytest

from tpu_pod_exporter.metrics import native
from tpu_pod_exporter.metrics.registry import format_value

REPO = Path(__file__).resolve().parent.parent
SO = REPO / "native" / "libtpumon.so"


@pytest.fixture(scope="module")
def built_lib():
    from tpu_pod_exporter import nativelib

    if not SO.exists():
        if shutil.which("g++") is None:
            pytest.skip("no libtpumon.so and no g++ to build it")
        subprocess.run(["make"], cwd=REPO / "native", check=True, capture_output=True)
        # earlier tests may have cached a failed load from before the build
        nativelib.reset_for_tests()
    lib = native.load()
    if lib is None:
        pytest.skip("native lib not loadable")
    return lib


class TestNativeRender:
    def test_parity_with_python_formatting(self, built_lib):
        values = [0.0, 1.0, -1.0, 2.5, 1e18, 1.5e-9, 123456789.0,
                  math.nan, math.inf, -math.inf, 0.1, 1 / 3]
        prefixes = [f'm{{i="{i}"}}'.encode() for i in range(len(values))]
        out = native.render_lines(prefixes, values)
        assert out is not None
        lines = out.decode().strip().split("\n")
        assert len(lines) == len(values)
        for line, prefix, v in zip(lines, prefixes, values):
            got_prefix, got_val = line.rsplit(" ", 1)
            assert got_prefix == prefix.decode()
            # native may choose different digits than repr(); must round-trip
            if math.isnan(v):
                assert got_val == "NaN"
            elif math.isinf(v):
                assert got_val == ("+Inf" if v > 0 else "-Inf")
            else:
                assert float(got_val) == v
                # integral values render without decimal point, like Python's
                if v == int(v) and abs(v) < 2**53:
                    assert got_val == format_value(v)

    def test_empty_input(self, built_lib):
        assert native.render_lines([], []) is None  # caller falls back

    def test_device_scan_against_fake_tree(self, built_lib, tmp_path):
        (tmp_path / "dev").mkdir()
        for i in range(4):
            (tmp_path / "dev" / f"accel{i}").touch()
        (tmp_path / "dev" / "accelfoo").touch()  # non-numeric suffix ignored
        built_lib.tpumon_count_devices.restype = ctypes.c_int
        built_lib.tpumon_count_devices.argtypes = [ctypes.c_char_p]
        assert built_lib.tpumon_count_devices(str(tmp_path).encode()) == 4

    def test_snapshot_encode_uses_native_and_parses(self, built_lib):
        from prometheus_client.parser import text_string_to_metric_families

        from tpu_pod_exporter.metrics.registry import MetricSpec, SnapshotBuilder

        b = SnapshotBuilder()
        spec = MetricSpec(name="m", help="h", label_names=("a",))
        for i in range(100):
            b.add(spec, i * 1.5, (str(i),))
        text = b.build().encode().decode()
        fams = {f.name: f for f in text_string_to_metric_families(text)}
        assert len(fams["m"].samples) == 100
        assert fams["m"].samples[3].value == 4.5
