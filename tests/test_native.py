"""Native renderer parity tests — build libtpumon.so if a toolchain exists,
then assert byte-level behavior matches the Python fallback's contract."""

import ctypes
import math
import shutil
import subprocess
from pathlib import Path

import pytest

from tpu_pod_exporter.metrics import native
from tpu_pod_exporter.metrics.registry import format_value

REPO = Path(__file__).resolve().parent.parent
SO = REPO / "native" / "libtpumon.so"


@pytest.fixture(scope="module")
def built_lib():
    from tpu_pod_exporter import nativelib

    if not SO.exists():
        if shutil.which("g++") is None:
            pytest.skip("no libtpumon.so and no g++ to build it")
        subprocess.run(["make"], cwd=REPO / "native", check=True, capture_output=True)
        # earlier tests may have cached a failed load from before the build
        nativelib.reset_for_tests()
    lib = native.load()
    if lib is None:
        pytest.skip("native lib not loadable")
    return lib


class TestNativeRender:
    def test_parity_with_python_formatting(self, built_lib):
        values = [0.0, 1.0, -1.0, 2.5, 1e18, 1.5e-9, 123456789.0,
                  math.nan, math.inf, -math.inf, 0.1, 1 / 3]
        prefixes = [f'm{{i="{i}"}}'.encode() for i in range(len(values))]
        out = native.render_lines(prefixes, values)
        assert out is not None
        lines = out.decode().strip().split("\n")
        assert len(lines) == len(values)
        for line, prefix, v in zip(lines, prefixes, values):
            got_prefix, got_val = line.rsplit(" ", 1)
            assert got_prefix == prefix.decode()
            # native may choose different digits than repr(); must round-trip
            if math.isnan(v):
                assert got_val == "NaN"
            elif math.isinf(v):
                assert got_val == ("+Inf" if v > 0 else "-Inf")
            else:
                assert float(got_val) == v
                # integral values render without decimal point, like Python's
                if v == int(v) and abs(v) < 2**53:
                    assert got_val == format_value(v)

    def test_empty_input(self, built_lib):
        assert native.render_lines([], []) is None  # caller falls back

    def test_device_scan_against_fake_tree(self, built_lib, tmp_path):
        (tmp_path / "dev").mkdir()
        for i in range(4):
            (tmp_path / "dev" / f"accel{i}").touch()
        (tmp_path / "dev" / "accelfoo").touch()  # non-numeric suffix ignored
        built_lib.tpumon_count_devices.restype = ctypes.c_int
        built_lib.tpumon_count_devices.argtypes = [ctypes.c_char_p]
        assert built_lib.tpumon_count_devices(str(tmp_path).encode()) == 4

    def test_snapshot_encode_uses_native_and_parses(self, built_lib):
        from prometheus_client.parser import text_string_to_metric_families

        from tpu_pod_exporter.metrics.registry import MetricSpec, SnapshotBuilder

        b = SnapshotBuilder()
        spec = MetricSpec(name="m", help="h", label_names=("a",))
        for i in range(100):
            b.add(spec, i * 1.5, (str(i),))
        text = b.build().encode().decode()
        fams = {f.name: f for f in text_string_to_metric_families(text)}
        assert len(fams["m"].samples) == 100
        assert fams["m"].samples[3].value == 4.5


class TestNativeParseLayout:
    """The whole-body native parse must be a strict subset of the Python
    layout parser: identical values on perfect matches, None on anything
    else (incl. shapes where native acceptance would widen the grammar)."""

    NAMES = frozenset({"m", "tpu_x"})

    def _warm(self, text):
        from tpu_pod_exporter.metrics.parse import (
            LayoutCache,
            parse_exposition_layout,
        )

        layout = LayoutCache()
        parse_exposition_layout(text, self.NAMES, layout)
        return layout

    def test_values_match_python(self, built_lib):
        t1 = (
            "# HELP m h\n# TYPE m gauge\n"
            'm{a="1"} 5\nskip{a="1"} 2\nm{a="2"} NaN\n'
            "tpu_x +Inf\nm 2.5 1700000000\n"
        )
        layout = self._warm(t1)
        t2 = t1.replace(" 5\n", " 50\n").replace(" 2.5 ", " -7.25 ")
        got = native.parse_layout(layout, t2)
        assert got is not None
        import math

        assert got[0] == 50.0
        assert math.isnan(got[1])
        assert got[2] == math.inf
        assert got[3] == -7.25

    def test_rejects_what_python_float_rejects(self, built_lib):
        # strtod would take a hex float; Python float() raises — native
        # must decline so the Python parser can raise ParseError.
        layout = self._warm("m 5\n")
        assert native.parse_layout(layout, "m 0x1p3\n") is None

    def test_rejects_brace_tails(self, built_lib):
        layout = self._warm('m{a="1"} 5\nm{a="2"} 6\n')
        assert native.parse_layout(layout, 'm{a="1"} 5 m{a="2"} 6\n') is None

    def test_rejects_shape_changes(self, built_lib):
        layout = self._warm("m 1\nm 2\n")
        assert native.parse_layout(layout, "m 1\n") is None          # shrank
        assert native.parse_layout(layout, "m 1\nm 2\nm 3\n") is None  # grew
        assert native.parse_layout(layout, "m2 1\nm 2\n") is None    # renamed

    def test_arrays_rebuilt_on_churn(self, built_lib):
        from tpu_pod_exporter.metrics.parse import parse_exposition_layout

        layout = self._warm("m 1\n")
        built = layout.native_built_for
        parse_exposition_layout("m 1\nm 2\n", self.NAMES, layout)  # churn
        got = native.parse_layout(layout, "m 3\nm 4\n")
        assert got == [3.0, 4.0]
        assert layout.native_built_for is not built

    def test_end_to_end_fast_path_returns_shared_labels(self, built_lib):
        from tpu_pod_exporter.metrics.parse import parse_exposition_layout

        t = 'm{a="1"} 5\n'
        layout = self._warm(t)
        r1 = parse_exposition_layout(t, self.NAMES, layout)
        r2 = parse_exposition_layout('m{a="1"} 6\n', self.NAMES, layout)
        assert r1[0][1] is r2[0][1]  # labels dict shared via the template
        assert r2[0][2] == 6.0
