"""Test harness setup (SURVEY.md §4).

JAX-touching tests (loadgen, sharding) run on a virtual 8-device CPU mesh so
multi-chip code paths execute with zero TPU hardware.

Platform pinning is two-layer because of this machine's sitecustomize hook
(see ``tpu_pod_exporter.jaxenv``): the hook imports jax at interpreter start
and force-sets ``jax_platforms="axon,cpu"``, so exporting
``JAX_PLATFORMS=cpu`` alone is ignored and any ``jax.devices()`` call —
including ``jax.devices("cpu")`` — would initialize the experimental
TPU-tunnel backend and could hang pytest forever (round 1: 17 always-firing
skips). ``pin_cpu_inprocess`` re-updates the already-imported jax config
*before any backend init*, which restores a pure 8-device CPU world
in-process — the numeric suites then run everywhere, hardware or not.
"""

import os
import subprocess
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

# Lock witness (TPE_LOCK_WITNESS=1): must install BEFORE any package
# module is imported, so module-level and constructor locks are created
# through the patched factories. The CI concurrency leg runs tier-1 under
# this and cross-checks the edge dump against the static lock-order graph
# (`python -m tpu_pod_exporter.analysis --check-witness`).
from tpu_pod_exporter.analysis import witness as _lock_witness  # noqa: E402

_WITNESS = _lock_witness.install_from_env()

# Loop witness (TPE_LOOP_WITNESS=1): hooks server.LOOP_PROBE so every
# callback the event loop runs inline is timed; any stall over
# TPE_LOOP_WITNESS_STALL_MS fails the session (exit 4). Installed after
# the lock witness on purpose — this one imports the server module, and
# the lock factories must already be patched when that import runs.
_LOOP_WITNESS = _lock_witness.install_loop_from_env()

import pytest  # noqa: E402


def _ensure_native_built() -> None:
    """Best-effort build of native/libtpumon.so before tests run.

    A fresh checkout has no compiled artifact; without it every native-path
    test silently exercises only the pure-Python fallback and the aggregator
    scale guards measure the slow parser. One ~2 s g++ invocation at session
    start keeps the tested configuration equal to the deployed one. Failures
    are non-fatal — the fallbacks are themselves under test.
    """
    native = Path(__file__).resolve().parent.parent / "native"
    so = native / "libtpumon.so"
    src = native / "tpumon.cc"
    try:
        if not src.exists() or (
            so.exists() and so.stat().st_mtime >= src.stat().st_mtime
        ):
            return
        subprocess.run(
            ["make", "-C", str(native)],
            check=False,
            capture_output=True,
            timeout=60,
        )
    except Exception:
        pass


_ensure_native_built()

from tpu_pod_exporter.attribution.fake import FakeAttribution, simple_allocation  # noqa: E402
from tpu_pod_exporter.backend.fake import FakeBackend, FakeChipScript  # noqa: E402
from tpu_pod_exporter.jaxenv import pin_cpu_inprocess  # noqa: E402
from tpu_pod_exporter.metrics import SnapshotStore  # noqa: E402

_jax_ok: bool | None = None


def jax_usable() -> bool:
    """Pin this process to an 8-device CPU JAX, once; True on success."""
    global _jax_ok
    if _jax_ok is None:
        _jax_ok = pin_cpu_inprocess(8)
    return _jax_ok


def require_jax():
    if not jax_usable():
        pytest.skip("jax missing or already initialized on a non-CPU platform")


# Pin the config eagerly at collection time — before any test (or import
# side effect) can initialize a backend and freeze the platform choice —
# but skip device verification (creating the XLA CPU client costs seconds)
# so non-JAX test subsets don't pay for it; require_jax() verifies lazily.
pin_cpu_inprocess(8, verify=False)


def pytest_terminal_summary(terminalreporter, exitstatus, config):
    """Witness session report: edge/hold summary, inversions verbatim.
    The edge dump is written either way so CI can cross-check it against
    the static lock-order graph."""
    tr = terminalreporter
    if _WITNESS is not None:
        out = os.environ.get("TPE_LOCK_WITNESS_OUT", "lock-witness.json")
        doc = _WITNESS.dump(out)
        tr.write_sep("-", "lock witness")
        meta = doc["meta"]
        tr.write_line(
            f"lock witness: {meta['locks']} lock site(s), "
            f"{meta['acquisitions']} acquisition(s), {meta['edges']} order "
            f"edge(s); dump -> {out}")
        for inv in doc["inversions"]:
            tr.write_line(f"INVERSION: {inv['detail']}", red=True)
        if doc["long_holds"]:
            worst = max(doc["long_holds"], key=lambda h: h["held_ms"])
            tr.write_line(
                f"{len(doc['long_holds'])} hold(s) over "
                f"{meta['hold_warn_ms']} ms (worst: {worst['site']} "
                f"{worst['held_ms']} ms on {worst['thread']}) — review, "
                f"not a gate")
    if _LOOP_WITNESS is not None:
        out = os.environ.get("TPE_LOOP_WITNESS_OUT", "loop-witness.json")
        doc = _LOOP_WITNESS.dump(out)
        tr.write_sep("-", "loop witness")
        meta = doc["meta"]
        tr.write_line(
            f"loop witness: {meta['callbacks']} distinct inline "
            f"callback(s) timed, {meta['stalls']} stall(s) over "
            f"{meta['threshold_ms']} ms; dump -> {out}")
        for stall in doc["stalls"]:
            tr.write_line(
                f"LOOP STALL: {stall['qualname']} ({stall['kind']}) ran "
                f"{stall['ms']} ms inline on the event loop", red=True)


def pytest_sessionfinish(session, exitstatus):
    """A witnessed lock-order inversion fails the run even if every test
    passed — the interleaving that deadlocks may just not have happened
    this time. A loop stall likewise: one stalled inline callback parks
    every connection, whether or not an assertion noticed."""
    if _WITNESS is not None and _WITNESS.inversions:
        session.exitstatus = 3
    if _LOOP_WITNESS is not None and _LOOP_WITNESS.stalls:
        session.exitstatus = 4


@pytest.fixture
def store():
    return SnapshotStore()


@pytest.fixture
def four_chip_backend():
    """A v4-8-like host: 4 chips, 32 GiB HBM each, some usage."""
    script = FakeChipScript(
        hbm_total_bytes=32 * 1024**3,
        hbm_used_bytes=4 * 1024**3,
        duty_cycle_percent=50.0,
        ici_link_count=6,
        ici_bytes_per_step=1000.0,
    )
    return FakeBackend(chips=4, script=script)


@pytest.fixture
def one_pod_attribution():
    """One pod owning all 4 chips (baseline config 2)."""
    return FakeAttribution(
        [simple_allocation("train-job-0", ["0", "1", "2", "3"], namespace="ml")]
    )
