"""Test harness setup (SURVEY.md §4).

JAX-touching tests (loadgen, sharding) run on a virtual 8-device CPU mesh so
multi-chip code paths execute with zero TPU hardware.

Platform pinning is two-layer because of this machine's sitecustomize hook
(see ``tpu_pod_exporter.jaxenv``): the hook imports jax at interpreter start
and force-sets ``jax_platforms="axon,cpu"``, so exporting
``JAX_PLATFORMS=cpu`` alone is ignored and any ``jax.devices()`` call —
including ``jax.devices("cpu")`` — would initialize the experimental
TPU-tunnel backend and could hang pytest forever (round 1: 17 always-firing
skips). ``pin_cpu_inprocess`` re-updates the already-imported jax config
*before any backend init*, which restores a pure 8-device CPU world
in-process — the numeric suites then run everywhere, hardware or not.
"""

import subprocess
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import pytest  # noqa: E402


def _ensure_native_built() -> None:
    """Best-effort build of native/libtpumon.so before tests run.

    A fresh checkout has no compiled artifact; without it every native-path
    test silently exercises only the pure-Python fallback and the aggregator
    scale guards measure the slow parser. One ~2 s g++ invocation at session
    start keeps the tested configuration equal to the deployed one. Failures
    are non-fatal — the fallbacks are themselves under test.
    """
    native = Path(__file__).resolve().parent.parent / "native"
    so = native / "libtpumon.so"
    src = native / "tpumon.cc"
    try:
        if not src.exists() or (
            so.exists() and so.stat().st_mtime >= src.stat().st_mtime
        ):
            return
        subprocess.run(
            ["make", "-C", str(native)],
            check=False,
            capture_output=True,
            timeout=60,
        )
    except Exception:
        pass


_ensure_native_built()

from tpu_pod_exporter.attribution.fake import FakeAttribution, simple_allocation  # noqa: E402
from tpu_pod_exporter.backend.fake import FakeBackend, FakeChipScript  # noqa: E402
from tpu_pod_exporter.jaxenv import pin_cpu_inprocess  # noqa: E402
from tpu_pod_exporter.metrics import SnapshotStore  # noqa: E402

_jax_ok: bool | None = None


def jax_usable() -> bool:
    """Pin this process to an 8-device CPU JAX, once; True on success."""
    global _jax_ok
    if _jax_ok is None:
        _jax_ok = pin_cpu_inprocess(8)
    return _jax_ok


def require_jax():
    if not jax_usable():
        pytest.skip("jax missing or already initialized on a non-CPU platform")


# Pin the config eagerly at collection time — before any test (or import
# side effect) can initialize a backend and freeze the platform choice —
# but skip device verification (creating the XLA CPU client costs seconds)
# so non-JAX test subsets don't pay for it; require_jax() verifies lazily.
pin_cpu_inprocess(8, verify=False)


@pytest.fixture
def store():
    return SnapshotStore()


@pytest.fixture
def four_chip_backend():
    """A v4-8-like host: 4 chips, 32 GiB HBM each, some usage."""
    script = FakeChipScript(
        hbm_total_bytes=32 * 1024**3,
        hbm_used_bytes=4 * 1024**3,
        duty_cycle_percent=50.0,
        ici_link_count=6,
        ici_bytes_per_step=1000.0,
    )
    return FakeBackend(chips=4, script=script)


@pytest.fixture
def one_pod_attribution():
    """One pod owning all 4 chips (baseline config 2)."""
    return FakeAttribution(
        [simple_allocation("train-job-0", ["0", "1", "2", "3"], namespace="ml")]
    )
