"""Test harness setup (SURVEY.md §4).

JAX-touching tests (loadgen, sharding) run on a virtual 8-device CPU mesh so
multi-chip code paths execute with zero TPU hardware. These env vars must be
set before jax is first imported anywhere in the test process.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import subprocess  # noqa: E402

import pytest  # noqa: E402

from tpu_pod_exporter.attribution.fake import FakeAttribution, simple_allocation  # noqa: E402
from tpu_pod_exporter.backend.fake import FakeBackend, FakeChipScript  # noqa: E402
from tpu_pod_exporter.metrics import SnapshotStore  # noqa: E402

_jax_ok: bool | None = None


def jax_usable() -> bool:
    """Probe JAX in a killable subprocess.

    On this machine an experimental TPU-tunnel plugin initializes during
    backend discovery and can hang the entire process (even
    ``jax.devices('cpu')``) when the tunnel is wedged. An in-process probe
    would hang pytest itself, so probe from a subprocess with a hard
    timeout and skip all JAX-dependent tests when it fails — exporter tests
    must stay green with no (working) accelerator runtime at all.
    """
    global _jax_ok
    if _jax_ok is None:
        try:
            proc = subprocess.run(
                [sys.executable, "-c", "import jax; jax.devices('cpu')"],
                timeout=60,
                capture_output=True,
                env={**os.environ},
            )
            _jax_ok = proc.returncode == 0
        except subprocess.TimeoutExpired:
            _jax_ok = False
    return _jax_ok


def require_jax():
    if not jax_usable():
        pytest.skip("jax runtime unavailable or hung (TPU tunnel wedge)")


@pytest.fixture
def store():
    return SnapshotStore()


@pytest.fixture
def four_chip_backend():
    """A v4-8-like host: 4 chips, 32 GiB HBM each, some usage."""
    script = FakeChipScript(
        hbm_total_bytes=32 * 1024**3,
        hbm_used_bytes=4 * 1024**3,
        duty_cycle_percent=50.0,
        ici_link_count=6,
        ici_bytes_per_step=1000.0,
    )
    return FakeBackend(chips=4, script=script)


@pytest.fixture
def one_pod_attribution():
    """One pod owning all 4 chips (baseline config 2)."""
    return FakeAttribution(
        [simple_allocation("train-job-0", ["0", "1", "2", "3"], namespace="ml")]
    )
