"""Test harness setup (SURVEY.md §4).

JAX-touching tests (loadgen, sharding) run on a virtual 8-device CPU mesh so
multi-chip code paths execute with zero TPU hardware. These env vars must be
set before jax is first imported anywhere in the test process.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import pytest  # noqa: E402

from tpu_pod_exporter.attribution.fake import FakeAttribution, simple_allocation  # noqa: E402
from tpu_pod_exporter.backend.fake import FakeBackend, FakeChipScript  # noqa: E402
from tpu_pod_exporter.metrics import SnapshotStore  # noqa: E402


@pytest.fixture
def store():
    return SnapshotStore()


@pytest.fixture
def four_chip_backend():
    """A v4-8-like host: 4 chips, 32 GiB HBM each, some usage."""
    script = FakeChipScript(
        hbm_total_bytes=32 * 1024**3,
        hbm_used_bytes=4 * 1024**3,
        duty_cycle_percent=50.0,
        ici_link_count=6,
        ici_bytes_per_step=1000.0,
    )
    return FakeBackend(chips=4, script=script)


@pytest.fixture
def one_pod_attribution():
    """One pod owning all 4 chips (baseline config 2)."""
    return FakeAttribution(
        [simple_allocation("train-job-0", ["0", "1", "2", "3"], namespace="ml")]
    )
