"""Deploy-manifest drift guards: the YAML must parse, and every `tpu_*`
metric name referenced in rules/dashboards must exist in the exporter's
(or aggregator's) schema — a renamed metric must fail CI, not silently
break dashboards in production."""

import json
import re
from pathlib import Path

import pytest
import yaml

from tpu_pod_exporter.metrics import schema

DEPLOY = Path(__file__).resolve().parent.parent / "deploy"

METRIC_RE = re.compile(r"\btpu_[a-z0-9_]+\b")

# Strings that look like metric names but aren't (app labels, image names).
NON_METRIC_TOKENS = {"tpu_pod_exporter"}


def schema_metric_names() -> set:
    from tpu_pod_exporter.metrics import HistogramSpec

    names = set()
    for val in vars(schema).values():
        name = getattr(val, "name", None)
        if isinstance(name, str) and name.startswith("tpu_"):
            names.add(name)
        if isinstance(val, HistogramSpec):
            # Histograms expose _bucket/_count/_sum series (the parent
            # family name above is the HELP/TYPE header only).
            base = val.parent.name
            names |= {f"{base}_bucket", f"{base}_count", f"{base}_sum"}
    return names


def recorded_rule_names(doc) -> set:
    """Names minted by Prometheus recording rules in this file."""
    out = set()
    for group in (doc or {}).get("groups", []):
        for rule in group.get("rules", []):
            record = rule.get("record")
            if record:
                out.add(record)
    return out


@pytest.mark.parametrize(
    "manifest",
    ["daemonset.yaml", "aggregator.yaml", "replica.yaml",
     "prometheus-example.yaml", "prometheus-rules.yaml"],
)
def test_manifest_parses(manifest):
    list(yaml.safe_load_all((DEPLOY / manifest).read_text()))


def test_rules_reference_only_schema_metrics():
    doc = yaml.safe_load((DEPLOY / "prometheus-rules.yaml").read_text())
    known = schema_metric_names() | recorded_rule_names(doc) | NON_METRIC_TOKENS
    referenced = set(METRIC_RE.findall((DEPLOY / "prometheus-rules.yaml").read_text()))
    unknown = referenced - known
    assert not unknown, f"rules reference metrics the schema never exports: {unknown}"


def test_grafana_dashboard_references_only_schema_metrics():
    text = (DEPLOY / "grafana-dashboard.json").read_text()
    json.loads(text)  # must be valid JSON at all
    doc = yaml.safe_load((DEPLOY / "prometheus-rules.yaml").read_text())
    known = schema_metric_names() | recorded_rule_names(doc) | NON_METRIC_TOKENS
    unknown = set(METRIC_RE.findall(text)) - known
    assert not unknown, f"dashboard references unknown metrics: {unknown}"


def test_daemonset_probes_match_server_endpoints():
    docs = list(yaml.safe_load_all((DEPLOY / "daemonset.yaml").read_text()))
    ds = next(d for d in docs if d and d.get("kind") == "DaemonSet")
    container = ds["spec"]["template"]["spec"]["containers"][0]
    assert container["readinessProbe"]["httpGet"]["path"] == "/readyz"
    assert container["livenessProbe"]["httpGet"]["path"] == "/healthz"


def test_every_alert_has_a_runbook_entry():
    """An alert without triage guidance pages someone with nowhere to go;
    RUNBOOK.md must gain an entry whenever prometheus-rules.yaml gains an
    alert (and stale entries for deleted alerts should be pruned)."""
    import re

    rules = (DEPLOY / "prometheus-rules.yaml").read_text()
    runbook = (DEPLOY / "RUNBOOK.md").read_text()
    alerts = re.findall(r"- alert: (\w+)", rules)
    assert alerts, "no alerts found — regex or file moved?"
    missing = [a for a in alerts if f"## {a}" not in runbook]
    assert not missing, f"alerts without runbook entries: {missing}"
    documented = re.findall(r"^## (\w+)", runbook, flags=re.M)
    stale = [d for d in documented if d not in alerts]
    assert not stale, f"runbook entries for nonexistent alerts: {stale}"
