"""libtpu metrics backend tests against a scripted RuntimeMetricService
served over real gRPC (SURVEY.md §4.2: fake backends behind real seams)."""

from concurrent import futures

import grpc
import pytest

from tpu_pod_exporter.backend import BackendError
from tpu_pod_exporter.backend.libtpu import (
    DUTY_CYCLE,
    HBM_TOTAL,
    HBM_USAGE,
    ICI_TRANSFERRED,
    LibtpuMetricsBackend,
)
from tpu_pod_exporter.backend.proto import tpu_metric_service_pb2 as pb


def metric_response(rows):
    """rows: [(device_id:int, value:float|int)]"""
    resp = pb.MetricResponse()
    for dev, value in rows:
        m = resp.metric.metrics.add()
        m.attribute.key = "device-id"
        m.attribute.value.int_attr = dev
        if isinstance(value, int):
            m.gauge.as_int = value
        else:
            m.gauge.as_double = value
    return resp


class _FakeMetricService:
    def __init__(self):
        self.tables = {}
        self.fail_metrics = set()
        self.calls = []

    def set(self, metric_name, rows):
        self.tables[metric_name] = metric_response(rows)

    def __call__(self, request, context):
        self.calls.append(request.metric_name)
        if request.metric_name in self.fail_metrics:
            context.abort(grpc.StatusCode.UNAVAILABLE, "injected")
        if request.metric_name not in self.tables:
            context.abort(grpc.StatusCode.NOT_FOUND, "unsupported metric")
        return self.tables[request.metric_name]


@pytest.fixture
def metric_server(tmp_path):
    service = _FakeMetricService()
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=2))
    handler = grpc.method_handlers_generic_handler(
        "tpu.monitoring.runtime.RuntimeMetricService",
        {
            "GetRuntimeMetric": grpc.unary_unary_rpc_method_handler(
                service,
                request_deserializer=pb.MetricRequest.FromString,
                response_serializer=pb.MetricResponse.SerializeToString,
            )
        },
    )
    server.add_generic_rpc_handlers((handler,))
    sock = str(tmp_path / "libtpu.sock")
    server.add_insecure_port(f"unix://{sock}")
    server.start()
    yield service, f"unix://{sock}"
    server.stop(0)


GIB = 1024**3


class TestLibtpuBackend:
    def test_full_sample(self, metric_server):
        service, addr = metric_server
        service.set(HBM_USAGE, [(0, 10 * GIB), (1, 20 * GIB)])
        service.set(HBM_TOTAL, [(0, 32 * GIB), (1, 32 * GIB)])
        service.set(DUTY_CYCLE, [(0, 55.5), (1, 0.0)])
        service.set(ICI_TRANSFERRED, [(0, 1000), (1, 2000)])
        backend = LibtpuMetricsBackend(addr=addr, device_paths={0: "/dev/accel0", 1: "/dev/accel1"})
        sample = backend.sample()
        assert len(sample.chips) == 2
        c0, c1 = sample.chips
        assert c0.info.chip_id == 0 and c0.info.device_path == "/dev/accel0"
        assert c0.hbm_used_bytes == 10 * GIB
        assert c0.hbm_total_bytes == 32 * GIB
        assert c0.tensorcore_duty_cycle_percent == 55.5
        assert c0.ici_links[0].transferred_bytes_total == 1000
        assert c1.info.device_ids == ("1",)
        assert sample.partial_errors == ()
        backend.close()

    def test_duty_cycle_failure_is_partial(self, metric_server):
        service, addr = metric_server
        service.set(HBM_USAGE, [(0, GIB)])
        service.set(HBM_TOTAL, [(0, 32 * GIB)])
        service.fail_metrics.add(DUTY_CYCLE)
        backend = LibtpuMetricsBackend(addr=addr, device_paths={})
        sample = backend.sample()
        assert len(sample.chips) == 1
        assert sample.chips[0].tensorcore_duty_cycle_percent is None
        assert len(sample.partial_errors) == 1
        backend.close()

    def test_ici_unsupported_probed_once(self, metric_server):
        service, addr = metric_server
        service.set(HBM_USAGE, [(0, GIB)])
        service.set(HBM_TOTAL, [(0, 32 * GIB)])
        service.set(DUTY_CYCLE, [(0, 1.0)])
        backend = LibtpuMetricsBackend(addr=addr, device_paths={})
        backend.sample()
        backend.sample()
        assert service.calls.count(ICI_TRANSFERRED) == 1  # not re-probed
        assert backend.sample().chips[0].ici_links == ()
        backend.close()

    def test_hbm_failure_is_fatal_backend_error(self, metric_server):
        service, addr = metric_server
        service.set(HBM_TOTAL, [(0, 32 * GIB)])
        service.fail_metrics.add(HBM_USAGE)
        backend = LibtpuMetricsBackend(addr=addr, device_paths={})
        with pytest.raises(BackendError):
            backend.sample()
        backend.close()

    def test_no_service_raises_backend_error(self, tmp_path):
        backend = LibtpuMetricsBackend(
            addr=f"unix://{tmp_path}/absent.sock", timeout_s=0.2, device_paths={}
        )
        with pytest.raises(BackendError):
            backend.sample()
        backend.close()

    def test_recovers_after_service_restart(self, metric_server):
        service, addr = metric_server
        service.set(HBM_USAGE, [(0, GIB)])
        service.set(HBM_TOTAL, [(0, 32 * GIB)])
        service.set(DUTY_CYCLE, [(0, 1.0)])
        backend = LibtpuMetricsBackend(addr=addr, device_paths={})
        assert backend.sample().chips
        service.fail_metrics.update({HBM_USAGE})
        with pytest.raises(BackendError):
            backend.sample()
        service.fail_metrics.clear()
        assert backend.sample().chips
        backend.close()

    def test_ici_transient_failure_after_success_is_partial_not_latched(
        self, metric_server
    ):
        service, addr = metric_server
        service.set(HBM_USAGE, [(0, GIB)])
        service.set(HBM_TOTAL, [(0, 32 * GIB)])
        service.set(DUTY_CYCLE, [(0, 1.0)])
        service.set(ICI_TRANSFERRED, [(0, 100)])
        backend = LibtpuMetricsBackend(addr=addr, device_paths={})
        assert backend.sample().chips[0].ici_links  # supported
        service.fail_metrics.add(ICI_TRANSFERRED)
        sample = backend.sample()
        assert sample.chips[0].ici_links == ()
        assert any("ICI" in e for e in sample.partial_errors)
        service.fail_metrics.clear()
        assert backend.sample().chips[0].ici_links  # retried, not latched off
        backend.close()

    def test_ici_first_probe_transient_error_not_latched(self, metric_server):
        service, addr = metric_server
        service.set(HBM_USAGE, [(0, GIB)])
        service.set(HBM_TOTAL, [(0, 32 * GIB)])
        service.set(DUTY_CYCLE, [(0, 1.0)])
        service.set(ICI_TRANSFERRED, [(0, 100)])
        service.fail_metrics.add(ICI_TRANSFERRED)  # UNAVAILABLE ≠ unsupported
        backend = LibtpuMetricsBackend(addr=addr, device_paths={})
        sample = backend.sample()
        assert sample.chips[0].ici_links == ()
        assert any("ICI" in e for e in sample.partial_errors)
        service.fail_metrics.clear()
        assert backend.sample().chips[0].ici_links  # recovered on next poll
        backend.close()

    def test_mixed_device_ids_never_collide(self, metric_server):
        service, addr = metric_server
        resp = pb.MetricResponse()
        for dev in ("1", "x"):
            m = resp.metric.metrics.add()
            m.attribute.key = "device-id"
            m.attribute.value.string_attr = dev
            m.gauge.as_int = GIB
        service.tables[HBM_USAGE] = resp
        service.tables[HBM_TOTAL] = resp
        service.set(DUTY_CYCLE, [])
        backend = LibtpuMetricsBackend(addr=addr, device_paths={})
        sample = backend.sample()
        ids = [c.info.chip_id for c in sample.chips]
        assert len(set(ids)) == 2  # unique even with non-numeric device ids
        backend.close()

    def test_string_device_ids(self, metric_server):
        service, addr = metric_server
        resp = pb.MetricResponse()
        m = resp.metric.metrics.add()
        m.attribute.key = "device-id"
        m.attribute.value.string_attr = "7"
        m.gauge.as_int = 5 * GIB
        service.tables[HBM_USAGE] = resp
        service.set(HBM_TOTAL, [(7, 32 * GIB)])
        service.set(DUTY_CYCLE, [(7, 1.0)])
        backend = LibtpuMetricsBackend(addr=addr, device_paths={})
        sample = backend.sample()
        assert sample.chips[0].info.chip_id == 7
        assert sample.chips[0].hbm_total_bytes == 32 * GIB
        backend.close()
