"""libtpu metrics backend tests against a scripted RuntimeMetricService
served over real gRPC (SURVEY.md §4.2: fake backends behind real seams)."""

from concurrent import futures

import grpc
import pytest

from tpu_pod_exporter.backend import BackendError
from tpu_pod_exporter.backend.libtpu import (
    DUTY_CYCLE,
    HBM_TOTAL,
    HBM_USAGE,
    ICI_TRANSFERRED,
    LibtpuMetricsBackend,
)
from tpu_pod_exporter.backend.proto import tpu_metric_service_pb2 as pb


def metric_response(rows):
    """rows: [(device_id:int, value:float|int)]"""
    resp = pb.MetricResponse()
    for dev, value in rows:
        m = resp.metric.metrics.add()
        a = m.attribute.add()
        a.key = "device-id"
        a.value.int_attr = dev
        if isinstance(value, int):
            m.gauge.as_int = value
        else:
            m.gauge.as_double = value
    return resp


def link_response(rows, device_key="device-id", link_key="link-id",
                  link_first=False):
    """rows: [(device_id:int, link_id:int|str, value:int)] — two-attribute
    per-link rows, in either attribute order."""
    resp = pb.MetricResponse()
    for dev, link, value in rows:
        m = resp.metric.metrics.add()
        attrs = []
        d = pb.Attribute(key=device_key)
        d.value.int_attr = dev
        l = pb.Attribute(key=link_key)
        if isinstance(link, int):
            l.value.int_attr = link
        else:
            l.value.string_attr = link
        attrs = [l, d] if link_first else [d, l]
        m.attribute.extend(attrs)
        m.gauge.as_int = value
    return resp


class _FakeMetricService:
    def __init__(self):
        self.tables = {}
        self.fail_metrics = set()
        self.calls = []
        # None = serve UNIMPLEMENTED for ListSupportedMetrics (old runtime);
        # a list = enumeration returns exactly those names.
        self.supported: list | None = None
        self.list_calls = 0

    def set(self, metric_name, rows):
        self.tables[metric_name] = metric_response(rows)

    def __call__(self, request, context):
        self.calls.append(request.metric_name)
        if request.metric_name in self.fail_metrics:
            context.abort(grpc.StatusCode.UNAVAILABLE, "injected")
        if request.metric_name not in self.tables:
            context.abort(grpc.StatusCode.NOT_FOUND, "unsupported metric")
        return self.tables[request.metric_name]

    def list_supported(self, request, context):
        self.list_calls += 1
        if self.supported is None:
            context.abort(grpc.StatusCode.UNIMPLEMENTED, "old runtime")
        resp = pb.ListSupportedMetricsResponse()
        for name in self.supported:
            resp.supported_metric.add().metric_name = name
        return resp


@pytest.fixture
def metric_server(tmp_path):
    service = _FakeMetricService()
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=2))
    handler = grpc.method_handlers_generic_handler(
        "tpu.monitoring.runtime.RuntimeMetricService",
        {
            "GetRuntimeMetric": grpc.unary_unary_rpc_method_handler(
                service,
                request_deserializer=pb.MetricRequest.FromString,
                response_serializer=pb.MetricResponse.SerializeToString,
            ),
            "ListSupportedMetrics": grpc.unary_unary_rpc_method_handler(
                service.list_supported,
                request_deserializer=pb.ListSupportedMetricsRequest.FromString,
                response_serializer=(
                    pb.ListSupportedMetricsResponse.SerializeToString
                ),
            ),
        },
    )
    server.add_generic_rpc_handlers((handler,))
    sock = str(tmp_path / "libtpu.sock")
    server.add_insecure_port(f"unix://{sock}")
    server.start()
    yield service, f"unix://{sock}"
    server.stop(0)


GIB = 1024**3


class TestLibtpuBackend:
    def test_full_sample(self, metric_server):
        service, addr = metric_server
        service.set(HBM_USAGE, [(0, 10 * GIB), (1, 20 * GIB)])
        service.set(HBM_TOTAL, [(0, 32 * GIB), (1, 32 * GIB)])
        service.set(DUTY_CYCLE, [(0, 55.5), (1, 0.0)])
        service.set(ICI_TRANSFERRED, [(0, 1000), (1, 2000)])
        backend = LibtpuMetricsBackend(addr=addr, device_paths={0: "/dev/accel0", 1: "/dev/accel1"})
        sample = backend.sample()
        assert len(sample.chips) == 2
        c0, c1 = sample.chips
        assert c0.info.chip_id == 0 and c0.info.device_path == "/dev/accel0"
        assert c0.hbm_used_bytes == 10 * GIB
        assert c0.hbm_total_bytes == 32 * GIB
        assert c0.tensorcore_duty_cycle_percent == 55.5
        assert c0.ici_links[0].transferred_bytes_total == 1000
        assert c1.info.device_ids == ("1",)
        assert sample.partial_errors == ()
        backend.close()

    def test_duty_cycle_failure_is_partial(self, metric_server):
        service, addr = metric_server
        service.set(HBM_USAGE, [(0, GIB)])
        service.set(HBM_TOTAL, [(0, 32 * GIB)])
        service.fail_metrics.add(DUTY_CYCLE)
        backend = LibtpuMetricsBackend(addr=addr, device_paths={})
        sample = backend.sample()
        assert len(sample.chips) == 1
        assert sample.chips[0].tensorcore_duty_cycle_percent is None
        assert len(sample.partial_errors) == 1
        backend.close()

    def test_ici_unsupported_probed_once(self, metric_server):
        service, addr = metric_server
        service.set(HBM_USAGE, [(0, GIB)])
        service.set(HBM_TOTAL, [(0, 32 * GIB)])
        service.set(DUTY_CYCLE, [(0, 1.0)])
        backend = LibtpuMetricsBackend(addr=addr, device_paths={})
        backend.sample()
        backend.sample()
        assert service.calls.count(ICI_TRANSFERRED) == 1  # not re-probed
        assert backend.sample().chips[0].ici_links == ()
        backend.close()

    def test_total_missing_for_one_device_is_none_plus_partial(
        self, metric_server
    ):
        # VERDICT r4 weak #1: a device in the usage response but absent from
        # the total response must publish NO total (None → series omitted),
        # not a fake 0 — and the gap must be visible as a partial error.
        service, addr = metric_server
        service.set(HBM_USAGE, [(0, 10 * GIB), (1, 20 * GIB)])
        service.set(HBM_TOTAL, [(0, 32 * GIB)])  # device 1 missing
        service.set(DUTY_CYCLE, [(0, 1.0), (1, 2.0)])
        backend = LibtpuMetricsBackend(addr=addr, device_paths={})
        sample = backend.sample()
        c0, c1 = sample.chips
        assert c0.hbm_total_bytes == 32 * GIB
        assert c1.hbm_total_bytes is None
        assert c1.hbm_used_bytes == 20 * GIB  # usage still published
        assert any(
            "total missing" in e and "1" in e for e in sample.partial_errors
        )
        backend.close()

    def test_usage_missing_for_one_device_still_enumerates_it(
        self, metric_server
    ):
        # Code-review r5: the symmetric case — a device served in the total
        # response but omitted from usage must not vanish from the sample
        # (chip presence drives chips/hosts_reporting downstream).
        service, addr = metric_server
        service.set(HBM_USAGE, [(0, 10 * GIB)])
        service.set(HBM_TOTAL, [(0, 32 * GIB), (1, 32 * GIB)])
        service.set(DUTY_CYCLE, [(0, 1.0), (1, 2.0)])
        backend = LibtpuMetricsBackend(addr=addr, device_paths={})
        sample = backend.sample()
        assert len(sample.chips) == 2
        c1 = sample.chips[1]
        assert c1.hbm_used_bytes is None
        assert c1.hbm_total_bytes == 32 * GIB
        assert c1.tensorcore_duty_cycle_percent == 2.0
        assert any("usage missing" in e for e in sample.partial_errors)
        backend.close()

    def test_junk_device_key_in_ici_response_is_dropped_not_enumerated(
        self, metric_server
    ):
        # Code-review r5: a mis-parsed single-attribute ICI row (its value
        # a link id like "x+") must not fabricate a phantom chip nor flip
        # every real chip's id scheme to positional.
        service, addr = metric_server
        service.set(HBM_USAGE, [(0, GIB), (1, GIB)])
        service.set(HBM_TOTAL, [(0, 32 * GIB), (1, 32 * GIB)])
        service.set(DUTY_CYCLE, [(0, 1.0), (1, 2.0)])
        junk = pb.MetricResponse()
        m = junk.metric.metrics.add()
        a = m.attribute.add()
        a.key = "link-id"  # no device attribute at all
        a.value.string_attr = "x+"
        m.gauge.as_int = 123
        service.tables[ICI_TRANSFERRED] = junk
        backend = LibtpuMetricsBackend(
            addr=addr, device_paths={0: "/dev/accel0", 1: "/dev/accel1"}
        )
        sample = backend.sample()
        assert [c.info.chip_id for c in sample.chips] == [0, 1]
        assert sample.chips[0].info.device_path == "/dev/accel0"
        assert any("non-numeric device key" in e for e in sample.partial_errors)
        backend.close()

    def test_empty_device_key_dropped_with_partial_error(self, metric_server):
        # An attribute-less row has no identity to publish under; it is
        # dropped but must be ACCOUNTED (code-review r5: silent drop =
        # silent undercount).
        service, addr = metric_server
        resp = metric_response([(0, GIB)])
        m = resp.metric.metrics.add()  # row with no attributes at all
        m.gauge.as_int = 7
        service.tables[HBM_USAGE] = resp
        service.set(HBM_TOTAL, [(0, 32 * GIB)])
        service.set(DUTY_CYCLE, [(0, 1.0)])
        backend = LibtpuMetricsBackend(addr=addr, device_paths={})
        sample = backend.sample()
        assert [c.info.chip_id for c in sample.chips] == [0]
        assert any("empty device key" in e for e in sample.partial_errors)
        backend.close()

    def test_duty_only_device_still_enumerates(self, metric_server):
        service, addr = metric_server
        service.set(HBM_USAGE, [(0, GIB)])
        service.set(HBM_TOTAL, [(0, 32 * GIB)])
        service.set(DUTY_CYCLE, [(0, 1.0), (1, 2.0)])
        backend = LibtpuMetricsBackend(addr=addr, device_paths={})
        sample = backend.sample()
        assert len(sample.chips) == 2
        assert sample.chips[1].hbm_used_bytes is None
        assert sample.chips[1].tensorcore_duty_cycle_percent == 2.0
        backend.close()

    def test_hbm_failure_is_fatal_backend_error(self, metric_server):
        service, addr = metric_server
        service.set(HBM_TOTAL, [(0, 32 * GIB)])
        service.fail_metrics.add(HBM_USAGE)
        backend = LibtpuMetricsBackend(addr=addr, device_paths={})
        with pytest.raises(BackendError):
            backend.sample()
        backend.close()

    def test_no_service_raises_backend_error(self, tmp_path):
        backend = LibtpuMetricsBackend(
            addr=f"unix://{tmp_path}/absent.sock", timeout_s=0.2, device_paths={}
        )
        with pytest.raises(BackendError):
            backend.sample()
        backend.close()

    def test_recovers_after_service_restart(self, metric_server):
        service, addr = metric_server
        service.set(HBM_USAGE, [(0, GIB)])
        service.set(HBM_TOTAL, [(0, 32 * GIB)])
        service.set(DUTY_CYCLE, [(0, 1.0)])
        backend = LibtpuMetricsBackend(addr=addr, device_paths={})
        assert backend.sample().chips
        service.fail_metrics.update({HBM_USAGE})
        with pytest.raises(BackendError):
            backend.sample()
        service.fail_metrics.clear()
        assert backend.sample().chips
        backend.close()

    def test_ici_transient_failure_after_success_is_partial_not_latched(
        self, metric_server
    ):
        service, addr = metric_server
        service.set(HBM_USAGE, [(0, GIB)])
        service.set(HBM_TOTAL, [(0, 32 * GIB)])
        service.set(DUTY_CYCLE, [(0, 1.0)])
        service.set(ICI_TRANSFERRED, [(0, 100)])
        backend = LibtpuMetricsBackend(addr=addr, device_paths={})
        assert backend.sample().chips[0].ici_links  # supported
        service.fail_metrics.add(ICI_TRANSFERRED)
        sample = backend.sample()
        assert sample.chips[0].ici_links == ()
        assert any("ICI" in e for e in sample.partial_errors)
        service.fail_metrics.clear()
        assert backend.sample().chips[0].ici_links  # retried, not latched off
        backend.close()

    def test_ici_first_probe_transient_error_not_latched(self, metric_server):
        service, addr = metric_server
        service.set(HBM_USAGE, [(0, GIB)])
        service.set(HBM_TOTAL, [(0, 32 * GIB)])
        service.set(DUTY_CYCLE, [(0, 1.0)])
        service.set(ICI_TRANSFERRED, [(0, 100)])
        service.fail_metrics.add(ICI_TRANSFERRED)  # UNAVAILABLE ≠ unsupported
        backend = LibtpuMetricsBackend(addr=addr, device_paths={})
        sample = backend.sample()
        assert sample.chips[0].ici_links == ()
        assert any("ICI" in e for e in sample.partial_errors)
        service.fail_metrics.clear()
        assert backend.sample().chips[0].ici_links  # recovered on next poll
        backend.close()

    def test_mixed_device_ids_never_collide(self, metric_server):
        service, addr = metric_server
        resp = pb.MetricResponse()
        for dev in ("1", "x"):
            m = resp.metric.metrics.add()
            a = m.attribute.add()
            a.key = "device-id"
            a.value.string_attr = dev
            m.gauge.as_int = GIB
        service.tables[HBM_USAGE] = resp
        service.tables[HBM_TOTAL] = resp
        service.set(DUTY_CYCLE, [])
        backend = LibtpuMetricsBackend(addr=addr, device_paths={})
        sample = backend.sample()
        ids = [c.info.chip_id for c in sample.chips]
        assert len(set(ids)) == 2  # unique even with non-numeric device ids
        backend.close()

    def test_string_device_ids(self, metric_server):
        service, addr = metric_server
        resp = pb.MetricResponse()
        m = resp.metric.metrics.add()
        a = m.attribute.add()
        a.key = "device-id"
        a.value.string_attr = "7"
        m.gauge.as_int = 5 * GIB
        service.tables[HBM_USAGE] = resp
        service.set(HBM_TOTAL, [(7, 32 * GIB)])
        service.set(DUTY_CYCLE, [(7, 1.0)])
        backend = LibtpuMetricsBackend(addr=addr, device_paths={})
        sample = backend.sample()
        assert sample.chips[0].info.chip_id == 7
        assert sample.chips[0].hbm_total_bytes == 32 * GIB
        backend.close()


class TestDcnCounters:
    """DCN rides the same discovery ladder as ICI, independently."""

    def _base(self, service):
        service.set(HBM_USAGE, [(0, GIB)])
        service.set(HBM_TOTAL, [(0, 32 * GIB)])
        service.set(DUTY_CYCLE, [(0, 1.0)])

    def test_dcn_per_link_rows(self, metric_server):
        from tpu_pod_exporter.backend.libtpu import DCN_TRANSFERRED

        service, addr = metric_server
        self._base(service)
        service.set(ICI_TRANSFERRED, [(0, 100)])
        service.tables[DCN_TRANSFERRED] = link_response(
            [(0, 0, 5000), (0, 1, 7000)]
        )
        backend = LibtpuMetricsBackend(addr=addr, device_paths={})
        (c0,) = backend.sample().chips
        assert [(l.link, l.transferred_bytes_total) for l in c0.dcn_links] == [
            ("0", 5000.0), ("1", 7000.0)
        ]
        assert c0.ici_links[0].transferred_bytes_total == 100.0
        backend.close()

    def test_dcn_unsupported_independently_of_ici(self, metric_server):
        from tpu_pod_exporter.backend.libtpu import DCN_CANDIDATES

        service, addr = metric_server
        self._base(service)
        service.set(ICI_TRANSFERRED, [(0, 100)])  # ICI served, DCN not
        backend = LibtpuMetricsBackend(addr=addr, device_paths={})
        backend.sample()
        backend.sample()
        (c0,) = backend.sample().chips
        assert c0.ici_links and c0.dcn_links == ()
        # DCN candidates probed exactly once, then latched off.
        for name in DCN_CANDIDATES:
            assert service.calls.count(name) == 1
        backend.close()

    def test_enumeration_confirms_dcn(self, metric_server):
        from tpu_pod_exporter.backend.libtpu import DCN_CANDIDATES

        service, addr = metric_server
        self._base(service)
        alt = DCN_CANDIDATES[1]
        service.supported = [HBM_USAGE, HBM_TOTAL, DUTY_CYCLE, alt]
        service.set(alt, [(0, 999)])
        backend = LibtpuMetricsBackend(addr=addr, device_paths={})
        (c0,) = backend.sample().chips
        assert c0.dcn_links[0].transferred_bytes_total == 999.0
        assert service.list_calls == 1  # shared with the ICI discovery
        backend.close()


class TestIciDiscovery:
    """ICI metric-name discovery: enumeration first, candidate probes as
    fallback (VERDICT r1 #3 — stop hard-coding a guessed name)."""

    def _base(self, service):
        service.set(HBM_USAGE, [(0, GIB)])
        service.set(HBM_TOTAL, [(0, 32 * GIB)])
        service.set(DUTY_CYCLE, [(0, 1.0)])

    def test_enumeration_confirms_candidate(self, metric_server):
        from tpu_pod_exporter.backend.libtpu import ICI_CANDIDATES

        service, addr = metric_server
        self._base(service)
        alt = ICI_CANDIDATES[1]  # not the default guess
        service.supported = [HBM_USAGE, HBM_TOTAL, DUTY_CYCLE, alt]
        service.set(alt, [(0, 777)])
        backend = LibtpuMetricsBackend(addr=addr, device_paths={})
        sample = backend.sample()
        assert sample.chips[0].ici_links[0].transferred_bytes_total == 777
        # the wrong guesses were never queried
        assert ICI_TRANSFERRED not in service.calls
        backend.sample()
        assert service.list_calls == 1  # discovery ran once
        backend.close()

    def test_enumeration_without_ici_latches_off(self, metric_server):
        service, addr = metric_server
        self._base(service)
        service.supported = [HBM_USAGE, HBM_TOTAL, DUTY_CYCLE]
        backend = LibtpuMetricsBackend(addr=addr, device_paths={})
        backend.sample()
        backend.sample()
        assert service.list_calls == 1
        assert ICI_TRANSFERRED not in service.calls  # no blind probing
        assert backend.sample().chips[0].ici_links == ()
        backend.close()

    def test_probe_fallback_tries_candidates_in_order(self, metric_server):
        from tpu_pod_exporter.backend.libtpu import ICI_CANDIDATES

        service, addr = metric_server
        self._base(service)
        alt = ICI_CANDIDATES[2]
        service.set(alt, [(0, 42)])  # enumeration UNIMPLEMENTED (default)
        backend = LibtpuMetricsBackend(addr=addr, device_paths={})
        sample = backend.sample()
        assert sample.chips[0].ici_links[0].transferred_bytes_total == 42
        # earlier candidates were each probed exactly once, then dropped
        assert service.calls.count(ICI_CANDIDATES[0]) == 1
        assert service.calls.count(ICI_CANDIDATES[1]) == 1
        backend.sample()
        assert service.calls.count(ICI_CANDIDATES[0]) == 1
        backend.close()

    def test_confirmed_name_vanishing_triggers_rediscovery(self, metric_server):
        service, addr = metric_server
        self._base(service)
        service.supported = [HBM_USAGE, HBM_TOTAL, DUTY_CYCLE, ICI_TRANSFERRED]
        service.set(ICI_TRANSFERRED, [(0, 5)])
        backend = LibtpuMetricsBackend(addr=addr, device_paths={})
        assert backend.sample().chips[0].ici_links
        del service.tables[ICI_TRANSFERRED]  # runtime swap: now NOT_FOUND
        service.supported = [HBM_USAGE, HBM_TOTAL, DUTY_CYCLE]
        assert backend.sample().chips[0].ici_links == ()
        backend.sample()
        assert service.list_calls == 2  # re-discovered once, then latched off
        backend.close()


    def test_inconsistent_runtime_does_not_flap(self, metric_server):
        # Enumeration lists the ICI name but GetRuntimeMetric NOT_FOUNDs it
        # (stale table): one vanish cycle, then latch off — no per-poll
        # rediscover/fail loop.
        service, addr = metric_server
        self._base(service)
        # Listed (alongside the really-served base metrics, so enumeration
        # passes the round-4 sanity check) but never served:
        service.supported = [HBM_USAGE, HBM_TOTAL, DUTY_CYCLE, ICI_TRANSFERRED]
        backend = LibtpuMetricsBackend(addr=addr, device_paths={})
        backend.sample()  # confirm -> query NOT_FOUND -> vanish
        backend.sample()  # rediscover without the vanished name -> latch off
        backend.sample()
        backend.sample()
        assert service.list_calls == 2  # no further discovery attempts
        assert backend.sample().chips[0].ici_links == ()
        backend.close()

    def test_probe_fallback_first_poll_queries_confirmed_name_once(
        self, metric_server
    ):
        service, addr = metric_server
        self._base(service)
        service.set(ICI_TRANSFERRED, [(0, 9)])  # enumeration UNIMPLEMENTED
        backend = LibtpuMetricsBackend(addr=addr, device_paths={})
        sample = backend.sample()
        assert sample.chips[0].ici_links[0].transferred_bytes_total == 9
        assert service.calls.count(ICI_TRANSFERRED) == 1  # probe rows reused
        backend.sample()
        assert service.calls.count(ICI_TRANSFERRED) == 2
        backend.close()


class TestProbeTool:
    def test_probe_with_enumeration(self, metric_server):
        from tpu_pod_exporter.probe import probe

        service, addr = metric_server
        service.supported = [HBM_USAGE, "custom.metric"]
        service.set(HBM_USAGE, [(0, GIB), (1, 2 * GIB)])
        report = probe(addr, timeout_s=2.0)
        assert report["reachable"] is True
        assert report["supported"] == [HBM_USAGE, "custom.metric"]
        assert report["metrics"][HBM_USAGE]["rows"] == 2
        assert report["metrics"][HBM_USAGE]["attr_keys"] == ["device-id"]
        assert report["metrics"][HBM_USAGE]["gauge_types"] == ["as_int"]
        assert report["errors"]["custom.metric"].startswith("StatusCode.NOT_FOUND")

    def test_probe_without_enumeration_uses_known_names(self, metric_server):
        from tpu_pod_exporter.probe import probe

        service, addr = metric_server
        service.set(HBM_USAGE, [(0, GIB)])
        report = probe(addr, timeout_s=2.0)
        assert report["reachable"] is True
        assert report["supported"] is None
        assert HBM_USAGE in report["metrics"]
        assert HBM_TOTAL in report["errors"]  # NOT_FOUND recorded, not fatal

    def test_probe_unreachable_exit_code(self, tmp_path):
        from tpu_pod_exporter.probe import main

        rc = main(["--addr", f"unix://{tmp_path}/absent.sock", "--timeout-s", "0.2"])
        assert rc == 2

    def test_probe_cli_writes_fixture(self, metric_server, tmp_path, capsys):
        from tpu_pod_exporter.probe import main

        service, addr = metric_server
        service.supported = [HBM_USAGE]
        service.set(HBM_USAGE, [(0, GIB)])
        out = tmp_path / "fixture.json"
        rc = main(["--addr", addr, "--out", str(out)])
        assert rc == 0
        import json

        doc = json.loads(out.read_text())
        assert doc["supported"] == [HBM_USAGE]
        assert json.loads(capsys.readouterr().out) == doc

    def test_probe_string_gauge_stays_json_strict(self, metric_server):
        # A string/unset gauge must not become float NaN (json.dumps would
        # emit the non-RFC literal `NaN` into the committed fixture).
        import json

        from tpu_pod_exporter.probe import probe

        service, addr = metric_server
        resp = pb.MetricResponse()
        m = resp.metric.metrics.add()
        a = m.attribute.add()
        a.key = "device-id"
        a.value.int_attr = 0
        m.gauge.as_string = "v5e"
        n = resp.metric.metrics.add()
        b = n.attribute.add()
        b.key = "device-id"
        b.value.int_attr = 1  # gauge left unset
        service.tables["chip.kind"] = resp
        service.supported = ["chip.kind"]
        report = probe(addr, timeout_s=2.0)
        text = json.dumps(report)  # strict parse must round-trip
        doc = json.loads(text)
        samples = doc["metrics"]["chip.kind"]["sample"]
        assert samples[0]["value"] == "v5e"
        assert samples[1]["value"] is None


class TestPerLinkIci:
    """Per-link ICI through the production proto path (BASELINE config 4's
    headline; VERDICT r3 #3): two-attribute rows in either order become real
    `link` labels; single-attribute rows keep the degraded link="all"."""

    def _base(self, service):
        service.set(HBM_USAGE, [(0, GIB), (1, GIB)])
        service.set(HBM_TOTAL, [(0, 32 * GIB), (1, 32 * GIB)])
        service.set(DUTY_CYCLE, [(0, 1.0), (1, 1.0)])

    ROWS = [(0, 0, 100), (0, 1, 200), (1, 0, 300), (1, 1, 400)]

    @pytest.mark.parametrize("link_first", [False, True])
    def test_two_attribute_rows_either_order(self, metric_server, link_first):
        service, addr = metric_server
        self._base(service)
        service.tables[ICI_TRANSFERRED] = link_response(
            self.ROWS, link_first=link_first
        )
        backend = LibtpuMetricsBackend(addr=addr, device_paths={})
        sample = backend.sample()
        c0, c1 = sample.chips
        assert [(l.link, l.transferred_bytes_total) for l in c0.ici_links] == [
            ("0", 100.0), ("1", 200.0)
        ]
        assert [(l.link, l.transferred_bytes_total) for l in c1.ici_links] == [
            ("0", 300.0), ("1", 400.0)
        ]
        backend.close()

    def test_unrecognized_keys_fall_back_positionally(self, metric_server):
        service, addr = metric_server
        self._base(service)
        # Keys matching no hint: first attribute is the device, second the
        # link — the only sane default for an unknown runtime vocabulary.
        service.tables[ICI_TRANSFERRED] = link_response(
            [(0, 3, 50)], device_key="idx", link_key="lane"
        )
        backend = LibtpuMetricsBackend(addr=addr, device_paths={})
        (c0, _c1) = backend.sample().chips
        assert [(l.link, l.transferred_bytes_total) for l in c0.ici_links] == [
            ("3", 50.0)
        ]
        backend.close()

    def test_positional_fallback_logs_once(
        self, metric_server, monkeypatch, caplog
    ):
        # VERDICT r4 weak #4: the silent positional assumption must leave
        # one diagnosable log line (and only one — it's the hot parse path).
        import logging

        from tpu_pod_exporter.backend import libtpu as libtpu_mod

        monkeypatch.setattr(libtpu_mod, "_positional_fallback_logged", False)
        service, addr = metric_server
        self._base(service)
        service.tables[ICI_TRANSFERRED] = link_response(
            [(0, 3, 50), (1, 4, 60)], device_key="idx", link_key="lane"
        )
        backend = LibtpuMetricsBackend(addr=addr, device_paths={})
        with caplog.at_level(logging.WARNING, "tpu_pod_exporter.backend.libtpu"):
            backend.sample()
            backend.sample()  # second poll: no second warning
        warnings = [
            r for r in caplog.records if "positional" in r.message
        ]
        assert len(warnings) == 1
        assert "idx" in warnings[0].message or "lane" in warnings[0].message
        backend.close()

    def test_string_link_ids(self, metric_server):
        service, addr = metric_server
        self._base(service)
        service.tables[ICI_TRANSFERRED] = link_response(
            [(0, "x+", 10), (0, "x-", 20)]
        )
        backend = LibtpuMetricsBackend(addr=addr, device_paths={})
        (c0, _c1) = backend.sample().chips
        assert {l.link for l in c0.ici_links} == {"x+", "x-"}
        backend.close()

    def test_end_to_end_link_labels_in_bandwidth_series(self, metric_server):
        """Fake gRPC server → libtpu backend → collector → per-link
        tpu_ici_link_bandwidth_bytes_per_second{link="..."}."""
        from tpu_pod_exporter.attribution.fake import FakeAttribution
        from tpu_pod_exporter.collector import Collector
        from tpu_pod_exporter.metrics import SnapshotStore
        from tpu_pod_exporter.topology import HostTopology

        service, addr = metric_server
        self._base(service)
        service.tables[ICI_TRANSFERRED] = link_response(self.ROWS)
        backend = LibtpuMetricsBackend(
            addr=addr, device_paths={0: "/dev/accel0", 1: "/dev/accel1"}
        )
        store = SnapshotStore()
        fake_now = [0.0]
        c = Collector(
            backend,
            FakeAttribution(),
            store,
            topology=HostTopology(
                accelerator="v5e-8", slice_name="s0", host="h0", worker_id="0"
            ),
            clock=lambda: fake_now[0],
        )
        c.poll_once()
        # Advance counters and the clock: 2 s, +200 bytes on dev0 link1.
        service.tables[ICI_TRANSFERRED] = link_response(
            [(0, 0, 100), (0, 1, 400), (1, 0, 300), (1, 1, 400)]
        )
        fake_now[0] += 2.0
        c.poll_once()
        snap = store.current()
        labels = {
            "chip_id": "0", "device_path": "/dev/accel0",
            "accelerator": "v5e-8", "slice_name": "s0", "host": "h0",
            "worker_id": "0", "pod": "", "namespace": "", "container": "",
            "link": "1",
        }
        assert snap.value("tpu_ici_transferred_bytes_total", labels) == 400.0
        assert snap.value("tpu_ici_link_bandwidth_bytes_per_second", labels) == 100.0
        # The degraded link="all" shape is NOT emitted when real links exist.
        assert snap.value(
            "tpu_ici_transferred_bytes_total", {**labels, "link": "all"}
        ) is None
        backend.close()


class TestEnumerationSanityCheck:
    """ADVICE r2 #1: a wire-shape-mismatched ListSupportedMetrics parses as
    an empty/garbled list; trusting it would silently latch ICI off. The
    check: HBM_USAGE was served seconds ago, so any enumeration omitting it
    is unreliable and discovery must fall through to direct probes."""

    def _base(self, service):
        service.set(HBM_USAGE, [(0, GIB)])
        service.set(HBM_TOTAL, [(0, 32 * GIB)])
        service.set(DUTY_CYCLE, [(0, 1.0)])

    def test_empty_enumeration_falls_through_to_probe(self, metric_server):
        service, addr = metric_server
        self._base(service)
        service.supported = []  # mismatched schema parses to nothing
        service.set(ICI_TRANSFERRED, [(0, 123)])
        backend = LibtpuMetricsBackend(addr=addr, device_paths={})
        sample = backend.sample()
        # ICI survived: probe path found the metric enumeration "denied".
        assert sample.chips[0].ici_links[0].transferred_bytes_total == 123
        backend.close()

    def test_garbled_enumeration_falls_through_to_probe(self, metric_server):
        service, addr = metric_server
        self._base(service)
        service.supported = ["unrelated.metric.name"]  # omits HBM_USAGE
        service.set(ICI_TRANSFERRED, [(0, 7)])
        backend = LibtpuMetricsBackend(addr=addr, device_paths={})
        assert backend.sample().chips[0].ici_links[0].transferred_bytes_total == 7
        backend.close()

    def test_trusted_enumeration_still_avoids_blind_probes(self, metric_server):
        service, addr = metric_server
        self._base(service)
        service.supported = [HBM_USAGE, HBM_TOTAL, DUTY_CYCLE]
        backend = LibtpuMetricsBackend(addr=addr, device_paths={})
        backend.sample()
        assert ICI_TRANSFERRED not in service.calls  # enumeration trusted
        backend.close()


class TestProbeToolPerLink:
    def test_probe_records_link_attribute_in_fixture(self, metric_server):
        """A runtime serving two-attribute ICI rows must ground-truth the
        link axis into the committed fixture (attr_keys + per-row link),
        so a future real TPU VM probe captures the per-link shape."""
        import json

        from tpu_pod_exporter.probe import probe

        service, addr = metric_server
        service.set(HBM_USAGE, [(0, GIB)])
        service.supported = [HBM_USAGE, ICI_TRANSFERRED]
        service.tables[ICI_TRANSFERRED] = link_response(
            [(0, 0, 11), (0, 1, 22)]
        )
        report = probe(addr, timeout_s=2.0)
        json.dumps(report)  # fixture must stay strict-JSON
        m = report["metrics"][ICI_TRANSFERRED]
        assert m["rows"] == 2
        assert m["attr_keys"] == ["device-id", "link-id"]
        assert m["sample"] == [
            {"attr": "0", "link": "0", "value": 11},
            {"attr": "0", "link": "1", "value": 22},
        ]
