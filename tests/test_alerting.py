"""Native alerting plane (tpu_pod_exporter.alerting).

The unit-level half of the acceptance story (the end-to-end half is the
scenario engine's ``alert_partition`` drill, ``make alert-demo``): the
rule grammar parses with actionable startup errors and round-trips
through the canonical renderer; the per-instance state machine walks
pending → firing → resolved with ``for`` debounce and ``keep_firing``
flap damping; suppression holds a presumed-false-positive down and
counts every withheld round; the notifier delivers each transition
exactly once across restarts, skips poison bodies, and sheds oldest
when the backlog cap trips; the sidecar, status footer, stream rows and
self-metric emission all agree with the evaluator's state.
"""

import json
import time
import urllib.error

import pytest

from tpu_pod_exporter.alerting import (
    FIRING,
    PENDING,
    RESOLVED,
    SEQ_HEADER,
    AlertEvaluator,
    AlertNotifier,
    alert_status_summary,
    import_prometheus_rules,
    load_alert_rules_file,
    main,
    parse_alert_rules,
    parse_duration,
    parse_expr,
    render_rules,
    render_template,
)
from tpu_pod_exporter.egress import build_breaker
from tpu_pod_exporter.metrics import schema
from tpu_pod_exporter.metrics.registry import SnapshotBuilder
from tpu_pod_exporter.status import alert_line


def wait_for(predicate, timeout=10.0, interval=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


# ---------------------------------------------------------------- grammar


RULES_TEXT = """\
# comments and blank lines are ignored
alert LeafDown = tpu_root_leaf_up == 0
    for 20s
    keep_firing 10s
    labels(severity="page", team="ml-infra")
    annotations(summary="leaf {{ $labels.leaf }} down (value {{ $value }})")
    suppress(tpu_root_leaf_partition_suspected == 1)

alert Partitioned = tpu_root_leaf_partition_suspected == 1
    labels(severity="page")
"""


class TestParseAlertRules:
    def test_parses_clauses(self):
        rules = parse_alert_rules(RULES_TEXT)
        assert [r.name for r in rules] == ["LeafDown", "Partitioned"]
        r = rules[0]
        assert r.for_s == 20.0
        assert r.keep_firing_s == 10.0
        assert dict(r.labels) == {"severity": "page", "team": "ml-infra"}
        assert "{{ $labels.leaf }}" in dict(r.annotations)["summary"]
        assert r.suppress is not None
        assert rules[1].for_s == 0.0 and rules[1].suppress is None

    def test_render_round_trip(self):
        rules = parse_alert_rules(RULES_TEXT)
        again = parse_alert_rules(render_rules(rules))
        def key(r):
            return (r.name, r.for_s, r.keep_firing_s, r.labels,
                    r.annotations, r.expr.render(),
                    r.suppress.render() if r.suppress else "")
        assert [key(r) for r in rules] == [key(r) for r in again]
        # Rendering is canonical: render(parse(render(x))) is a fixpoint.
        assert render_rules(again) == render_rules(rules)

    def test_duplicate_name_names_first_definition(self):
        text = ("alert A = tpu_root_leaf_up == 0\n"
                "alert A = tpu_root_leaf_up == 1\n")
        with pytest.raises(ValueError, match=r"line 2.*first defined on line 1"):
            parse_alert_rules(text)

    def test_unknown_metric_is_a_startup_error(self):
        with pytest.raises(ValueError, match=r"unknown metric 'tpu_nope'"):
            parse_alert_rules("alert A = tpu_nope == 0\n")

    def test_known_names_override_admits_drill_families(self):
        rules = parse_alert_rules("alert A = synth_gauge > 1\n",
                                  known_names=frozenset({"synth_gauge"}))
        assert rules[0].name == "A"

    def test_unknown_clause_lists_what_is_accepted(self):
        text = "alert A = tpu_root_leaf_up == 0\n    severity page\n"
        with pytest.raises(ValueError, match=r"for <dur> \| keep_firing"):
            parse_alert_rules(text)

    def test_clause_outside_block(self):
        with pytest.raises(ValueError, match="outside any alert block"):
            parse_alert_rules("    for 5s\n")

    def test_bad_label_kv(self):
        text = "alert A = tpu_root_leaf_up == 0\n    labels(severity=page)\n"
        with pytest.raises(ValueError, match='want \nkey="value"'.replace("\n", "")):
            parse_alert_rules(text)

    def test_colon_names_pass_as_recording_outputs(self):
        rules = parse_alert_rules("alert A = fleet:hbm:by_slice > 10\n")
        assert rules[0].expr_text.startswith("fleet:hbm:by_slice")

    def test_external_up_is_admitted(self):
        parse_alert_rules('alert A = up{job="tpu-pod-exporter"} == 0\n')

    def test_load_file_propagates_errors(self, tmp_path):
        p = tmp_path / "rules.txt"
        p.write_text("alert A = tpu_nope == 0\n")
        with pytest.raises(ValueError):
            load_alert_rules_file(str(p))
        with pytest.raises(OSError):
            load_alert_rules_file(str(tmp_path / "absent.txt"))

    @pytest.mark.parametrize("text,seconds", [
        ("30s", 30.0), ("5m", 300.0), ("2h", 7200.0), ("1d", 86400.0),
    ])
    def test_durations(self, text, seconds):
        assert parse_duration(text) == seconds

    def test_template_interpolation(self):
        out = render_template("leaf {{ $labels.leaf }} at {{ $value }}",
                              {"leaf": "b"}, 0.5)
        assert out == "leaf b at 0.5"


# ------------------------------------------------------------- evaluation


def leaf_snapshot(up, suspected=()):
    """Build a root-shaped snapshot: {(shard, leaf): value} per family."""
    b = SnapshotBuilder()
    for (shard, leaf), v in dict(up).items():
        b.add(schema.TPU_ROOT_LEAF_UP, v, (shard, leaf))
    for (shard, leaf), v in dict(suspected).items():
        b.add(schema.TPU_ROOT_LEAF_PARTITION_SUSPECTED, v, (shard, leaf))
    return b.build()


def eval_leaf_expr(text, up, suspected=()):
    from tpu_pod_exporter.alerting import EvalContext
    ev = AlertEvaluator(parse_alert_rules(f"alert X = {text}\n"))
    snap = leaf_snapshot(up, suspected)
    vectors = ev._ingest(snap, 0.0)
    ctx = EvalContext(0.0, lambda name: vectors.get(name, {}),
                      lambda name, w: {})
    return ev.rules[0].expr.evaluate(ctx)


class TestExpressions:
    def test_comparison_filters_vector(self):
        out = eval_leaf_expr("tpu_root_leaf_up == 0",
                             {("0", "a"): 0.0, ("0", "b"): 1.0})
        assert set(out) == {(("leaf", "a"), ("shard", "0"))}

    def test_label_selector(self):
        out = eval_leaf_expr('tpu_root_leaf_up{shard="1"} == 0',
                             {("0", "a"): 0.0, ("1", "b"): 0.0})
        assert set(out) == {(("leaf", "b"), ("shard", "1"))}

    def test_aggregation(self):
        out = eval_leaf_expr("sum by (shard) (tpu_root_leaf_up) < 1",
                             {("0", "a"): 0.0, ("0", "b"): 0.0,
                              ("1", "c"): 1.0})
        assert set(out) == {(("shard", "0"),)}

    def test_arithmetic_against_scalar(self):
        out = eval_leaf_expr("tpu_root_leaf_up * 100 >= 100",
                             {("0", "a"): 1.0, ("0", "b"): 0.0})
        assert out == {(("leaf", "a"), ("shard", "0")): 100.0}


class TestStateMachine:
    RULES = parse_alert_rules(RULES_TEXT)

    def test_pending_then_firing_then_resolved(self, tmp_path):
        ev = AlertEvaluator(self.RULES, alert_dir=str(tmp_path))
        down = leaf_snapshot({("0", "a"): 0.0, ("0", "b"): 1.0})
        up = leaf_snapshot({("0", "a"): 1.0, ("0", "b"): 1.0})

        r = ev.evaluate_round(down, now_wall=0.0)
        assert (r["firing"], r["pending"]) == (0, 1)
        assert ev.counts() == (0, 1)
        assert [t["to"] for t in ev.transitions()] == [PENDING]

        ev.evaluate_round(down, now_wall=10.0)          # still pending
        assert ev.counts() == (0, 1)

        ev.evaluate_round(down, now_wall=20.0)          # for 20s elapsed
        assert ev.counts() == (1, 0)
        rows = ev.rows()
        assert [(row["labels"]["alertname"], row["labels"]["leaf"],
                 row["state"]) for row in rows] == [("LeafDown", "a", FIRING)]
        assert rows[0]["active_since"] == 0.0
        assert rows[0]["state_since"] == 20.0

        ev.evaluate_round(up, now_wall=25.0)            # keep_firing damps
        assert ev.counts() == (1, 0)

        ev.evaluate_round(up, now_wall=35.0)            # dip outlived 10s
        assert ev.counts() == (0, 0)
        assert [t["to"] for t in ev.transitions()] == \
               [PENDING, FIRING, RESOLVED]

    def test_pending_recovery_is_silent(self, tmp_path):
        ev = AlertEvaluator(self.RULES, alert_dir=str(tmp_path))
        ev.evaluate_round(leaf_snapshot({("0", "a"): 0.0}), now_wall=0.0)
        ev.evaluate_round(leaf_snapshot({("0", "a"): 1.0}), now_wall=5.0)
        # Prometheus convention: pending → inactive makes no noise.
        assert ev.counts() == (0, 0)
        assert [t["to"] for t in ev.transitions()] == [PENDING]

    def test_zero_for_fires_in_one_round(self):
        ev = AlertEvaluator(self.RULES)
        r = ev.evaluate_round(
            leaf_snapshot({}, suspected={("0", "a"): 1.0}), now_wall=0.0)
        assert r["firing"] == 1
        assert [t["to"] for t in ev.transitions()] == [PENDING, FIRING]

    def test_suppression_holds_and_counts(self):
        ev = AlertEvaluator(self.RULES)
        down_suspected = leaf_snapshot({("0", "a"): 0.0},
                                       suspected={("0", "a"): 1.0})
        for now in (0.0, 20.0, 40.0):
            ev.evaluate_round(down_suspected, now_wall=now)
        # Partitioned fires; LeafDown never even pends — and every
        # withheld round is counted, not silent.
        fired = {t["alert"] for t in ev.transitions() if t["to"] == FIRING}
        assert fired == {"Partitioned"}
        assert ev.stats()["suppressed_total"] == {"LeafDown": 3}

    def test_suppression_off_is_the_double_page(self):
        ev = AlertEvaluator(self.RULES, suppression=False)
        down_suspected = leaf_snapshot({("0", "a"): 0.0},
                                       suspected={("0", "a"): 1.0})
        for now in (0.0, 20.0):
            ev.evaluate_round(down_suspected, now_wall=now)
        fired = {t["alert"] for t in ev.transitions() if t["to"] == FIRING}
        assert fired == {"Partitioned", "LeafDown"}

    def test_suppression_is_label_scoped(self):
        ev = AlertEvaluator(self.RULES)
        # Leaf a is suspected-partitioned; leaf b is plain down.
        snap = leaf_snapshot({("0", "a"): 0.0, ("1", "b"): 0.0},
                             suspected={("0", "a"): 1.0})
        for now in (0.0, 20.0):
            ev.evaluate_round(snap, now_wall=now)
        down_rows = [row for row in ev.rows()
                     if row["labels"]["alertname"] == "LeafDown"]
        assert [(row["labels"]["leaf"], row["state"])
                for row in down_rows] == [("b", FIRING)]

    def test_bad_rule_degrades_not_crashes(self):
        rules = parse_alert_rules(
            "alert Scalar = 1 > 0\n"          # top-level scalar: eval error
            "alert Ok = tpu_root_leaf_up == 0\n")
        ev = AlertEvaluator(rules)
        r = ev.evaluate_round(leaf_snapshot({("0", "a"): 0.0}),
                              now_wall=0.0)
        assert r["eval_failures"] == 1
        assert ev.counts() == (1, 0)          # the healthy rule still ran
        assert ev.ready_detail()["status"] == "degraded"

    def test_store_receives_alerts_rows(self):
        appended = []

        class FakeStore:
            def append_samples(self, rows, now_wall):
                appended.append((list(rows), now_wall))

        ev = AlertEvaluator(self.RULES, store=FakeStore())
        ev.evaluate_round(leaf_snapshot({}, suspected={("0", "a"): 1.0}),
                          now_wall=7.0)
        (rows, wall), = appended
        assert wall == 7.0
        names = {(m, labels["alertname"], labels["alertstate"])
                 for m, labels, _v in rows}
        assert names == {("ALERTS", "Partitioned", FIRING)}

    def test_emit_publishes_self_metrics(self):
        ev = AlertEvaluator(self.RULES)
        ev.evaluate_round(leaf_snapshot({}, suspected={("0", "a"): 1.0}),
                          now_wall=0.0)
        b = SnapshotBuilder()
        ev.emit(b)
        snap = b.build()
        assert snap.value("tpu_root_alerts_firing", ()) == 1.0
        assert snap.value("tpu_root_alert_rules", ()) == 2.0
        assert snap.value("tpu_root_alert_transitions_total",
                          ("Partitioned", FIRING)) == 1.0


# ------------------------------------------------- sidecar + status footer


class TestSidecar:
    def test_sidecar_roundtrip_to_status_footer(self, tmp_path):
        ev = AlertEvaluator(parse_alert_rules(RULES_TEXT),
                            alert_dir=str(tmp_path))
        ev.evaluate_round(leaf_snapshot({}, suspected={("0", "a"): 1.0}),
                          now_wall=time.time())
        doc = alert_status_summary(str(tmp_path))
        assert doc is not None
        assert (doc["firing"], doc["pending"], doc["rules"]) == (1, 0, 2)
        line = alert_line(doc)
        assert line.startswith("alerts: 1 firing · 0 pending · rules 2")
        assert "last transition" in line

    def test_missing_sidecar_is_none(self, tmp_path):
        assert alert_status_summary(str(tmp_path)) is None

    def test_suppression_off_is_visible_in_the_footer(self, tmp_path):
        ev = AlertEvaluator(parse_alert_rules(RULES_TEXT),
                            alert_dir=str(tmp_path), suppression=False)
        ev.evaluate_round(leaf_snapshot({}), now_wall=time.time())
        line = alert_line(alert_status_summary(str(tmp_path)))
        assert "SUPPRESSION OFF" in line


# --------------------------------------------------------------- notifier


class Receiver:
    """In-process webhook endpoint for the notifier's `send` seam."""

    def __init__(self):
        self.got = []            # (seq, body-dict) in arrival order
        self.down = False
        self.poison_seqs = set()

    def __call__(self, url, body, headers, timeout_s):
        if self.down:
            raise urllib.error.URLError("receiver down")
        seq = int(headers[SEQ_HEADER])
        if seq in self.poison_seqs:
            return 400
        self.got.append((seq, json.loads(body)))
        return 200

    @property
    def seqs(self):
        return [s for s, _ in self.got]


def make_notifier(tmp_path, recv, **kw):
    kw.setdefault("breaker", build_breaker(2, 0.05, 0.2))
    n = AlertNotifier("http://alerts.invalid/hook", str(tmp_path),
                      send=recv, **kw)
    n.load()
    return n


class TestNotifier:
    def test_delivers_in_order_with_contiguous_seqs(self, tmp_path):
        recv = Receiver()
        n = make_notifier(tmp_path, recv)
        n.start()
        for i in range(5):
            n.enqueue({"alert": "A", "state": FIRING, "n": i})
        assert wait_for(lambda: len(recv.got) == 5)
        n.close()
        assert recv.seqs == [1, 2, 3, 4, 5]
        assert [b["n"] for _, b in recv.got] == list(range(5))
        assert n.stats()["backlog_records"] == 0

    def test_outage_buffers_then_drains_exactly_once(self, tmp_path):
        recv = Receiver()
        recv.down = True
        n = make_notifier(tmp_path, recv)
        n.start()
        for i in range(4):
            n.enqueue({"alert": "A", "i": i})
        assert wait_for(lambda: n.stats()["failed"] >= 2)
        assert n.stats()["backlog_records"] == 4
        assert n.stats()["breaker_state"] != "closed"
        recv.down = False
        assert wait_for(lambda: len(recv.got) == 4)
        n.close()
        assert recv.seqs == [1, 2, 3, 4]          # no duplicates, no gaps

    def test_restart_never_redelivers_acked(self, tmp_path):
        recv = Receiver()
        n = make_notifier(tmp_path, recv)
        n.start()
        n.enqueue({"alert": "A", "i": 0})
        n.enqueue({"alert": "A", "i": 1})
        assert wait_for(lambda: len(recv.got) == 2)
        recv.down = True
        n.enqueue({"alert": "A", "i": 2})
        assert wait_for(lambda: n.stats()["failed"] >= 1)
        n.close()                                  # "crash" mid-outage

        n2 = make_notifier(tmp_path, recv)
        assert n2.stats()["backlog_records"] == 1  # only the unacked one
        recv.down = False
        n2.start()
        n2.enqueue({"alert": "A", "i": 3})         # seq resumes, no reuse
        assert wait_for(lambda: len(recv.got) == 4)
        n2.close()
        assert recv.seqs == [1, 2, 3, 4]

    def test_drained_buffer_recovers_seq_from_sidecar(self, tmp_path):
        # Evaluator sidecar records the notifier high-water seq; a fully
        # drained buffer restart must resume from it, not from 1.
        recv = Receiver()
        n = make_notifier(tmp_path, recv)
        n.start()
        n.enqueue({"alert": "A"})
        assert wait_for(lambda: len(recv.got) == 1)
        ev = AlertEvaluator(parse_alert_rules(RULES_TEXT),
                            alert_dir=str(tmp_path), notifier=n)
        ev.evaluate_round(leaf_snapshot({}), now_wall=time.time())
        n.close()

        n2 = make_notifier(tmp_path, recv)
        n2.start()
        n2.enqueue({"alert": "B"})
        assert wait_for(lambda: len(recv.got) == 2)
        n2.close()
        assert recv.seqs == [1, 2]

    def test_poison_is_skipped_and_counted(self, tmp_path):
        recv = Receiver()
        recv.poison_seqs = {2}
        n = make_notifier(tmp_path, recv)
        n.start()
        for i in range(3):
            n.enqueue({"alert": "A", "i": i})
        assert wait_for(lambda: 3 in recv.seqs)
        n.close()
        assert recv.seqs == [1, 3]                 # 2 rejected, not retried
        s = n.stats()
        assert s["dropped"]["poison"] == 1
        assert s["backlog_records"] == 0

    def test_backlog_cap_sheds_oldest_counted(self, tmp_path):
        recv = Receiver()
        recv.down = True
        n = make_notifier(tmp_path, recv, max_backlog_mb=0.0002)  # ~200 B
        n.start()
        for i in range(50):
            n.enqueue({"alert": "A", "i": i})
        assert wait_for(lambda: n.stats()["dropped"]["backlog"] > 0)
        recv.down = False
        assert wait_for(lambda: n.stats()["backlog_records"] == 0)
        n.close()
        s = n.stats()
        # Bounded loss by policy: newest survive, loss is counted.
        assert s["dropped"]["backlog"] + len(recv.got) == 50
        assert recv.seqs == sorted(recv.seqs)
        assert recv.seqs[-1] == 50

    def test_evaluator_notifications_carry_rendered_annotations(
            self, tmp_path):
        recv = Receiver()
        n = make_notifier(tmp_path, recv)
        n.start()
        ev = AlertEvaluator(parse_alert_rules(RULES_TEXT), notifier=n,
                            suppression=False)
        ev.evaluate_round(leaf_snapshot({("0", "b"): 0.0}), now_wall=0.0)
        ev.evaluate_round(leaf_snapshot({("0", "b"): 0.0}), now_wall=20.0)
        assert wait_for(lambda: len(recv.got) == 1)
        ev.close()                                  # closes the notifier
        _, body = recv.got[0]
        assert body["alert"] == "LeafDown" and body["state"] == FIRING
        assert body["labels"]["severity"] == "page"
        assert body["labels"]["leaf"] == "b"
        assert body["annotations"]["summary"] == "leaf b down (value 0)"


# ------------------------------------------------------------------- CLI


class TestCli:
    def test_check_ok(self, tmp_path, capsys):
        p = tmp_path / "rules.txt"
        p.write_text(RULES_TEXT)
        assert main(["--check", str(p)]) == 0
        out = capsys.readouterr().out
        assert "ok: 2 alert rule(s)" in out
        assert "LeafDown [for 20s, keep_firing 10s, suppressed]" in out

    def test_check_fail_names_the_line(self, tmp_path, capsys):
        p = tmp_path / "rules.txt"
        p.write_text("alert A = tpu_nope == 0\n")
        assert main(["--check", str(p)]) == 1
        assert "line 1" in capsys.readouterr().err

    def test_import_emits_parseable_grammar(self, tmp_path, capsys):
        pytest.importorskip("yaml")
        yml = tmp_path / "rules.yaml"
        yml.write_text(
            "groups:\n"
            "- name: g\n"
            "  rules:\n"
            "  - record: slice:x:sum\n"          # recording rule: skipped
            "    expr: sum by (slice_name) (tpu_hbm_used_bytes)\n"
            "  - alert: TpuRootLeafDown\n"
            "    expr: tpu_root_leaf_up == 0\n"
            "    for: 2m\n"
            "    labels: {severity: page}\n"
            "    annotations: {summary: 'leaf {{ $labels.leaf }} down'}\n")
        assert main(["--import", str(yml)]) == 0
        text = capsys.readouterr().out
        rules = parse_alert_rules(text)
        assert [r.name for r in rules] == ["TpuRootLeafDown"]
        assert rules[0].for_s == 120.0
        # The importer injects the stale-serve suspicion suppression for
        # the alerts that have a native partition-false-positive twin.
        assert rules[0].suppress_text == \
            "tpu_root_leaf_partition_suspected == 1"


class TestImporter:
    def test_unsuppressed_alerts_stay_unsuppressed(self):
        pytest.importorskip("yaml")
        text = import_prometheus_rules(
            "groups:\n- name: g\n  rules:\n"
            "  - alert: TpuExporterDown\n"
            "    expr: up{job=\"tpu-pod-exporter\"} == 0\n")
        (rule,) = parse_alert_rules(text)
        assert rule.suppress is None
