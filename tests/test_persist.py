"""Restart survivability: crash-safe persistence, warm start, torn-write
recovery (tpu_pod_exporter.persist).

The suite covers the acceptance wedge in-process (the subprocess version is
``make restart-demo``): state written by one collector "process" restores
into a fresh one with history continuity and breaker carryover; a WAL
truncated or corrupted at ANY offset restores a consistent prefix and never
refuses to boot; the persist phase never leaks into publish/total timings;
and ``--state-dir ""`` cleanly disables the layer.
"""

import json
import os
import random
import time

import pytest

from tpu_pod_exporter.attribution.fake import FakeAttribution
from tpu_pod_exporter.backend.fake import FakeBackend
from tpu_pod_exporter.collector import Collector
from tpu_pod_exporter.history import HistoryStore
from tpu_pod_exporter.metrics import SnapshotStore
from tpu_pod_exporter.persist import (
    MAGIC,
    RestoredSnapshot,
    StatePersister,
    WAL_NAME,
    append_record,
    read_record_file,
    state_dir_summary,
)
from tpu_pod_exporter.supervisor import CircuitBreaker, SourceSupervisor


def make_world(state_dir, chips=2, supervise=True, **persist_kw):
    """A collector + history + persister trio writing into state_dir."""
    history = HistoryStore(capacity=128, retention_s=0.0)
    store = SnapshotStore()
    supervisors = {}
    if supervise:
        supervisors["device"] = SourceSupervisor("device", lambda: None)
    persist_kw.setdefault("snapshot_interval_s", 1e9)  # WAL-only by default
    persist_kw.setdefault("fsync_interval_s", 0)       # durable per record
    persister = StatePersister(
        str(state_dir), history=history, supervisors=supervisors,
        exposition_fn=store.current, **persist_kw,
    )
    collector = Collector(
        FakeBackend(chips=chips), FakeAttribution(), store,
        history=history, persister=persister,
    )
    return collector, history, store, supervisors, persister


def drain(persister, timeout=5.0):
    """Wait until the writer thread has consumed every queued record."""
    deadline = time.monotonic() + timeout
    while persister.stats()["queue_depth"] and time.monotonic() < deadline:
        time.sleep(0.01)
    time.sleep(0.1)  # let the in-flight item finish its write + fsync


def series_map(history):
    return {
        (m, tuple(sorted(l.items()))): [(round(w, 6), v) for w, v in s]
        for m, l, s in history.export_series()
    }


def restore_world(state_dir, supervise=True):
    history = HistoryStore(capacity=128, retention_s=0.0)
    supervisors = {}
    if supervise:
        supervisors["device"] = SourceSupervisor("device", lambda: None)
    persister = StatePersister(
        str(state_dir), history=history, supervisors=supervisors,
    )
    restored = persister.load()
    return restored, history, supervisors


# ------------------------------------------------------------------ framing


class TestRecordFile:
    def test_roundtrip(self, tmp_path):
        path = tmp_path / "f.bin"
        with open(path, "wb") as f:
            f.write(MAGIC)
            for payload in (b"Jone", b"Stwo", b"E" + b"x" * 1000):
                append_record(f, payload)
        payloads, valid, err = read_record_file(str(path))
        assert err is None
        assert payloads == [b"Jone", b"Stwo", b"E" + b"x" * 1000]
        assert valid == os.path.getsize(path)

    def test_missing_file_is_empty(self, tmp_path):
        payloads, valid, err = read_record_file(str(tmp_path / "nope"))
        assert (payloads, valid, err) == ([], 0, None)

    def test_bad_magic(self, tmp_path):
        path = tmp_path / "f.bin"
        path.write_bytes(b"NOTMINE!" + b"rest")
        payloads, valid, err = read_record_file(str(path))
        assert payloads == [] and "magic" in err

    def test_torn_tail_yields_prefix(self, tmp_path):
        path = tmp_path / "f.bin"
        with open(path, "wb") as f:
            f.write(MAGIC)
            append_record(f, b"Jfirst")
            append_record(f, b"Jsecond")
        size = os.path.getsize(path)
        os.truncate(path, size - 3)
        payloads, valid, err = read_record_file(str(path))
        assert payloads == [b"Jfirst"]
        assert err is not None
        # valid is the truncate point: re-reading after truncation is clean
        os.truncate(path, valid)
        payloads2, _, err2 = read_record_file(str(path))
        assert payloads2 == [b"Jfirst"] and err2 is None

    def test_corrupt_crc_stops(self, tmp_path):
        path = tmp_path / "f.bin"
        with open(path, "wb") as f:
            f.write(MAGIC)
            append_record(f, b"Jfirst")
            append_record(f, b"Jsecond")
        data = bytearray(path.read_bytes())
        data[-2] ^= 0xFF  # flip a byte inside the last payload
        path.write_bytes(bytes(data))
        payloads, _, err = read_record_file(str(path))
        assert payloads == [b"Jfirst"] and "CRC" in err

    def test_implausible_length_rejected(self, tmp_path):
        import struct

        path = tmp_path / "f.bin"
        with open(path, "wb") as f:
            f.write(MAGIC)
            f.write(struct.pack("<II", 1 << 30, 0))
        payloads, valid, err = read_record_file(str(path))
        assert payloads == [] and "implausible" in err


# ------------------------------------------------------------- round trips


class TestPersistRestore:
    def test_wal_restore_matches_original(self, tmp_path):
        collector, history, _store, _sups, persister = make_world(tmp_path)
        persister.start()
        for _ in range(8):
            collector.poll_once()
            time.sleep(0.005)
        drain(persister)
        # crash: no close() — WAL only, no checkpoint
        orig = series_map(history)
        restored, history2, _ = restore_world(tmp_path)
        assert restored.restored
        assert series_map(history2) == orig

    def test_restored_labeled_series_merge_with_live_appends(self, tmp_path):
        """The restore-key discipline: after a restart, the first LIVE poll
        must append into the restored series objects, not fork a second
        series with identical labels. tpu_exporter_up (no labels) cannot
        catch this — both key shapes coincide for an empty label set — so
        this asserts on per-chip HBM, where the collector keys by label
        VALUE tuple."""
        collector, history, _store, _sups, persister = make_world(tmp_path)
        persister.start()
        for _ in range(4):
            collector.poll_once()
            time.sleep(0.005)
        drain(persister)
        persister.close()

        # "restarted process": fresh history restored from disk, then fed
        # by a fresh collector (fresh label caches, same fake backend).
        history2 = HistoryStore(capacity=128, retention_s=0.0)
        p2 = StatePersister(str(tmp_path), history=history2)
        restored = p2.load()
        assert restored.restored
        before = history2.stats()["series"]
        c2 = Collector(
            FakeBackend(chips=2), FakeAttribution(), SnapshotStore(),
            history=history2,
        )
        for _ in range(3):
            c2.poll_once()
        after = history2.stats()["series"]
        # Live polls may add series the restore missed (e.g. rate gauges),
        # but never a duplicate of a restored one: chip HBM existed before
        # and after, so the per-chip count must not have doubled.
        rows = history2.query_range(
            "tpu_hbm_used_bytes", {"chip_id": "0"}, start=0,
            end=time.time() + 10,
        )
        assert len(rows) == 1, "restored and live samples forked the series"
        walls = [t for t, _v in rows[0]["values"]]
        assert len(walls) >= 6  # restored 4 + live 3 (same ring)
        assert walls == sorted(walls)
        assert after <= before + 8  # no wholesale duplication of the store

    def test_checkpoint_plus_wal_dedup(self, tmp_path):
        collector, history, _store, _sups, persister = make_world(
            tmp_path, snapshot_interval_s=0.2, fsync_interval_s=0
        )
        persister.start()
        for _ in range(10):
            collector.poll_once()
            time.sleep(0.05)  # several checkpoint rotations mid-run
        drain(persister)
        assert persister.stats()["snapshots"] >= 1
        orig = series_map(history)
        restored, history2, _ = restore_world(tmp_path)
        # No duplicated samples from records both checkpointed and WAL'd.
        assert series_map(history2) == orig
        assert restored.series > 0

    def test_final_flush_on_close(self, tmp_path):
        collector, history, _store, _sups, persister = make_world(
            tmp_path, fsync_interval_s=1e9  # never fsync on cadence...
        )
        persister.start()
        for _ in range(5):
            collector.poll_once()
        persister.close()  # ...the SIGTERM drain must still make it durable
        orig = series_map(history)
        restored, history2, _ = restore_world(tmp_path)
        assert series_map(history2) == orig
        assert restored.exposition is not None

    def test_breaker_carryover(self, tmp_path):
        collector, _h, _store, sups, persister = make_world(tmp_path)
        persister.start()
        br = sups["device"].breaker
        for _ in range(6):
            br.record_failure()
        assert br.state == "open"
        collector.poll_once()  # on_poll notices the signature change
        drain(persister)
        restored, _h2, sups2 = restore_world(tmp_path)
        br2 = sups2["device"].breaker
        assert br2.state == "open"
        assert br2.reopens == br.reopens
        assert br2.consecutive_failures == br.consecutive_failures
        assert br2.transitions["open"] == br.transitions["open"]
        # The remaining open window carried over (within clock slop).
        assert abs(br2.seconds_until_probe - br.seconds_until_probe) < 1.0

    def test_half_open_restores_as_probe_now(self):
        br = CircuitBreaker(failure_threshold=1)
        br.record_failure()
        while br.decide() != "probe":
            time.sleep(0.01)
        assert br.state == "half_open"
        doc = br.export_state()
        br2 = CircuitBreaker(failure_threshold=1)
        br2.restore_state(doc)
        assert br2.state == "open"
        assert br2.decide() == "probe"  # due immediately

    def test_breaker_restore_tolerates_garbage(self):
        br = CircuitBreaker()
        for doc in (
            {},
            {"state": "bogus"},
            {"state": "open", "open_until_wall": "NaNsense",
             "consecutive_failures": 3, "reopens": 1},
            {"state": "open", "open_until_wall": time.time() + 1e9,
             "consecutive_failures": 1, "reopens": 1},
        ):
            br2 = CircuitBreaker()
            br2.restore_state(doc)
            # clamped: never quarantined past the backoff ceiling
            assert br2.seconds_until_probe <= br2.backoff_max_s + 1.0
        assert br.state == "closed"

    def test_exposition_restored_with_timestamp(self, tmp_path):
        collector, _h, store, _sups, persister = make_world(tmp_path)
        persister.start()
        collector.poll_once()
        ts = store.current().timestamp
        persister.close()
        restored, _h2, _ = restore_world(tmp_path)
        assert restored.exposition_ts == pytest.approx(ts)
        assert b"tpu_exporter_up" in restored.exposition

    def test_empty_dir_cold_start(self, tmp_path):
        restored, history, _ = restore_world(tmp_path / "fresh")
        assert not restored.restored
        assert history.stats()["series"] == 0

    def test_wal_open_failure_counts_drops_and_recovers(self, tmp_path):
        """An unopenable WAL must not silently discard records (the
        TpuExporterPersistenceFailing alert watches errors+dropped), and
        the writer must retry the open on every write — persistence comes
        back as soon as the filesystem does, not at the next rotation."""
        collector, _h, _store, _sups, persister = make_world(tmp_path)
        wal = tmp_path / WAL_NAME
        wal.mkdir()  # open(wal_path, "ab") now raises IsADirectoryError
        persister.start()
        collector.poll_once()
        deadline = time.monotonic() + 5
        while (
            persister.stats()["dropped"] == 0
            and time.monotonic() < deadline
        ):
            time.sleep(0.02)
        st = persister.stats()
        assert st["dropped"] >= 1 and st["errors"] >= 1
        wal.rmdir()  # filesystem "recovers"
        collector.poll_once()
        deadline = time.monotonic() + 5
        while (
            persister.stats()["wal_records"] == 0
            and time.monotonic() < deadline
        ):
            time.sleep(0.02)
        assert persister.stats()["wal_records"] >= 1
        persister.close()

    def test_unwritable_dir_never_raises(self):
        p = StatePersister("/proc/definitely/not/writable")
        restored = p.load()
        assert not restored.restored
        p.start()  # no-op, no crash
        p.close()


# --------------------------------------------------------- torn-write fuzz


class TestTornWriteFuzz:
    def test_random_truncation_and_corruption_always_boots(self, tmp_path):
        """Seeded fuzz: cut or scramble the WAL at random offsets; every
        boot must succeed and restore a consistent prefix — per family,
        every surviving series carries the SAME wall-timestamp sequence (a
        WAL record is all-or-nothing; no partial poll may surface), and
        every sample matches the uncorrupted restore at its position."""
        collector, _h, _store, _sups, persister = make_world(tmp_path)
        persister.start()
        for _ in range(12):
            collector.poll_once()
            time.sleep(0.002)
        drain(persister)
        wal = tmp_path / WAL_NAME
        pristine = wal.read_bytes()
        _, full_hist, _ = restore_world(tmp_path)
        full = series_map(full_hist)

        rng = random.Random(1234)
        for trial in range(25):
            data = bytearray(pristine)
            offset = rng.randrange(len(MAGIC), len(data))
            if trial % 2:
                del data[offset:]  # torn tail
            else:
                for i in range(offset, min(offset + 8, len(data))):
                    data[i] ^= 0xA5  # mid-file scramble
            wal.write_bytes(bytes(data))
            restored, hist, _ = restore_world(tmp_path)
            got = series_map(hist)
            # prefix property per series
            for key, samples in got.items():
                assert key in full, (trial, key)
                assert samples == full[key][: len(samples)], (trial, key)
            # per-poll atomicity: within one metric family, all restored
            # series agree on their timestamp set (no half-applied record)
            by_family: dict[str, set] = {}
            for (metric, _labels), samples in got.items():
                walls = tuple(w for w, _v in samples)
                by_family.setdefault(metric, set()).add(walls)
            for metric, wallsets in by_family.items():
                assert len(wallsets) <= 2, (trial, metric)
                if len(wallsets) == 2:
                    # late-born series (e.g. rate gauges from poll 2): one
                    # set must be a suffix of the other, never interleaved
                    a, b = sorted(wallsets, key=len)
                    assert b[-len(a):] == a if a else True, (trial, metric)
            # restoring a corrupted dir also truncated the WAL to the clean
            # prefix; put the pristine bytes back for the next trial
            wal.write_bytes(pristine)

    def test_query_range_never_sees_partial_record(self, tmp_path):
        collector, _h, _store, _sups, persister = make_world(tmp_path)
        persister.start()
        for _ in range(6):
            collector.poll_once()
            time.sleep(0.002)
        drain(persister)
        wal = tmp_path / WAL_NAME
        data = wal.read_bytes()
        # cut INSIDE the last record's payload
        os.truncate(wal, len(data) - 5)
        _restored, hist, _ = restore_world(tmp_path)
        rows = hist.query_range("tpu_hbm_used_bytes", {}, start=0,
                                end=time.time() + 10)
        walls = {tuple(t for t, _v in r["values"]) for r in rows}
        # every chip's series saw the same polls — the torn poll vanished
        # for all of them, not some of them
        assert len(walls) == 1


# ------------------------------------------------------------- warm start


class TestWarmStart:
    def test_restored_snapshot_patches_markers(self):
        body = (
            b"# HELP tpu_exporter_up x\n# TYPE tpu_exporter_up gauge\n"
            b"tpu_exporter_up 1\n"
            b"# HELP tpu_exporter_warm_start x\n"
            b"# TYPE tpu_exporter_warm_start gauge\n"
            b"tpu_exporter_warm_start 0\n"
            b"# HELP tpu_exporter_snapshot_stale_seconds x\n"
            b"# TYPE tpu_exporter_snapshot_stale_seconds gauge\n"
            b"tpu_exporter_snapshot_stale_seconds 0\n"
            b"# HELP tpu_ici_transferred_bytes_total x\n"
            b"# TYPE tpu_ici_transferred_bytes_total counter\n"
            b"tpu_ici_transferred_bytes_total 5\n"
        )
        ts = time.time() - 12.5
        snap = RestoredSnapshot(body, ts)
        text = snap.encode()
        assert b"tpu_exporter_warm_start 1\n" in text
        assert b"tpu_exporter_warm_start 0\n" not in text
        assert b"tpu_exporter_snapshot_stale_seconds 12." in text
        assert snap.stale_s == pytest.approx(12.5, abs=1.0)
        assert snap.poll_timestamp == ts
        assert snap.timestamp > ts  # serving-time, not data-time
        assert snap.series_count == 4
        om = snap.encode_openmetrics()
        assert om.endswith(b"# EOF\n")
        assert b"# TYPE tpu_ici_transferred_bytes counter" in om
        assert b"tpu_ici_transferred_bytes_total 5" in om  # sample unchanged
        import gzip

        assert gzip.decompress(snap.encode_gzip()) == text

    def test_app_warm_start_end_to_end(self, tmp_path):
        """Full app loop: run, SIGTERM-stop (final flush), rebuild on the
        same state dir — the new app must hold a warm snapshot whose body
        carries the markers, serve it immediately, and flip /readyz to
        warm until the first live poll lands."""
        from tpu_pod_exporter.app import ExporterApp
        from tpu_pod_exporter.config import ExporterConfig

        cfg = ExporterConfig(
            port=0, host="127.0.0.1", backend="fake", fake_chips=2,
            attribution="none", state_dir=str(tmp_path),
            state_fsync_interval_s=0, interval_s=0.1,
            history_retention_s=60.0, trace=False,
        )
        app = ExporterApp(cfg)
        app.collector.poll_once()
        app.persister.start()
        app.persister.close()  # the SIGTERM flush, without sockets
        app.collector.close()

        app2 = ExporterApp(cfg)
        try:
            assert app2._warm_snapshot is not None
            body = app2._warm_snapshot.encode()
            assert b"tpu_exporter_warm_start 1\n" in body
            # Simulate the serving sequence without binding sockets:
            app2.store.swap(app2._warm_snapshot)
            warm = app2._warm_state()
            assert warm is not None and warm["snapshot_stale_s"] >= 0
            # first live poll replaces the restored snapshot → warm ends
            app2.collector.poll_once()
            assert app2._warm_state() is None
            live = app2.store.current().encode()
            assert b"tpu_exporter_warm_start 0\n" in live
        finally:
            app2.persister.close()
            app2.collector.close()

    def test_readyz_reports_warm_then_ready(self, tmp_path):
        import urllib.request

        from tpu_pod_exporter.metrics import (
            MetricSpec,
            SnapshotBuilder,
            SnapshotStore,
        )
        from tpu_pod_exporter.server import MetricsServer

        store = SnapshotStore()
        warm = {"on": True}
        server = MetricsServer(
            store, host="127.0.0.1", port=0,
            warm_fn=lambda: {"snapshot_stale_s": 3.0} if warm["on"] else None,
        )
        server.start()
        try:
            base = f"http://127.0.0.1:{server.port}"

            def readyz():
                try:
                    with urllib.request.urlopen(base + "/readyz", timeout=5) as r:
                        return r.status, json.loads(r.read())
                except urllib.error.HTTPError as e:
                    return e.code, json.loads(e.read())

            status, body = readyz()
            assert status == 503 and body["state"] == "starting"
            b = SnapshotBuilder()
            b.add(MetricSpec(name="m", help="h"), 1.0)
            store.swap(b.build())
            status, body = readyz()
            assert status == 200 and body["state"] == "warm"
            assert body["snapshot_stale_s"] == 3.0
            warm["on"] = False
            status, body = readyz()
            assert status == 200 and body["state"] == "ready"
        finally:
            server.stop()


# ---------------------------------------------------------- phase isolation


class TestPhaseIsolation:
    def test_persist_excluded_from_publish_and_total(self, tmp_path):
        _c, history, store, sups, persister = make_world(tmp_path)
        slow_called = {"n": 0}

        class SlowPersister:
            @staticmethod
            def on_poll(snap):
                slow_called["n"] += 1
                time.sleep(0.08)
                return 1

            @staticmethod
            def stats():
                return {
                    "wal_records": 0, "wal_bytes": 0, "snapshots": 0,
                    "errors": 0, "dropped": 0, "last_fsync_s": 0.0,
                    "last_snapshot_wall": 0.0,
                }

        collector = Collector(
            FakeBackend(chips=2), FakeAttribution(), SnapshotStore(),
            history=history, persister=SlowPersister(),
        )
        stats = collector.poll_once()
        assert slow_called["n"] == 1
        # the 80 ms persist sleep must not appear in any poll phase timing
        assert stats.publish_s < 0.05
        assert stats.total_s < 0.05

    def test_poll_survives_broken_persister(self):
        class BrokenPersister:
            @staticmethod
            def on_poll(snap):
                raise OSError("disk on fire")

            @staticmethod
            def stats():
                raise OSError("still on fire")

        collector = Collector(
            FakeBackend(chips=2), FakeAttribution(), SnapshotStore(),
            persister=BrokenPersister(),
        )
        stats = collector.poll_once()
        assert stats.ok  # neither on_poll nor stats() can fail a poll

    def test_state_dir_empty_disables_layer(self):
        from tpu_pod_exporter.app import ExporterApp
        from tpu_pod_exporter.config import ExporterConfig

        cfg = ExporterConfig(
            port=0, host="127.0.0.1", backend="fake", fake_chips=0,
            attribution="none", trace=False,
        )
        assert cfg.state_dir == ""
        app = ExporterApp(cfg)
        try:
            assert app.persister is None
            app.collector.poll_once()
            body = app.store.current().encode()
            # persist self-metrics absent; warm markers present (live, 0)
            assert b"tpu_exporter_persist_wal_bytes" not in body
            assert b"tpu_exporter_warm_start 0\n" in body
        finally:
            app.collector.close()

    def test_persist_metrics_published_when_enabled(self, tmp_path):
        collector, _h, store, _sups, persister = make_world(tmp_path)
        persister.start()
        collector.poll_once()
        drain(persister)
        collector.poll_once()  # stats land one poll behind
        body = store.current().encode()
        assert b"tpu_exporter_persist_wal_records_total" in body
        assert b"tpu_exporter_persist_wal_bytes" in body
        persister.close()


# -------------------------------------------------- aggregator breaker file


class TestBreakerStateFile:
    def test_roundtrip(self, tmp_path):
        from tpu_pod_exporter.persist import BreakerStateFile

        f = BreakerStateFile(str(tmp_path / "b.json"))
        br = CircuitBreaker(failure_threshold=1)
        br.record_failure()
        f.save({"h0:8000": br.export_state()})
        loaded = f.load()
        br2 = CircuitBreaker(failure_threshold=1)
        br2.restore_state(loaded["h0:8000"])
        assert br2.state == "open"

    def test_corrupt_file_loads_empty(self, tmp_path):
        from tpu_pod_exporter.persist import BreakerStateFile

        path = tmp_path / "b.json"
        path.write_text("{not json")
        assert BreakerStateFile(str(path)).load() == {}
        path.write_text('["wrong shape"]')
        assert BreakerStateFile(str(path)).load() == {}

    def test_aggregator_restores_quarantine(self, tmp_path):
        from tpu_pod_exporter.aggregate import SliceAggregator
        from tpu_pod_exporter.persist import BreakerStateFile

        store_file = BreakerStateFile(str(tmp_path / "b.json"))

        def dead_fetch(target, timeout_s):
            raise ConnectionError("down")

        agg = SliceAggregator(
            ("t0:1",), SnapshotStore(), fetch=dead_fetch,
            breaker_failures=2, breaker_backoff_s=30.0,
            breaker_backoff_max_s=60.0, breaker_store=store_file,
        )
        agg.poll_once()
        agg.poll_once()
        assert agg._breakers["t0:1"].state == "open"
        agg.close()  # forces a save

        agg2 = SliceAggregator(
            ("t0:1",), SnapshotStore(), fetch=dead_fetch,
            breaker_failures=2, breaker_backoff_s=30.0,
            breaker_backoff_max_s=60.0, breaker_store=store_file,
        )
        br = agg2._breakers["t0:1"]
        assert br.state == "open"  # no re-learning from closed
        assert br.seconds_until_probe > 0
        agg2.close()


# ------------------------------------------------------------ chaos tokens


class TestChaosKill:
    def test_kill_kind_and_offset_parse(self):
        from tpu_pod_exporter.chaos import parse_chaos_spec

        rules = parse_chaos_spec("kill:device:1:@20:x1")
        assert rules[0].kind == "kill"
        assert rules[0].min_index == 20
        assert rules[0].max_count == 1

    def test_offset_defers_injection(self):
        from tpu_pod_exporter.chaos import ChaosError, ChaosWrapper, parse_chaos_spec

        class Inner:
            name = "inner"

            @staticmethod
            def sample():
                return "ok"

        rules = parse_chaos_spec("err:device:1:@3")
        w = ChaosWrapper(Inner(), "device", rules, seed=1)
        for _ in range(3):
            assert w.sample() == "ok"  # calls 0..2: rule not armed yet
        with pytest.raises(ChaosError):
            w.sample()  # call 3: armed
        assert w.injected[0] == (3, "err")

    def test_bad_offset_token_loud(self):
        from tpu_pod_exporter.chaos import parse_chaos_spec

        with pytest.raises(ValueError):
            parse_chaos_spec("err:device:@nope")


# --------------------------------------------------------------- dir summary


class TestStateDirSummary:
    def test_missing_dir(self, tmp_path):
        s = state_dir_summary(str(tmp_path / "nope"))
        assert s["exists"] is False

    def test_sizes_and_age(self, tmp_path):
        collector, _h, _store, _sups, persister = make_world(
            tmp_path, snapshot_interval_s=0.05
        )
        persister.start()
        collector.poll_once()
        deadline = time.monotonic() + 5
        while (
            persister.stats()["snapshots"] == 0
            and time.monotonic() < deadline
        ):
            collector.poll_once()
            time.sleep(0.05)
        persister.close()
        s = state_dir_summary(str(tmp_path))
        assert s["exists"] and s["snapshot_bytes"] > 0
        assert s["snapshot_age_s"] is not None and s["snapshot_age_s"] < 60
        assert s["total_bytes"] >= s["snapshot_bytes"]

    def test_status_persist_line(self, tmp_path):
        from tpu_pod_exporter.status import persist_line

        line = persist_line(str(tmp_path / "nope"))
        assert "cold-start" in line
        collector, _h, _store, _sups, persister = make_world(tmp_path)
        persister.start()
        collector.poll_once()
        persister.close()  # writes the final checkpoint
        line = persist_line(str(tmp_path))
        assert "warm restart ready" in line
