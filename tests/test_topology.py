"""Topology parsing + env detection tests."""

from tpu_pod_exporter.topology import (
    HostTopology,
    detect_host_topology,
    parse_accelerator_type,
)


class TestParseAcceleratorType:
    def test_v4_8(self):
        t = parse_accelerator_type("v4-8")
        assert (t.generation, t.total_cores, t.total_chips) == ("v4", 8, 4)
        assert t.chips_per_host == 4
        assert t.num_hosts == 1
        assert not t.multi_host

    def test_v5p_64(self):
        t = parse_accelerator_type("v5p-64")
        assert t.total_chips == 32
        assert t.num_hosts == 8
        assert t.multi_host

    def test_v5litepod_16(self):
        t = parse_accelerator_type("v5litepod-16")
        assert t.total_chips == 16
        assert t.chips_per_host == 8
        assert t.num_hosts == 2

    def test_v5e_alias(self):
        t = parse_accelerator_type("v5e-16")
        assert t.total_chips == 16

    def test_sub_host_slice(self):
        t = parse_accelerator_type("v5litepod-4")
        assert t.total_chips == 4
        assert t.chips_per_host == 4
        assert t.num_hosts == 1

    def test_unknown_generation_degrades(self):
        t = parse_accelerator_type("v99-8")
        assert t.accelerator == "v99-8"
        assert t.total_chips == 0

    def test_garbage_degrades(self):
        assert parse_accelerator_type("").total_chips == 0
        assert parse_accelerator_type("no-dash-num").total_chips == 0


class TestDetectHostTopology:
    def test_env_detection(self):
        env = {
            "TPU_ACCELERATOR_TYPE": "v5p-64",
            "TPU_WORKER_ID": "3",
            "NODE_NAME": "gke-node-7",
            "TPU_SLICE_NAME": "slice-a",
        }
        t = detect_host_topology(env=env)
        assert t.accelerator == "v5p-64"
        assert t.worker_id == "3"
        assert t.host == "gke-node-7"
        assert t.slice_name == "slice-a"
        assert t.slice_topology.multi_host

    def test_overrides_beat_env(self):
        env = {"TPU_ACCELERATOR_TYPE": "v4-8"}
        t = detect_host_topology(env=env, accelerator="v5e-16", worker_id="1")
        assert t.accelerator == "v5e-16"
        assert t.worker_id == "1"

    def test_hostname_fallback(self):
        t = detect_host_topology(env={})
        assert t.host  # socket.gethostname()

    def test_labels(self):
        t = HostTopology(accelerator="v4-8", slice_name="s", host="h", worker_id="0")
        assert t.labels() == {
            "accelerator": "v4-8",
            "slice_name": "s",
            "host": "h",
            "worker_id": "0",
        }


class TestMultislice:
    def test_megascale_env_detection(self):
        env = {
            "TPU_ACCELERATOR_TYPE": "v5p-128",
            "MEGASCALE_COORDINATOR_ADDRESS": "train-job-0.headless:8080",
            "MEGASCALE_NUM_SLICES": "2",
            "MEGASCALE_SLICE_ID": "1",
        }
        t = detect_host_topology(env=env)
        assert t.multislice_group == "train-job-0.headless"  # port stripped
        assert t.num_slices == "2"
        assert t.slice_name == "1"  # MEGASCALE_SLICE_ID fallback
        assert t.host_info_labels()["multislice_group"] == "train-job-0.headless"

    def test_override_beats_env(self):
        env = {"MEGASCALE_COORDINATOR_ADDRESS": "coord:8080"}
        t = detect_host_topology(env=env, multislice_group="my-group")
        assert t.multislice_group == "my-group"

    def test_override_taken_verbatim_even_with_colons(self):
        # An operator's group name may contain colons; only the ENV-derived
        # endpoint gets port-stripped (code-review r5).
        t = detect_host_topology(env={}, multislice_group="team:prod")
        assert t.multislice_group == "team:prod"

    def test_bare_ipv6_coordinator_not_mangled(self):
        env = {"MEGASCALE_COORDINATOR_ADDRESS": "fd00::a"}
        t = detect_host_topology(env=env)
        assert t.multislice_group == "fd00::a"  # tail not numeric: kept

    def test_bracketed_ipv6_with_port_stripped(self):
        env = {"MEGASCALE_COORDINATOR_ADDRESS": "[fd00::a]:8080"}
        t = detect_host_topology(env=env)
        assert t.multislice_group == "[fd00::a]"

    def test_not_multislice_is_empty(self):
        t = detect_host_topology(env={})
        assert t.multislice_group == ""
        assert t.num_slices == ""
        assert t.host_info_labels()["multislice_group"] == ""
