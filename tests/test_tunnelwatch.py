"""Tunnel watchdog tests: state-transition logging without any JAX init."""

import json
import socket
import threading

from tpu_pod_exporter import tunnelwatch


def test_sample_never_initializes_jax():
    import sys

    before = sys.modules.get("jax")
    s = tunnelwatch.sample()
    assert set(s) == {"relay", "libtpu_8431"}
    assert all(isinstance(v, bool) for v in s.values())
    assert sys.modules.get("jax") is before  # port probes only


def test_main_logs_transitions_only(tmp_path, monkeypatch):
    out = tmp_path / "watch.jsonl"
    states = iter([
        {"relay": False, "libtpu_8431": False},
        {"relay": False, "libtpu_8431": False},  # no change: not logged
        {"relay": True, "libtpu_8431": False},   # transition: logged
        {"relay": True, "libtpu_8431": False},
    ])
    monkeypatch.setattr(tunnelwatch, "sample", lambda: next(states))
    monkeypatch.setattr(tunnelwatch.time, "sleep", lambda s: None)

    calls = [0]
    real_monotonic = tunnelwatch.time.monotonic

    def monotonic():
        calls[0] += 1
        # Expire after the 4th sample's loop check.
        return real_monotonic() + (1000.0 if calls[0] > 5 else 0.0)

    monkeypatch.setattr(tunnelwatch.time, "monotonic", monotonic)
    tunnelwatch.main(["--out", str(out), "--interval", "0",
                      "--max-seconds", "1", "--heartbeat-every", "1000"])
    records = [json.loads(l) for l in out.read_text().splitlines()]
    assert [r["relay"] for r in records] == [False, True]
    assert records[0]["change"] is True and records[1]["change"] is True


def test_port_probe_detects_listener():
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]
    def accept_quietly():
        try:
            srv.accept()
        except OSError:  # srv.close() tears the socket down under us
            pass

    t = threading.Thread(target=accept_quietly, daemon=True)
    t.start()
    try:
        assert tunnelwatch._port_open(port)
        assert not tunnelwatch._port_open(1)  # nothing on tcp/1
    finally:
        srv.close()


def test_heartbeat_every_zero_is_a_usage_error(capsys):
    # Advisor r4: 0 used to ZeroDivisionError inside the loop; it must be
    # rejected at argparse time with a usage message instead.
    import pytest

    with pytest.raises(SystemExit) as ei:
        tunnelwatch.main(["--heartbeat-every", "0", "--max-seconds", "1"])
    assert ei.value.code == 2  # argparse usage error, not a traceback
    assert "must be >= 1" in capsys.readouterr().err


def test_heartbeat_every_one_records_every_sample(tmp_path, monkeypatch):
    out = tmp_path / "watch.jsonl"
    states = iter([{"relay": False, "libtpu_8431": False}] * 3)
    monkeypatch.setattr(tunnelwatch, "sample", lambda: next(states))
    monkeypatch.setattr(tunnelwatch.time, "sleep", lambda s: None)
    calls = [0]
    real = tunnelwatch.time.monotonic

    def monotonic():
        calls[0] += 1
        return real() + (1000.0 if calls[0] > 4 else 0.0)

    monkeypatch.setattr(tunnelwatch.time, "monotonic", monotonic)
    tunnelwatch.main(["--out", str(out), "--interval", "0",
                      "--max-seconds", "1", "--heartbeat-every", "1"])
    assert len(out.read_text().splitlines()) == 3  # one record per sample
