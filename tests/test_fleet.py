"""Federated fleet query plane (ISSUE 6).

Covers the fan-out/merge mechanics with injected fetches (no sockets),
partial-result semantics (error / timeout / quarantine), the result cache
and its generation-bump invalidation, the aggregator exposition of the
plane's self-metrics, the HTTP routing through the shared /api/v1 fence,
traceparent propagation, the `status --fleet` renderer, and a small
end-to-end run of the fleet simulator acceptance harness.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from tpu_pod_exporter.fleet import FleetQueryPlane
from tpu_pod_exporter.metrics import SnapshotStore
from tpu_pod_exporter.server import MetricsServer
from tpu_pod_exporter.supervisor import CircuitBreaker


def node_rows(host, n_series=2, last_ts=1000.0):
    return [
        {
            "metric": "tpu_hbm_used_bytes",
            "labels": {"host": host, "chip_id": str(i)},
            "values": [[last_ts - 1, 1.0], [last_ts, 2.0]],
            "tier": 0.0,
            "last_sample_wall_ts": last_ts,
        }
        for i in range(n_series)
    ]


def make_fetch(behaviors):
    """fetch(url, timeout_s) whose behavior keys on the target host:port
    inside the url. A behavior is rows (answer), an Exception (raise), or
    a float (sleep that long, then answer)."""

    def fetch(url, timeout_s):
        for target, behavior in behaviors.items():
            if target in url:
                if isinstance(behavior, Exception):
                    raise behavior
                if isinstance(behavior, float):
                    time.sleep(behavior)
                    behavior = node_rows(target)
                return {"status": "ok",
                        "data": {"resultType": "matrix", "result": behavior}}
        raise ConnectionError(f"unknown target in {url}")

    return fetch


WALL = 1000.0


def make_plane(behaviors, **kw):
    kw.setdefault("timeout_s", 0.5)
    kw.setdefault("wallclock", lambda: WALL + 10.0)
    return FleetQueryPlane(
        tuple(behaviors), fetch=make_fetch(behaviors), **kw
    )


class TestFanOutMerge:
    def test_full_merge_not_partial(self):
        plane = make_plane({"h0:1": node_rows("h0:1"),
                            "h1:1": node_rows("h1:1")})
        env = plane.query_range("tpu_hbm_used_bytes", start=0.0, end=2000.0)
        assert env["status"] == "ok" and env["partial"] is False
        assert env["fleet"]["merged_series"] == 4
        assert env["fleet"]["ok"] == 2
        assert {t["state"] for t in env["targets"].values()} == {"ok"}
        plane.close()

    def test_staleness_per_target(self):
        plane = make_plane({
            "h0:1": node_rows("h0:1", last_ts=WALL + 9.0),   # 1 s stale
            "h1:1": node_rows("h1:1", last_ts=WALL - 110.0),  # 2 min stale
        })
        env = plane.query_range("tpu_hbm_used_bytes", start=0.0, end=2000.0)
        assert env["targets"]["h0:1"]["staleness_s"] == pytest.approx(1.0)
        assert env["targets"]["h1:1"]["staleness_s"] == pytest.approx(120.0)
        plane.close()

    def test_dead_target_is_partial_with_remainder_merged(self):
        plane = make_plane({
            "h0:1": node_rows("h0:1"),
            "h1:1": ConnectionRefusedError("refused"),
            "h2:1": node_rows("h2:1"),
        })
        env = plane.query_range("tpu_hbm_used_bytes", start=0.0, end=2000.0)
        assert env["partial"] is True
        assert env["fleet"]["ok"] == 2 and env["fleet"]["errors"] == 1
        assert env["fleet"]["merged_series"] == 4
        assert env["targets"]["h1:1"]["state"] == "error"
        assert "refused" in env["targets"]["h1:1"]["error"]
        plane.close()

    def test_slow_target_times_out_without_blocking(self):
        plane = make_plane({"h0:1": node_rows("h0:1"), "h1:1": 5.0},
                           timeout_s=0.1)
        t0 = time.monotonic()
        env = plane.query_range("tpu_hbm_used_bytes", start=0.0, end=2000.0)
        took = time.monotonic() - t0
        assert took < 2.0  # deadline, not the sleeping target, bounds us
        assert env["partial"] is True
        assert env["targets"]["h1:1"]["state"] == "timeout"
        assert env["fleet"]["merged_series"] == 2
        plane.close()

    def test_quarantined_target_skipped_not_probed(self):
        br = CircuitBreaker(failure_threshold=1, backoff_base_s=60.0,
                            backoff_max_s=120.0)
        br.record_failure()  # open
        probed = []

        def fetch(url, timeout_s):
            probed.append(url)
            return {"status": "ok",
                    "data": {"resultType": "matrix",
                             "result": node_rows("h0:1")}}

        plane = FleetQueryPlane(("h0:1", "h1:1"), fetch=fetch,
                                breakers={"h1:1": br})
        env = plane.query_range("tpu_hbm_used_bytes", start=0.0, end=2000.0)
        assert env["partial"] is True
        assert env["targets"]["h1:1"]["state"] == "quarantined"
        assert env["targets"]["h1:1"]["next_probe_in_s"] > 0
        assert all("h1:1" not in u for u in probed)  # never touched
        plane.close()

    def test_404_is_no_data_not_partial(self):
        def fetch(url, timeout_s):
            if "h1:1" in url:
                raise urllib.error.HTTPError(url, 404, "no samples", None, None)
            return {"status": "ok",
                    "data": {"resultType": "matrix",
                             "result": node_rows("h0:1")}}

        plane = FleetQueryPlane(("h0:1", "h1:1"), fetch=fetch)
        env = plane.query_range("tpu_hbm_used_bytes", start=0.0, end=2000.0)
        assert env["partial"] is False
        assert env["targets"]["h1:1"]["state"] == "no_data"
        plane.close()

    def test_colliding_series_disambiguated_by_target(self):
        # Label-less self-metrics (tpu_exporter_up) collide for EVERY
        # target pair; the merge must keep every host's answer under a
        # synthetic target label, not fold 63 hosts' outage data away.
        def up_row(v):
            return {"metric": "tpu_exporter_up", "labels": {},
                    "values": [[10.0, v]], "last_sample_wall_ts": 10.0}

        plane = make_plane({"h0:1": [up_row(1.0)], "h1:1": [up_row(0.0)]})
        env = plane.query_range("tpu_exporter_up", start=0.0, end=2000.0)
        assert env["fleet"]["merged_series"] == 2
        assert env["fleet"]["duplicate_series"] == 1
        by_target = {r["labels"]["target"]: r["values"][0][1]
                     for r in env["data"]["result"]}
        assert by_target == {"h0:1": 1.0, "h1:1": 0.0}
        plane.close()

    def test_grid_alignment_respects_node_resolution_cap(self):
        # Alignment widens start/end by up to 2·step; a request at the 11k
        # resolution edge must still produce a node-legal grid instead of
        # 400ing on every healthy target.
        seen = []

        def fetch(url, timeout_s):
            seen.append(url)
            return {"status": "ok",
                    "data": {"resultType": "matrix",
                             "result": node_rows("h0:1")}}

        plane = FleetQueryPlane(("h0:1",), fetch=fetch)
        env = plane.query_range("m", start=0.9, end=11000.2, step=1.0)
        assert env["fleet"]["ok"] == 1 and not env["partial"]
        assert (env["end"] - env["start"]) / 1.0 <= 11000
        plane.close()

    def test_window_stats_and_series_shapes(self):
        rows = [{"metric": "m", "labels": {"host": "h0"},
                 "stats": {"last": 1.0}, "last_sample_wall_ts": 5.0}]

        def fetch(url, timeout_s):
            if "/api/v1/series" in url:
                return {"status": "ok",
                        "data": [{"metric": "m", "labels": {"host": "h0"},
                                  "samples": 3}]}
            return {"status": "ok", "data": {"result": rows}}

        plane = FleetQueryPlane(("h0:1",), fetch=fetch)
        ws = plane.window_stats("m", window_s=60.0)
        assert ws["data"]["result"][0]["stats"]["last"] == 1.0
        sr = plane.series()
        assert sr["data"][0]["samples"] == 3
        plane.close()


class TestResultCache:
    def test_hit_within_generation_miss_after_bump(self):
        calls = {"n": 0}
        gen = {"g": 0}

        def fetch(url, timeout_s):
            calls["n"] += 1
            return {"status": "ok",
                    "data": {"resultType": "matrix",
                             "result": node_rows("h0:1")}}

        plane = FleetQueryPlane(("h0:1",), fetch=fetch,
                                generation_fn=lambda: gen["g"])
        e1 = plane.query_range("m", start=0.0, end=100.0, step=10.0)
        e2 = plane.query_range("m", start=0.0, end=100.0, step=10.0)
        assert calls["n"] == 1
        assert "cached" not in e1 and e2["cached"] is True
        # generation bump (new aggregator round / layout change) invalidates
        gen["g"] += 1
        e3 = plane.query_range("m", start=0.0, end=100.0, step=10.0)
        assert calls["n"] == 2 and "cached" not in e3
        plane.close()

    def test_grid_alignment_shares_cache_key(self):
        calls = {"n": 0}

        def fetch(url, timeout_s):
            calls["n"] += 1
            return {"status": "ok",
                    "data": {"resultType": "matrix",
                             "result": node_rows("h0:1")}}

        plane = FleetQueryPlane(("h0:1",), fetch=fetch,
                                generation_fn=lambda: 7)
        # A sliding dashboard window: starts differ by < step, same grid.
        plane.query_range("m", start=0.2, end=100.4, step=10.0)
        env = plane.query_range("m", start=3.9, end=101.7, step=10.0)
        assert calls["n"] == 1 and env["cached"] is True
        assert env["start"] == 0.0 and env["end"] == 110.0
        plane.close()

    def test_distinct_queries_distinct_entries(self):
        calls = {"n": 0}

        def fetch(url, timeout_s):
            calls["n"] += 1
            return {"status": "ok",
                    "data": {"resultType": "matrix",
                             "result": node_rows("h0:1")}}

        plane = FleetQueryPlane(("h0:1",), fetch=fetch,
                                generation_fn=lambda: 1)
        plane.query_range("m", start=0.0, end=100.0, step=10.0)
        plane.query_range("m", start=0.0, end=100.0, step=10.0, agg="min")
        plane.query_range("m", match={"host": "h0"}, start=0.0, end=100.0,
                          step=10.0)
        plane.window_stats("m", window_s=60.0)
        assert calls["n"] == 4
        plane.close()


class TestAggregatorExposition:
    def test_fleet_metrics_reach_aggregator_exposition(self):
        from tpu_pod_exporter.aggregate import SliceAggregator

        store = SnapshotStore()
        plane = make_plane({"h0:1": node_rows("h0:1")})
        agg = SliceAggregator(
            ("h0:1",), store, fetch=lambda t, s: "", breaker_failures=0,
        )
        agg.set_fleet(plane)
        plane.query_range("m", start=0.0, end=100.0, step=10.0)
        plane.query_range("m", start=0.0, end=100.0, step=10.0)  # cache hit
        agg.poll_once()
        text = store.current().encode().decode()
        assert 'tpu_aggregator_fleet_queries_total{route="query_range"} 2' in text
        assert "tpu_aggregator_fleet_query_cache_hits_total 1" in text
        assert "tpu_aggregator_fleet_query_cache_misses_total 1" in text
        assert "tpu_aggregator_fleet_query_seconds_bucket" in text
        assert "tpu_aggregator_fleet_query_partial_total 0" in text
        # debug_vars exposes plane occupancy
        assert agg.debug_vars()["fleet_query"]["cache_entries"] == 1
        agg.close()
        plane.close()

    def test_partial_counter_rises(self):
        plane = make_plane({"h0:1": ConnectionRefusedError("down")})
        plane.query_range("m", start=0.0, end=100.0)
        from tpu_pod_exporter.metrics import SnapshotBuilder

        b = SnapshotBuilder()
        plane.emit(b)
        snap = b.build()
        assert snap.samples(
            "tpu_aggregator_fleet_query_partial_total")[()] == 1.0
        assert snap.samples(
            "tpu_aggregator_fleet_query_target_errors_total")[("h0:1",)] == 1.0
        plane.close()


@pytest.fixture
def fleet_server():
    plane = make_plane({"h0:1": node_rows("h0:1"),
                        "h1:1": node_rows("h1:1")})
    server = MetricsServer(SnapshotStore(), host="127.0.0.1", port=0,
                           fleet=plane)
    server.start()
    yield plane, server, f"http://127.0.0.1:{server.port}"
    server.stop()
    plane.close()


def get_json(url):
    try:
        resp = urllib.request.urlopen(url, timeout=5)
        return resp.status, json.loads(resp.read()), dict(resp.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


class TestHttpRouting:
    def test_query_range_envelope_over_http(self, fleet_server):
        _plane, _server, base = fleet_server
        status, doc, _ = get_json(
            base + "/api/v1/query_range?metric=tpu_hbm_used_bytes"
                   "&start=0&end=2000"
        )
        assert status == 200
        assert doc["partial"] is False
        assert doc["fleet"]["merged_series"] == 4
        assert doc["data"]["resultType"] == "matrix"

    def test_param_validation_shared_with_node_path(self, fleet_server):
        _plane, _server, base = fleet_server
        for path in (
            "/api/v1/query_range",                         # missing metric
            "/api/v1/query_range?metric=m&start=abc",
            "/api/v1/query_range?metric=m&start=0&step=1",  # resolution cap
            "/api/v1/query_range?metric=m&agg=median",      # bad agg
            "/api/v1/window_stats?metric=m&window=0",
        ):
            status, doc, _ = get_json(base + path)
            assert status == 400, path
            assert doc["status"] == "error"

    def test_api_fence_shared_429_with_retry_after(self, fleet_server):
        _plane, server, base = fleet_server
        handler = server._httpd.RequestHandlerClass
        assert handler.api_sem is not None  # fence active with fleet only
        assert handler.api_sem.acquire(timeout=1)
        assert handler.api_sem.acquire(timeout=1)
        try:
            status, doc, headers = get_json(base + "/api/v1/series")
            assert status == 429
            assert "too many" in doc["error"]
            assert headers.get("Retry-After") == "1"
        finally:
            handler.api_sem.release()
            handler.api_sem.release()

    def test_agg_param_validated_on_node_local_path_too(self):
        from tpu_pod_exporter.history import HistoryStore

        h = HistoryStore(capacity=8)
        h.append("m", {}, 1.0)
        server = MetricsServer(SnapshotStore(), host="127.0.0.1", port=0,
                               history=h)
        server.start()
        try:
            base = f"http://127.0.0.1:{server.port}"
            status, doc, _ = get_json(
                base + "/api/v1/query_range?metric=m&agg=median")
            assert status == 400 and "agg" in doc["error"]
        finally:
            server.stop()


class TestTracePropagation:
    def test_fanout_stamps_traceparent_and_spans_recorded(self):
        from tpu_pod_exporter.trace import Tracer, TraceStore

        seen = []

        def fetch(url, timeout_s, traceparent=None):
            seen.append(traceparent)
            return {"status": "ok",
                    "data": {"resultType": "matrix",
                             "result": node_rows("h0:1")}}

        ts = TraceStore(max_traces=8)
        plane = FleetQueryPlane(
            ("h0:1", "h1:1"), fetch=fetch,
            tracer=Tracer(ts, slow_poll_s=0.0, root_name="query"),
        )
        plane.query_range("m", start=0.0, end=100.0)
        assert len(seen) == 2 and all(tp for tp in seen)
        [trace] = ts.last(1)
        names = [s.name for s in trace.spans]
        assert "fanout" in names and "merge" in names
        assert trace.root.name == "query"
        plane.close()

    def test_plain_fetch_not_forced_traceparent(self):
        # A 2-arg injected fetch must keep working with tracing on.
        from tpu_pod_exporter.trace import Tracer, TraceStore

        plane = FleetQueryPlane(
            ("h0:1",), fetch=make_fetch({"h0:1": node_rows("h0:1")}),
            tracer=Tracer(TraceStore(max_traces=8), slow_poll_s=0.0,
                          root_name="query"),
        )
        env = plane.query_range("m", start=0.0, end=100.0)
        assert env["fleet"]["ok"] == 1
        plane.close()

    def test_node_side_api_records_remote_span(self):
        from tpu_pod_exporter.history import HistoryStore
        from tpu_pod_exporter.trace import TraceStore, format_traceparent

        h = HistoryStore(capacity=8)
        h.append("m", {}, 1.0)
        ts = TraceStore(max_traces=8)
        server = MetricsServer(SnapshotStore(), host="127.0.0.1", port=0,
                               history=h, trace=ts)
        server.start()
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{server.port}/api/v1/series",
                headers={"traceparent": format_traceparent(
                    "ab" * 16, "cd" * 8)},
            )
            urllib.request.urlopen(req, timeout=5).read()
            # The span records just AFTER the response body is written —
            # poll briefly instead of racing the handler thread.
            deadline = time.monotonic() + 2.0
            spans = ts.scrapes(8)
            while not spans and time.monotonic() < deadline:
                time.sleep(0.01)
                spans = ts.scrapes(8)
            assert len(spans) == 1
            assert spans[0].trace_id == "ab" * 16
        finally:
            server.stop()


class TestStatusFleet:
    def _envelope(self, partial=False):
        return {
            "status": "ok", "partial": partial,
            "data": {"result": [
                {"metric": "tpu_hbm_used_bytes",
                 "labels": {"host": "host-a", "chip_id": "0"},
                 "stats": {"last": 2.0 * 2**30},
                 "last_sample_wall_ts": time.time() - 2.0},
            ]},
            "targets": {
                "t0:1": {"state": "ok", "staleness_s": 2.0},
                "t1:1": {"state": "error", "error": "refused"},
            },
        }

    def test_render_fleet_table_and_footer(self):
        from tpu_pod_exporter.status import render_fleet

        out = render_fleet(
            {"tpu_hbm_used_bytes": self._envelope(partial=True)}, 60.0)
        assert "host-a" in out
        assert "1/2 ok" in out
        assert "PARTIAL" in out
        assert "t1:1 (error: refused)" in out

    def test_run_fleet_json_against_real_server(self, fleet_server, capsys):
        from tpu_pod_exporter.status import main as status_main

        _plane, _server, base = fleet_server
        rc = status_main(["--fleet", base.removeprefix("http://"), "--json"])
        assert rc == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["envelopes"]  # at least one metric answered
        env = next(iter(doc["envelopes"].values()))
        assert "targets" in env and "partial" in env

    def test_run_fleet_unreachable_is_clean_error(self, capsys):
        from tpu_pod_exporter.status import main as status_main

        rc = status_main(["--fleet", "127.0.0.1:1"])
        assert rc == 1
        assert "failed" in capsys.readouterr().err


class TestFleetSimAcceptance:
    def test_small_fleet_demo_end_to_end(self):
        # The make fleet-query-demo scenario at test scale: full merge,
        # staleness, traceparent join, kill→partial, p99 budget — with
        # tracing and persistence ON.
        from tpu_pod_exporter.loadgen.fleet import run_demo

        result = run_demo(
            n_targets=4, chips=2, polls=4, interval_s=0.01,
            queries=6, budget_ms=5000.0, kill_one=True, persist=True,
        )
        assert result["ok"], result
        assert result["full_merge"]["merged_series"] == 8
        assert result["after_kill"]["partial"] is True
        assert result["after_kill"]["ok_targets"] == 3
        assert result["after_kill"]["merged_series"] == 6
        assert result["node_side_query_spans"] > 0
