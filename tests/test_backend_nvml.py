"""NVML-shaped GPU backend + mixed-fleet plumbing (ISSUE 12).

Covers the second device family end to end: the simulated driver's NVML
call surface and error codes, the backend's degrade-not-die mapping
(total vs per-device failures — inverting main.go:119-137), the
collector's gpu_* twins and the per-pod memory join, record/replay of GPU
samples (committed fixture), chaos NVML error shapes, and the
family-keyed rollups up the aggregation tree.
"""

import json

import pytest

from tpu_pod_exporter.attribution import DeviceAllocation
from tpu_pod_exporter.attribution.fake import FakeAttribution
from tpu_pod_exporter.backend import BackendError, ChipInfo
from tpu_pod_exporter.backend.fake import FakeBackend
from tpu_pod_exporter.backend.nvml import (
    GpuScript,
    NvmlBackend,
    NvmlError,
    SimulatedNvmlDriver,
    normalize_nvml_code,
    run_gpu_demo,
    sim_driver_from_spec,
)
from tpu_pod_exporter.collector import Collector
from tpu_pod_exporter.metrics import SnapshotStore
from tpu_pod_exporter.metrics.parse import parse_families

GIB = 1024**3

FIXTURE = "tests/fixtures/gpu-recorded.jsonl"


def collect_once(backend, attribution=None, polls=1):
    store = SnapshotStore()
    c = Collector(backend, attribution or FakeAttribution(), store)
    for _ in range(polls):
        c.poll_once()
    c.close()
    return store.current(), c


def families_of(snap):
    return parse_families(snap.encode().decode())


# ---------------------------------------------------------------- the driver


class TestSimulatedDriver:
    def test_call_surface_and_step(self):
        drv = SimulatedNvmlDriver([
            GpuScript(mem_used_bytes=lambda s: float(s), mem_total_bytes=10.0),
        ])
        drv.nvmlInit()
        assert drv.nvmlDeviceGetCount() == 1  # step -> 0
        h = drv.nvmlDeviceGetHandleByIndex(0)
        assert drv.nvmlDeviceGetMemoryInfo(h)["used"] == 0.0
        assert drv.nvmlDeviceGetCount() == 1  # step -> 1
        assert drv.nvmlDeviceGetMemoryInfo(h)["used"] == 1.0
        assert drv.nvmlDeviceGetUUID(h) == "GPU-sim-0"
        drv.nvmlShutdown()
        assert drv.shutdown_calls == 1

    def test_uninitialized_is_an_nvml_error(self):
        drv = SimulatedNvmlDriver(1)
        with pytest.raises(Exception) as ei:
            drv.nvmlDeviceGetCount()
        assert getattr(ei.value, "value", None) == 1  # UNINITIALIZED

    def test_injected_fault_fifo(self):
        drv = SimulatedNvmlDriver(1)
        drv.nvmlInit()
        drv.inject("DeviceGetMemoryInfo", "gpu_is_lost", times=2)
        for _ in range(2):
            with pytest.raises(Exception) as ei:
                drv.nvmlDeviceGetMemoryInfo(0)
            assert getattr(ei.value, "value", None) == 15
        assert drv.nvmlDeviceGetMemoryInfo(0)["total"] > 0

    def test_code_normalization(self):
        assert normalize_nvml_code("gpu_is_lost") == (
            "NVML_ERROR_GPU_IS_LOST", 15)
        assert normalize_nvml_code("NVML_ERROR_TIMEOUT") == (
            "NVML_ERROR_TIMEOUT", 10)
        assert normalize_nvml_code(999) == ("NVML_ERROR_UNKNOWN", 999)
        with pytest.raises(ValueError):
            normalize_nvml_code("not_a_code")

    def test_spec_parsing(self):
        drv = sim_driver_from_spec({
            "gpus": [{"mem_total": 10, "mem_used": 4, "utilization": 50,
                      "processes": [[1, 2.0, "c"]]}],
            "faults": [{"call": "DeviceGetCount", "code": "timeout"}],
        })
        drv.nvmlInit()
        with pytest.raises(Exception):
            drv.nvmlDeviceGetCount()
        assert drv.nvmlDeviceGetCount() == 1

    @pytest.mark.parametrize("doc", (
        {},
        {"gpus": []},
        {"gpus": [1]},
        {"gpus": [{}], "faults": [{"call": "Init"}]},
    ))
    def test_bad_spec_raises(self, doc):
        with pytest.raises(ValueError):
            sim_driver_from_spec(doc)


# ---------------------------------------------------------------- the backend


class TestNvmlBackend:
    def test_sample_shape(self):
        drv = SimulatedNvmlDriver([
            GpuScript(mem_used_bytes=2 * GIB, mem_total_bytes=8 * GIB,
                      utilization_percent=42.0,
                      processes=[(100, GIB, "train")]),
        ])
        be = NvmlBackend(driver=drv)
        assert be.family == "gpu"
        s = be.sample()
        (chip,) = s.chips
        assert chip.info.family == "gpu"
        assert chip.info.device_ids[0] == "GPU-sim-0"
        assert chip.hbm_used_bytes == 2 * GIB
        assert chip.tensorcore_duty_cycle_percent == 42.0
        assert chip.processes[0].pid == 100
        be.close()
        assert drv.shutdown_calls == 1

    def test_total_failure_raises_coded_error(self):
        drv = SimulatedNvmlDriver(1)
        drv.inject("Init", "driver_not_loaded")
        be = NvmlBackend(driver=drv)
        with pytest.raises(NvmlError) as ei:
            be.sample()
        assert ei.value.code_name == "NVML_ERROR_DRIVER_NOT_LOADED"
        assert isinstance(ei.value, BackendError)
        # Init succeeded on retry: the backend recovers without rebuild.
        assert be.sample().chips

    def test_per_device_failure_degrades_that_chip_only(self):
        drv = SimulatedNvmlDriver(2)
        be = NvmlBackend(driver=drv)
        drv.inject("DeviceGetMemoryInfo", "gpu_is_lost")
        s = be.sample()
        assert len(s.chips) == 2
        assert s.chips[0].hbm_used_bytes is None  # absent beats fake-zero
        assert s.chips[1].hbm_used_bytes is not None
        assert any("GPU_IS_LOST" in e for e in s.partial_errors)

    def test_not_supported_utilization_is_absent_not_an_error(self):
        drv = SimulatedNvmlDriver([GpuScript(utilization_percent=None)])
        s = NvmlBackend(driver=drv).sample()
        assert s.chips[0].tensorcore_duty_cycle_percent is None
        assert s.partial_errors == ()

    def test_close_then_sample_reinitializes(self):
        drv = SimulatedNvmlDriver(1)
        be = NvmlBackend(driver=drv)
        be.sample()
        be.close()
        be.sample()  # the supervisor's reconnect path: Shutdown + Init
        assert drv.init_calls == 2
        assert drv.shutdown_calls == 1


# --------------------------------------------------------- collector surface


class TestGpuCollectorSurface:
    def make_backend(self):
        return NvmlBackend(driver=SimulatedNvmlDriver([
            GpuScript(mem_used_bytes=2 * GIB, mem_total_bytes=8 * GIB,
                      utilization_percent=30.0,
                      processes=[(100, GIB, "train"), (101, GIB / 2, "io")]),
            GpuScript(mem_used_bytes=GIB, mem_total_bytes=8 * GIB),
        ]))

    def test_gpu_twins_published(self):
        snap, _ = collect_once(self.make_backend())
        fams = families_of(snap)
        assert len(fams["gpu_chip_info"]) == 2
        assert len(fams["gpu_hbm_used_bytes"]) == 2
        assert len(fams["gpu_process_memory_used_bytes"]) == 2
        (up,) = fams["gpu_backend_up"]
        assert up.value == 1.0
        # The TPU namespace stays sample-less (declared families only).
        assert not fams.get("tpu_hbm_used_bytes")
        assert not fams.get("tpu_chip_info")

    def test_gpu_surface_absent_on_tpu_exporter(self):
        snap, _ = collect_once(FakeBackend(chips=2))
        text = snap.encode().decode()
        assert "gpu_backend_up" not in text
        assert "gpu_chip_info" not in text

    def test_per_pod_memory_joins_like_tpu(self):
        attr = FakeAttribution(allocations=[
            DeviceAllocation(pod="trainer", namespace="ml", container="main",
                             device_ids=("GPU-sim-0", "GPU-sim-1")),
        ])
        snap, _ = collect_once(self.make_backend(), attr)
        fams = families_of(snap)
        (count,) = fams["gpu_pod_chip_count"]
        assert count.labels["pod"] == "trainer"
        assert count.value == 2.0
        (mem,) = fams["gpu_pod_memory_used_bytes"]
        assert mem.value == 3 * GIB
        assert not fams.get("tpu_pod_chip_count")

    def test_gpu_backend_up_drops_on_wedge(self):
        drv = SimulatedNvmlDriver(1)
        be = NvmlBackend(driver=drv)
        store = SnapshotStore()
        c = Collector(be, FakeAttribution(), store)
        c.poll_once()
        drv.inject("DeviceGetCount", "gpu_is_lost")
        c.poll_once()
        fams = families_of(store.current())
        (up,) = fams["gpu_backend_up"]
        assert up.value == 0.0
        (eup,) = fams["tpu_exporter_up"]
        assert eup.value == 0.0  # identical degradation to a TPU wedge
        c.close()

    def test_process_rows_carry_pod_attribution(self):
        attr = FakeAttribution(allocations=[
            DeviceAllocation(pod="trainer", namespace="ml", container="main",
                             device_ids=("GPU-sim-0",)),
        ])
        snap, _ = collect_once(self.make_backend(), attr)
        rows = families_of(snap)["gpu_process_memory_used_bytes"]
        by_pid = {s.labels["pid"]: s for s in rows}
        assert by_pid["100"].labels["pod"] == "trainer"
        assert by_pid["100"].labels["comm"] == "train"
        assert by_pid["100"].value == GIB

    def test_mixed_host_splits_pod_rollups_by_family(self):
        # A recorded/fake mixed host (one GPU chip, one TPU chip, same
        # pod) must publish BOTH pod rollups — never a cross-family sum.
        infos = [ChipInfo(chip_id=0, family="gpu", device_ids=("g0",)),
                 ChipInfo(chip_id=1, family="tpu", device_ids=("t0",))]
        be = FakeBackend(chips=infos)
        attr = FakeAttribution(allocations=[
            DeviceAllocation(pod="p", namespace="n", container="c",
                             device_ids=("g0", "t0")),
        ])
        snap, _ = collect_once(be, attr)
        fams = families_of(snap)
        (g,) = fams["gpu_pod_chip_count"]
        (t,) = fams["tpu_pod_chip_count"]
        assert g.value == 1.0 and t.value == 1.0


# ------------------------------------------------------------- record/replay


class TestGpuRecorded:
    def test_fixture_replays_family_and_processes(self):
        from tpu_pod_exporter.backend.recorded import RecordedBackend

        rb = RecordedBackend(FIXTURE)
        assert rb.family == "gpu"
        s = rb.sample()
        assert all(c.info.family == "gpu" for c in s.chips)
        assert s.chips[0].processes[0].comm == "train"
        # The injected NVML fault replays as the partial error it was.
        assert any("NVML_ERROR_TIMEOUT" in e for e in s.partial_errors)

    def test_round_trip_preserves_gpu_fields(self):
        from tpu_pod_exporter.backend.recorded import (
            sample_from_dict,
            sample_to_dict,
        )

        drv = SimulatedNvmlDriver([
            GpuScript(mem_used_bytes=GIB, processes=[(7, 8.0, "x")]),
        ])
        s = NvmlBackend(driver=drv).sample()
        doc = json.loads(json.dumps(sample_to_dict(s)))
        back = sample_from_dict(doc)
        assert back.chips[0].info.family == "gpu"
        assert back.chips[0].processes == s.chips[0].processes

    def test_tpu_samples_omit_gpu_keys(self):
        from tpu_pod_exporter.backend.recorded import sample_to_dict

        s = FakeBackend(chips=1).sample()
        doc = sample_to_dict(s)
        assert "family" not in doc["chips"][0]
        assert "procs" not in doc["chips"][0]

    def test_gpu_demo_green(self, capsys):
        assert run_gpu_demo(FIXTURE) == 0
        assert "gpu-demo" in capsys.readouterr().out


# ------------------------------------------------------------------- chaos


class TestChaosNvmlShapes:
    def test_err_device_nvml_code(self):
        from tpu_pod_exporter.chaos import ChaosWrapper, parse_chaos_spec

        rules = parse_chaos_spec("err:device:1:x1:nvml=gpu_is_lost")
        w = ChaosWrapper(FakeBackend(chips=1), "device", rules, seed=1)
        with pytest.raises(NvmlError) as ei:
            w.sample()
        assert ei.value.code == 15
        assert w.sample().chips  # x1: next call passes through

    @pytest.mark.parametrize("spec", (
        "err:device:nvml=not_a_code",
        "err:attribution:nvml=gpu_is_lost",
        "hang:device:nvml=gpu_is_lost",
    ))
    def test_bad_nvml_rules_fail_loudly(self, spec):
        from tpu_pod_exporter.chaos import parse_chaos_spec

        with pytest.raises(ValueError):
            parse_chaos_spec(spec)


# ----------------------------------------------------------- mixed rollups


class TestMixedFleetRollups:
    def host_text(self, family: str, slice_name: str, host: str,
                  used: float, total: float) -> str:
        p = family
        duty = ("gpu_utilization_percent" if family == "gpu"
                else "tpu_tensorcore_duty_cycle_percent")
        accel = "a100" if family == "gpu" else "v5p"
        cl = (f'chip_id="0",device_path="",accelerator="{accel}",'
              f'slice_name="{slice_name}",host="{host}",worker_id="0",'
              f'pod="p-{family}",namespace="ns",container="c"')
        return (
            f'{p}_chip_info{{{cl},device_kind="",coords=""}} 1\n'
            f'{p}_hbm_used_bytes{{{cl}}} {used}\n'
            f'{p}_hbm_total_bytes{{{cl}}} {total}\n'
            f'{duty}{{{cl}}} 50\n'
        )

    def aggregate(self, bodies: dict):
        from tpu_pod_exporter.aggregate import SliceAggregator

        store = SnapshotStore()
        agg = SliceAggregator(
            tuple(bodies), store, fetch=lambda t, timeout_s: bodies[t],
        )
        agg.poll_once()
        agg.close()
        return store.current()

    def test_families_never_sum_together(self):
        snap = self.aggregate({
            "t0": self.host_text("tpu", "s-t", "h0", 100.0, 200.0),
            "g0": self.host_text("gpu", "s-g", "g0", 40.0, 80.0),
        })
        assert snap.value("tpu_slice_hbm_used_bytes",
                          ("s-t", "v5p", "tpu")) == 100.0
        assert snap.value("tpu_slice_hbm_used_bytes",
                          ("s-g", "a100", "gpu")) == 40.0
        assert snap.value("tpu_fleet_family_chip_count", ("tpu",)) == 1.0
        assert snap.value("tpu_fleet_family_chip_count", ("gpu",)) == 1.0
        assert snap.value("tpu_fleet_family_hbm_used_bytes",
                          ("tpu",)) == 100.0
        assert snap.value("tpu_fleet_family_hbm_used_bytes",
                          ("gpu",)) == 40.0

    def test_gpu_utilization_folds_into_duty_rollup(self):
        snap = self.aggregate({
            "g0": self.host_text("gpu", "s-g", "g0", 40.0, 80.0),
        })
        assert snap.value(
            "tpu_slice_tensorcore_duty_cycle_avg_percent",
            ("s-g", "a100", "gpu"),
        ) == 50.0

    def test_leaf_component_family_roundtrips_to_root(self):
        from tpu_pod_exporter.metrics import schema
        from tpu_pod_exporter.shard import fold_leaf_body

        samples = [
            (schema.TPU_LEAF_SLICE_COMPONENT.name,
             {"slice_name": "s", "accelerator": "a100", "family": "gpu",
              "field": "chips"}, 4.0),
            # A pre-family leaf's components default to the TPU family.
            (schema.TPU_LEAF_SLICE_COMPONENT.name,
             {"slice_name": "s", "accelerator": "v5p", "field": "chips"},
             2.0),
        ]
        view = fold_leaf_body("leaf-0", samples)
        assert view.slice_fields[("s", "a100", "gpu")]["chips"] == 4.0
        assert view.slice_fields[("s", "v5p", "tpu")]["chips"] == 2.0

    def test_history_fallback_probes_gpu_only_for_gpu_targets(self):
        import urllib.error

        from tpu_pod_exporter.aggregate import SliceAggregator

        bodies = {
            "t0": self.host_text("tpu", "s-t", "h0", 100.0, 200.0),
            "g0": self.host_text("gpu", "s-g", "g0", 40.0, 80.0),
        }
        down: set = set()
        calls: list[str] = []

        def fetch(t, timeout_s):
            if t in down:
                raise ConnectionError("down")
            return bodies[t]

        def hist_fetch(url, timeout_s):
            calls.append(url)
            raise urllib.error.HTTPError(url, 404, "no samples", None, None)

        store = SnapshotStore()
        agg = SliceAggregator(("t0", "g0"), store, fetch=fetch,
                              history_fallback_window_s=15.0,
                              history_fetch=hist_fetch,
                              breaker_failures=0)
        try:
            agg.poll_once()  # both up: the gpu-target latch learns g0
            down.update(("t0", "g0"))
            agg.poll_once()
        finally:
            agg.close()
        by_target = {
            "t0": [u for u in calls if "//t0" in u],
            "g0": [u for u in calls if "//g0" in u],
        }
        assert not any("gpu_" in u for u in by_target["t0"])
        assert any("gpu_hbm_used_bytes" in u for u in by_target["g0"])
        assert len(by_target["t0"]) == 8
        assert len(by_target["g0"]) == 14

    def test_store_rules_aggregate_by_family(self):
        from tpu_pod_exporter.metrics import SnapshotBuilder, schema
        from tpu_pod_exporter.store import evaluate_rule, parse_rules

        (rule,) = parse_rules(
            "fleet:chips:by_family = sum(tpu_slice_chip_count) by (family)")
        b = SnapshotBuilder()
        b.declare(schema.TPU_SLICE_CHIP_COUNT)
        b.add(schema.TPU_SLICE_CHIP_COUNT, 8.0, ("s0", "v5p", "tpu"))
        b.add(schema.TPU_SLICE_CHIP_COUNT, 4.0, ("s1", "v5p", "tpu"))
        b.add(schema.TPU_SLICE_CHIP_COUNT, 2.0, ("s2", "a100", "gpu"))
        out = dict(
            (labels["family"], value)
            for labels, value in evaluate_rule(rule, b.build(timestamp=0.0))
        )
        assert out == {"tpu": 12.0, "gpu": 2.0}


# ----------------------------------------------------------------- app wiring


class TestAppWiring:
    def test_backend_nvml_sim_flag(self):
        from tpu_pod_exporter.app import build_backend
        from tpu_pod_exporter.config import ExporterConfig

        be = build_backend(ExporterConfig(backend="nvml", nvml_sim_gpus=3))
        assert be.family == "gpu"
        assert len(be.sample().chips) == 3

    def test_backend_nvml_spec_file(self, tmp_path):
        from tpu_pod_exporter.app import build_backend
        from tpu_pod_exporter.config import ExporterConfig

        spec = tmp_path / "sim.json"
        spec.write_text(json.dumps(
            {"gpus": [{"mem_total": 10, "mem_used": 4}]}))
        be = build_backend(ExporterConfig(
            backend="nvml", nvml_sim_spec=str(spec)))
        (chip,) = be.sample().chips
        assert chip.hbm_total_bytes == 10.0

    def test_gpu_backend_selects_gpu_resource_name(self):
        from tpu_pod_exporter.app import ExporterApp
        from tpu_pod_exporter.config import ExporterConfig

        cfg = ExporterConfig(backend="nvml", nvml_sim_gpus=1,
                             attribution="none", history_retention_s=0.0,
                             trace=False, phase_deadline_s=0.0, port=0)
        app = ExporterApp(cfg)
        try:
            assert app.resource_name == "nvidia.com/gpu"
        finally:
            app.collector.close()

    def test_farm_mixed_bodies(self):
        from tpu_pod_exporter.loadgen.fleet import SynthTargetFarm

        farm = SynthTargetFarm(16, chips=2, n_slices=8, gpu_slices=2)
        try:
            assert farm.family_of_slice(0) == "tpu"
            assert farm.family_of_slice(7) == "gpu"
            gpu_idx = next(i for i in range(16) if farm.family_of(i) == "gpu")
            body = farm.body(gpu_idx)
            assert "gpu_chip_info{" in body
            assert "gpu_pod_memory_used_bytes{" in body
            assert "tpu_chip_info{" not in body
            tpu_body = farm.body(0)
            assert "tpu_chip_info{" in tpu_body
            assert "gpu_" not in tpu_body
        finally:
            farm.close()
