"""HTTP surface tests: real sockets, scrape semantics (SURVEY.md §4.3)."""

import contextlib
import gzip
import urllib.request

import pytest

from tpu_pod_exporter.metrics import MetricSpec, SnapshotBuilder, SnapshotStore
from tpu_pod_exporter.server import MetricsServer


@pytest.fixture
def served_store():
    store = SnapshotStore()
    server = MetricsServer(store, host="127.0.0.1", port=0)
    server.start()
    yield store, f"http://127.0.0.1:{server.port}"
    server.stop()


def get(url, headers=None):
    req = urllib.request.Request(url, headers=headers or {})
    try:
        resp = urllib.request.urlopen(req, timeout=5)
        return resp.status, dict(resp.headers), resp.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


def put_snapshot(store, value=1.0):
    b = SnapshotBuilder()
    b.add(MetricSpec(name="test_metric", help="t"), value)
    store.swap(b.build())


class TestEndpoints:
    def test_metrics_empty_before_first_poll(self, served_store):
        _, base = served_store
        status, headers, body = get(base + "/metrics")
        assert status == 200
        assert headers["Content-Type"].startswith("text/plain")
        assert body == b""

    def test_metrics_after_swap(self, served_store):
        store, base = served_store
        put_snapshot(store, 42)
        status, _, body = get(base + "/metrics")
        assert status == 200
        assert b"test_metric 42\n" in body

    def test_scrape_serves_latest_snapshot(self, served_store):
        store, base = served_store
        put_snapshot(store, 1)
        put_snapshot(store, 2)
        _, _, body = get(base + "/metrics")
        assert b"test_metric 2\n" in body

    def test_healthz(self, served_store):
        _, base = served_store
        status, _, body = get(base + "/healthz")
        assert status == 200 and body == b"ok\n"

    def test_readyz_flips_on_first_snapshot(self, served_store):
        store, base = served_store
        status, _, _ = get(base + "/readyz")
        assert status == 503
        put_snapshot(store)
        status, _, _ = get(base + "/readyz")
        assert status == 200

    def test_gzip_negotiation(self, served_store):
        store, base = served_store
        put_snapshot(store, 3)
        status, headers, body = get(
            base + "/metrics", headers={"Accept-Encoding": "gzip"}
        )
        assert status == 200
        assert headers.get("Content-Encoding") == "gzip"
        assert b"test_metric 3\n" in gzip.decompress(body)

    def test_unknown_path_404(self, served_store):
        _, base = served_store
        status, _, _ = get(base + "/nope")
        assert status == 404

    def test_root_index(self, served_store):
        _, base = served_store
        status, _, body = get(base + "/")
        assert status == 200 and b"tpu-pod-exporter" in body


class TestLivenessStaleness:
    def test_healthz_trips_when_snapshot_goes_stale(self):
        import time

        from tpu_pod_exporter.metrics.registry import SnapshotBuilder

        store = SnapshotStore()
        server = MetricsServer(store, host="127.0.0.1", port=0, health_max_age_s=0.2)
        server.start()
        base = f"http://127.0.0.1:{server.port}"
        try:
            status, _, _ = get(base + "/healthz")
            assert status == 200  # no snapshot yet: startup, not a stall
            b = SnapshotBuilder()
            b.add(MetricSpec(name="m", help="h"), 1)
            store.swap(b.build())
            status, _, _ = get(base + "/healthz")
            assert status == 200
            time.sleep(0.4)  # poll "wedges": no further swaps
            status, _, body = get(base + "/healthz")
            assert status == 503
            assert b"poll stalled" in body
            store.swap(b.build())  # poll recovers
            status, _, _ = get(base + "/healthz")
            assert status == 200
        finally:
            server.stop()


class TestLifecycle:
    def test_stop_before_start_does_not_deadlock(self):
        server = MetricsServer(SnapshotStore(), host="127.0.0.1", port=0)
        server.stop()  # must release the port without hanging


class TestPortConflict:
    def test_second_bind_fails_loudly(self):
        store = SnapshotStore()
        first = MetricsServer(store, host="127.0.0.1", port=0)
        first.start()
        try:
            with pytest.raises(OSError):
                MetricsServer(store, host="127.0.0.1", port=first.port)
        finally:
            first.stop()


class TestOpenMetrics:
    OM_ACCEPT = {
        "Accept": "application/openmetrics-text;version=1.0.0;q=0.9,text/plain;q=0.5"
    }

    def _counter_snapshot(self, store):
        from tpu_pod_exporter.metrics.registry import COUNTER

        b = SnapshotBuilder()
        b.add(MetricSpec(name="g", help="a gauge"), 1.0)
        b.add(
            MetricSpec(name="c_total", help="a counter", type=COUNTER,
                       label_names=("x",)),
            3.0,
            ("v",),
        )
        store.swap(b.build())

    def test_negotiated_content_type_and_eof(self, served_store):
        store, base = served_store
        self._counter_snapshot(store)
        status, headers, body = get(base + "/metrics", headers=self.OM_ACCEPT)
        assert status == 200
        assert headers["Content-Type"].startswith("application/openmetrics-text")
        assert body.endswith(b"# EOF\n")
        # Counter family headers drop the _total suffix; samples keep it.
        assert b"# TYPE c counter" in body
        assert b'c_total{x="v"} 3' in body

    def test_plain_scrape_unchanged(self, served_store):
        store, base = served_store
        self._counter_snapshot(store)
        status, headers, body = get(base + "/metrics")
        assert headers["Content-Type"].startswith("text/plain")
        assert b"# EOF" not in body
        assert b"# TYPE c_total counter" in body

    def test_q_zero_refuses_openmetrics(self, served_store):
        # Explicit q=0 on the OpenMetrics token means "never send me this".
        store, base = served_store
        self._counter_snapshot(store)
        status, headers, body = get(
            base + "/metrics",
            headers={"Accept": "application/openmetrics-text;q=0, text/plain"},
        )
        assert headers["Content-Type"].startswith("text/plain")
        assert b"# EOF" not in body

    def test_counter_header_rewrite_is_line_anchored(self, served_store):
        # A HELP text *containing* "# HELP c_total " mid-line must not be
        # rewritten in place of the real header line.
        from tpu_pod_exporter.metrics.registry import COUNTER

        store, base = served_store
        b = SnapshotBuilder()
        b.add(MetricSpec(name="a", help="docs mention # HELP c_total here"), 1.0)
        b.add(MetricSpec(name="c_total", help="a counter", type=COUNTER), 3.0)
        store.swap(b.build())
        status, headers, body = get(base + "/metrics", headers=self.OM_ACCEPT)
        assert b"# HELP a docs mention # HELP c_total here\n" in body
        assert b"\n# HELP c a counter\n" in body
        assert b"# TYPE c counter" in body

    def test_openmetrics_gzip(self, served_store):
        store, base = served_store
        self._counter_snapshot(store)
        status, headers, body = get(
            base + "/metrics",
            headers={**self.OM_ACCEPT, "Accept-Encoding": "gzip"},
        )
        assert headers.get("Content-Encoding") == "gzip"
        assert gzip.decompress(body).endswith(b"# EOF\n")

    def test_strict_openmetrics_parser_accepts_full_exporter_surface(self):
        """The reference OpenMetrics parser (prometheus_client) must parse a
        real collector snapshot — counters, info-style gauges, and all."""
        from prometheus_client.openmetrics.parser import text_string_to_metric_families

        from tpu_pod_exporter.attribution.fake import FakeAttribution, simple_allocation
        from tpu_pod_exporter.backend.fake import FakeBackend, FakeChipScript
        from tpu_pod_exporter.collector import Collector

        store = SnapshotStore()
        backend = FakeBackend(
            chips=2,
            script=FakeChipScript(
                hbm_total_bytes=8.0, hbm_used_bytes=2.0, ici_bytes_per_step=10.0
            ),
        )
        attr = FakeAttribution([simple_allocation("p", ["0"], namespace="n")])
        c = Collector(backend, attr, store, legacy_metrics=True)
        c.poll_once()
        c.poll_once()
        text = store.current().encode_openmetrics().decode()
        fams = {f.name: f for f in text_string_to_metric_families(text)}
        assert "tpu_ici_transferred_bytes" in fams  # counter, suffix-stripped
        assert "tpu_hbm_used_bytes" in fams
        samples = fams["tpu_ici_transferred_bytes"].samples
        assert all(s.name == "tpu_ici_transferred_bytes_total" for s in samples)
        # The poll-phase histogram must be a strict-OM-valid histogram family.
        hist = fams["tpu_exporter_poll_phase_duration_seconds"]
        assert hist.type == "histogram"
        counts = {
            s.labels["phase"]: s.value
            for s in hist.samples
            if s.name.endswith("_count")
        }
        # Observations land at poll END, so the snapshot published during
        # poll 2 carries exactly poll 1's observation.
        assert counts["total"] == 1.0


def test_scrape_duration_histogram_reaches_exposition():
    """Handler threads observe; the collector emits on the next poll —
    end-to-end through a real ExporterApp."""
    from tpu_pod_exporter.app import ExporterApp
    from tpu_pod_exporter.attribution.fake import FakeAttribution
    from tpu_pod_exporter.backend.fake import FakeBackend
    from tpu_pod_exporter.config import ExporterConfig

    app = ExporterApp(
        ExporterConfig(port=0, host="127.0.0.1", interval_s=30.0,
                       backend="fake", fake_chips=1, attribution="none"),
        backend=FakeBackend(chips=1), attribution=FakeAttribution(),
    )
    app.start()
    try:
        import time

        base = f"http://127.0.0.1:{app.port}"
        for _ in range(3):
            get(base + "/metrics")
        # The observer runs on the handler thread just after the body write,
        # so the client can be back here before the observation lands —
        # poll-and-retry instead of assuming ordering.
        deadline = time.monotonic() + 5.0
        count = -1.0
        while time.monotonic() < deadline:
            app.collector.poll_once()
            body = get(base + "/metrics")[2].decode()
            lines = [
                l for l in body.splitlines()
                if l.startswith("tpu_exporter_scrape_duration_seconds_count")
            ]
            count = float(lines[0].split()[-1]) if lines else -1.0
            if count >= 3:
                break
            time.sleep(0.05)
        assert count >= 3
        assert "# TYPE tpu_exporter_scrape_duration_seconds histogram" in body
    finally:
        app.stop()


class TestAcceptParsing:
    """accepts_openmetrics q-value semantics (RFC 9110 §12.4.2 subset)."""

    def test_cases(self):
        from tpu_pod_exporter.server import accepts_openmetrics as acc

        assert acc("application/openmetrics-text") is True
        assert acc("application/openmetrics-text;version=1.0.0;q=0.9") is True
        assert acc("application/openmetrics-text;q=0, text/plain") is False
        assert acc("application/openmetrics-text;q=0.0") is False
        assert acc("application/openmetrics-text; q=0 ") is False
        # client prefers text (om down-weighted below text/plain's q=1)
        assert acc("text/plain, application/openmetrics-text ;q=0.001") is False
        # om down-weighted but still above text/plain
        assert acc("text/plain;q=0.5, application/openmetrics-text;q=0.9") is True
        # the Prometheus >=2.5 header shape
        assert acc(
            "application/openmetrics-text;version=1.0.0;q=0.75,"
            "text/plain;version=0.0.4;q=0.5"
        ) is True
        # equal preference goes to the richer format
        assert acc("text/plain;q=0.5, application/openmetrics-text;q=0.5") is True
        # wildcard sets text/plain's implicit q
        assert acc("*/*;q=1, application/openmetrics-text;q=0.2") is False
        assert acc("text/plain") is False
        assert acc("") is False
        assert acc("APPLICATION/OpenMetrics-Text") is True
        # malformed q counts as accepting (q defaults to 1)
        assert acc("application/openmetrics-text;q=abc") is True


def blocking_store(release, entered):
    """A store whose snapshots block inside encode() until released —
    holds handler threads inside the guarded section deterministically."""
    store = SnapshotStore()
    put_snapshot(store, 7)
    real = store.current()

    class BlockingSnapshot:
        timestamp = real.timestamp
        series_count = real.series_count

        @staticmethod
        def encode():
            entered.release()
            release.acquire()
            return real.encode()

        encode_openmetrics = encode
        encode_gzip = encode
        encode_openmetrics_gzip = encode

    class BlockingStore:
        @staticmethod
        def current():
            return BlockingSnapshot

    return BlockingStore()


class HeldServer:
    __slots__ = ("server", "base", "release", "holders", "holder_results")

    def __init__(self, server, base, release, holders, holder_results):
        self.server = server
        self.base = base
        self.release = release
        self.holders = holders
        self.holder_results = holder_results

    def free_holders(self):
        """Release the held scrapes and WAIT for them to finish — callers
        asserting a post-release 200 must not race the holder threads out
        of their slots. Generous release count: every LATER scrape against
        the blocking store also consumes one permit in encode()."""
        self.release.release(64)
        for t in self.holders:
            t.join(timeout=5)


@contextlib.contextmanager
def held_server(n_holders: int = 1, **server_kwargs):
    """A MetricsServer with `n_holders` scrapes deterministically held
    inside the guarded render (the context cleans up regardless)."""
    import threading

    release = threading.Semaphore(0)
    entered = threading.Semaphore(0)
    server = MetricsServer(
        blocking_store(release, entered), host="127.0.0.1", port=0,
        **server_kwargs,
    )
    server.start()
    base = f"http://127.0.0.1:{server.port}"
    results: list[int] = []
    holders = [
        threading.Thread(target=lambda: results.append(get(base + "/metrics")[0]))
        for _ in range(n_holders)
    ]
    try:
        # Inside the try: a timed-out acquire on a loaded host must still
        # release the semaphores and stop the server, or the blocked holder
        # threads hang pytest at interpreter exit.
        for t in holders:
            t.start()
        for _ in holders:
            assert entered.acquire(timeout=5)  # holder is INSIDE the render
        yield HeldServer(server, base, release, holders, results)
    finally:
        release.release(64)
        entered.release(64)
        for t in holders:
            t.join(timeout=5)
        server.stop()


class TestScrapeConcurrencyGuard:
    """VERDICT r3 #8: a scrape storm must hit a 429 wall, not eat a core.
    At most N /metrics handlers run at once; the N+1th queues briefly and
    is rejected with Retry-After."""

    def test_excess_scrapes_get_429(self):
        # TWO slots, both held: N concurrent scrapes up to the limit must
        # all serve (guards against an off-by-one in the semaphore), and
        # the N+1th must hit the wall.
        with held_server(
            n_holders=2, max_concurrent_scrapes=2, scrape_queue_timeout_s=0.1
        ) as h:
            status, headers, body = get(h.base + "/metrics")
            assert status == 429
            assert headers["Retry-After"] == "1"
            assert b"too many" in body
            # ...while non-scrape endpoints stay unguarded.
            assert get(h.base + "/healthz")[0] == 200
            assert h.server.scrape_rejects["concurrency"] == 1
            # Release the holders: both complete fine and slots free up.
            h.free_holders()
            assert h.holder_results == [200, 200]
            assert get(h.base + "/metrics")[0] == 200

    def test_reject_is_prerendered_and_closes_connection(self):
        with held_server(
            max_concurrent_scrapes=1, scrape_queue_timeout_s=0.05
        ) as h:
            base = h.base
            status, headers, body = get(base + "/metrics")
            assert status == 429
            # The pre-rendered wire bytes must still be a valid HTTP
            # response with the contract headers (VERDICT r4 #5).
            assert headers["Retry-After"] == "1"
            assert headers["Connection"] == "close"
            assert int(headers["Content-Length"]) == len(body)
            assert body == b"too many concurrent scrapes\n"

    def test_concurrent_rejects_count_exactly(self):
        # Advisor r4: the reject increment is lock-guarded — N concurrent
        # rejected scrapes must count exactly N, no lost updates under the
        # very storm the counter exists to measure.
        import threading

        with held_server(
            max_concurrent_scrapes=1, scrape_queue_timeout_s=0.05
        ) as h:
            server, base = h.server, h.base
            statuses = []

            def scrape():
                statuses.append(get(base + "/metrics")[0])

            n = 24
            threads = [threading.Thread(target=scrape) for _ in range(n)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=10)
            assert statuses.count(429) == n
            assert server.scrape_rejects["concurrency"] == n

    def test_guard_disabled_with_zero(self):
        store = SnapshotStore()
        put_snapshot(store)
        server = MetricsServer(
            store, host="127.0.0.1", port=0, max_concurrent_scrapes=0
        )
        server.start()
        try:
            assert get(f"http://127.0.0.1:{server.port}/metrics")[0] == 200
        finally:
            server.stop()


class TestScrapeRateCap:
    """VERDICT r4 #5: a sequential storm of full-body scrapes is pure
    kernel-copy CPU the concurrency guard cannot bound — above the token
    bucket's rate, scrapes get the pre-rendered 429 instead."""

    def test_storm_hits_rate_cap_then_recovers(self):
        import time

        store = SnapshotStore()
        put_snapshot(store)
        server = MetricsServer(
            store, host="127.0.0.1", port=0, max_scrapes_per_s=5.0,
            scrape_tarpit_s=0.0,  # keep the test fast; tarpit tested below
        )
        server.start()
        base = f"http://127.0.0.1:{server.port}"
        try:
            # Burst capacity is 2×rate = 10 tokens; 30 back-to-back scrapes
            # must drain it and hit the wall.
            statuses = [get(base + "/metrics")[0] for _ in range(30)]
            assert statuses[0] == 200           # bucket starts full
            assert statuses.count(429) >= 10    # the wall is real
            assert server.scrape_rejects["rate"] == statuses.count(429)
            # Refill: at 5/s, one token comes back well within a second.
            time.sleep(0.5)
            assert get(base + "/metrics")[0] == 200
            # Health endpoints are never rate-capped.
            assert get(base + "/healthz")[0] == 200
        finally:
            server.stop()

    def test_rate_cap_reject_is_tarpitted(self):
        # A fast 429 just speeds the storm's retry loop up; the reject must
        # hold the client for ~scrape_tarpit_s (cost: one sleeping thread,
        # not CPU).
        import time

        store = SnapshotStore()
        put_snapshot(store)
        server = MetricsServer(
            store, host="127.0.0.1", port=0, max_scrapes_per_s=0.5,
            scrape_tarpit_s=0.2,
        )
        server.start()
        base = f"http://127.0.0.1:{server.port}"
        try:
            for _ in range(2):  # drain the 1-token bucket (refill 0.5/s)
                get(base + "/metrics")
            t0 = time.monotonic()
            status = get(base + "/metrics")[0]
            elapsed = time.monotonic() - t0
            assert status == 429
            assert elapsed >= 0.15
        finally:
            server.stop()

    def test_concurrency_reject_refunds_rate_token(self):
        # Code-review r5: a scrape refused by the concurrency guard was
        # never served, so it must not count against the rate — a stall
        # would otherwise drain the bucket and 429 well-behaved scrapers
        # after it clears.
        with held_server(
            max_concurrent_scrapes=1, scrape_queue_timeout_s=0.05,
            max_scrapes_per_s=5.0, scrape_tarpit_s=0.0,
        ) as h:
            # 8 sem-rejects; each took then refunded a token (burst is 10,
            # and the holder itself consumed 1).
            for _ in range(8):
                assert get(h.base + "/metrics")[0] == 429
            # Free the holder's slot (joined, so no race on the slot);
            # the bucket must still hold ~9 tokens: 8 quick scrapes serve.
            h.free_holders()
            statuses = [get(h.base + "/metrics")[0] for _ in range(8)]
            assert statuses == [200] * 8

    def test_rate_cap_disabled_by_default(self):
        store = SnapshotStore()
        put_snapshot(store)
        server = MetricsServer(store, host="127.0.0.1", port=0)
        server.start()
        base = f"http://127.0.0.1:{server.port}"
        try:
            statuses = [get(base + "/metrics")[0] for _ in range(30)]
            assert statuses == [200] * 30
        finally:
            server.stop()

    def test_token_bucket_refills_to_burst_not_beyond(self):
        from tpu_pod_exporter.server import _TokenBucket

        b = _TokenBucket(rate=10.0, burst=3.0)
        assert [b.take() for _ in range(3)] == [True] * 3
        # Bucket just drained; an immediate take fails (refill in the
        # microseconds since is « 1 token at 10/s).
        assert b.take() is False
        b.last -= 10.0  # simulate 10 s idle: refill clamps at burst
        assert [b.take() for _ in range(3)] == [True] * 3
        assert b.take() is False


def test_scrape_rejects_surface_as_self_metric():
    """The 429 counter reaches the exporter's own exposition (and thus the
    TpuExporterPollErrors-style alerting surface) on the next poll."""
    from tpu_pod_exporter.app import ExporterApp
    from tpu_pod_exporter.attribution.fake import FakeAttribution
    from tpu_pod_exporter.backend.fake import FakeBackend
    from tpu_pod_exporter.config import ExporterConfig

    app = ExporterApp(
        ExporterConfig(port=0, host="127.0.0.1", interval_s=30.0,
                       backend="fake", fake_chips=1, attribution="none"),
        backend=FakeBackend(chips=1), attribution=FakeAttribution(),
    )
    app.start()
    try:
        base = f"http://127.0.0.1:{app.port}"
        body0 = get(base + "/metrics")[2]
        assert b'tpu_exporter_scrape_rejects_total{cause="concurrency"} 0\n' in body0
        assert b'tpu_exporter_scrape_rejects_total{cause="rate"} 0\n' in body0
        app.server.scrape_rejects["rate"] = 3  # as the guard would under a storm
        # Retry: the CollectorLoop's startup poll may still be in flight and
        # swap an older (rejects=0) snapshot AFTER our manual poll.
        import time

        deadline = time.monotonic() + 5.0
        body = b""
        while time.monotonic() < deadline:
            app.collector.poll_once()
            body = get(base + "/metrics")[2]
            if b'tpu_exporter_scrape_rejects_total{cause="rate"} 3\n' in body:
                break
            time.sleep(0.05)
        assert b'tpu_exporter_scrape_rejects_total{cause="rate"} 3\n' in body
    finally:
        app.stop()


class TestDebugStacks:
    """/debug/stacks — the pprof-equivalent SURVEY §5 asks for: a
    point-in-time dump of every thread's Python stack, served from a
    handler thread so it works even while another thread is wedged."""

    def test_wedged_thread_visible_with_blocking_site(self, served_store):
        import threading
        import time

        _, base = served_store
        started = threading.Event()
        release = threading.Event()

        def wedged_poll():
            started.set()
            release.wait()  # the "hung backend call"

        t = threading.Thread(target=wedged_poll, name="fake-poll", daemon=True)
        t.start()
        try:
            assert started.wait(timeout=5)
            # started.set() only proves the thread entered wedged_poll();
            # retry briefly until the dump catches it AT the wait site
            # (a loaded box can serve the first GET mid-bootstrap).
            text = ""
            for _ in range(50):
                status, headers, body = get(base + "/debug/stacks")
                assert status == 200
                assert headers["Content-Type"].startswith("text/plain")
                text = body.decode()
                if "release.wait()" in text:
                    break
                time.sleep(0.05)
            assert "(fake-poll)" in text
            # The dump must show WHERE the thread is blocked, not just that
            # it exists — that's the whole diagnostic value.
            assert "release.wait()" in text
            assert "in wedged_poll" in text
        finally:
            release.set()
            t.join(timeout=5)

    def test_every_live_thread_listed(self, served_store):
        import threading

        _, base = served_store
        _, _, body = get(base + "/debug/stacks")
        text = body.decode()
        # The handler thread serving this very request is live too.
        assert text.count("--- thread ") >= 1
        assert f"({threading.main_thread().name})" in text


class TestEventLoopRobustness:
    """Failure shapes specific to the event-loop server: a buggy worker
    task must still answer, and an error-closing connection must stop
    being read."""

    def test_worker_task_exception_answers_500(self):
        """An unexpected exception in a deferred worker task (here: a
        history backend raising TypeError) must produce a 500 and close —
        not a silently wedged connection that hangs the client forever."""

        class BrokenHistory:
            def series_list(self):
                raise TypeError("backend bug")

        store = SnapshotStore()
        put_snapshot(store)
        server = MetricsServer(
            store, host="127.0.0.1", port=0, history=BrokenHistory()
        )
        server.start()
        try:
            status, _, body = get(
                f"http://127.0.0.1:{server.port}/api/v1/series"
            )
            assert status == 500
            assert b"internal error" in body
        finally:
            server.stop()

    def test_worker_pool_burst_runs_in_parallel(self):
        """A burst of submits landing while one worker idles in cv.wait
        must spawn more workers (up to the cap), not serialize the whole
        batch onto the single idle thread via lost notify()s."""
        import threading
        import time

        from tpu_pod_exporter.server import _WorkerPool

        pool = _WorkerPool(4)
        primed = threading.Event()
        pool.submit(primed.set)
        assert primed.wait(2)
        time.sleep(0.1)  # let the worker reach its idle cv.wait
        lock = threading.Lock()
        active = 0
        peak = 0
        done = []

        def task():
            nonlocal active, peak
            with lock:
                active += 1
                peak = max(peak, active)
            time.sleep(0.3)
            with lock:
                active -= 1
                done.append(1)

        for _ in range(3):
            pool.submit(task)
        deadline = time.monotonic() + 5
        while len(done) < 3 and time.monotonic() < deadline:
            time.sleep(0.02)
        pool.shutdown()
        assert len(done) == 3
        assert peak >= 2, "burst serialized onto a single worker"

    def test_headerless_stream_gets_at_most_one_431_then_dies(self):
        """A client streaming bytes with no header terminator must be cut
        off after at most one 431 — never one error response per recv
        while its buffer grows at the client's send rate. (The server
        closes with client bytes still unread, so the teardown may be an
        RST that discards the in-flight 431 — 'at most one, then dead
        fast' is the invariant.)"""
        import socket
        import time

        store = SnapshotStore()
        put_snapshot(store)
        server = MetricsServer(store, host="127.0.0.1", port=0)
        server.start()
        try:
            s = socket.create_connection(("127.0.0.1", server.port),
                                         timeout=5)
            junk = b"x" * 65536
            got = b""
            dead = False
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                try:
                    s.sendall(junk)
                except OSError:
                    dead = True
                    break
                try:
                    s.settimeout(0.05)
                    chunk = s.recv(65536)
                    if not chunk:
                        dead = True
                        break
                    got += chunk
                except TimeoutError:
                    continue
                except OSError:
                    dead = True
                    break
                finally:
                    s.settimeout(5)
            assert dead, "server kept the header-less stream alive"
            assert got.count(b"HTTP/1.1 431") <= 1
            s.close()
        finally:
            server.stop()


class TestClientWriteTimeout:
    """Slow-client write defense (--client-write-timeout-s): a scraper that
    stops reading mid-body must not pin a handler thread — the blocked
    send times out (SO_SNDTIMEO), the connection drops, and the drop is
    counted for tpu_exporter_client_write_timeouts_total."""

    def test_stalled_reader_is_dropped_and_counted(self):
        import socket
        import time

        from tpu_pod_exporter.persist import RestoredSnapshot

        store = SnapshotStore()
        # A body far larger than the kernel's socket buffers, so the
        # server-side sendall() genuinely blocks on the stalled client.
        big = RestoredSnapshot(b"x 1\n" * (16 << 20 >> 2), time.time())
        store.swap(big)
        server = MetricsServer(
            store, host="127.0.0.1", port=0, client_write_timeout_s=0.5
        )
        server.start()
        try:
            c = socket.socket()
            c.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 4096)
            c.connect(("127.0.0.1", server.port))
            c.sendall(b"GET /metrics HTTP/1.1\r\nHost: x\r\n\r\n")
            # read nothing: the handler's send must block, then time out
            deadline = time.monotonic() + 10
            while (
                server.write_timeouts["total"] == 0
                and time.monotonic() < deadline
            ):
                time.sleep(0.05)
            assert server.write_timeouts["total"] == 1
            c.close()
        finally:
            server.stop()

    def test_fast_reader_unaffected(self):
        store = SnapshotStore()
        put_snapshot(store, 7)
        server = MetricsServer(
            store, host="127.0.0.1", port=0, client_write_timeout_s=0.5
        )
        server.start()
        try:
            base = f"http://127.0.0.1:{server.port}"
            status, _, body = get(base + "/metrics")
            assert status == 200 and b"test_metric 7\n" in body
            assert server.write_timeouts["total"] == 0
        finally:
            server.stop()


class TestWorkerPoolIdleReap:
    """ISSUE 15 satellite: a pool grown under a stall must shrink back.

    The old reap only fired when cv.wait() timed out; submit()'s notify()
    rotates through waiters, so ANY steady trickle of requests kept every
    storm-grown worker alive forever (BENCH_r06 slow_clients
    threads_after 17 vs 10). The reap now keys on each worker's idle age
    since ITS last completed task."""

    def test_pool_grows_then_reaps_to_baseline_while_trickling(self):
        import threading
        import time

        from tpu_pod_exporter.server import _WorkerPool

        pool = _WorkerPool(8, idle_expire_s=0.25)
        gate = threading.Event()
        started = threading.Semaphore(0)

        def stall():
            started.release()
            gate.wait(10.0)

        for _ in range(8):
            pool.submit(stall)
        for _ in range(8):
            assert started.acquire(timeout=5.0)
        assert pool.threads == 8
        gate.set()
        # A trickle of instant tasks — the exact traffic pattern that
        # defeated the timeout-only reap (each notify() refreshed a
        # DIFFERENT waiter's timeout). The idle-age reap shrinks the pool
        # to what the trickle actually needs.
        deadline = time.monotonic() + 8.0
        while time.monotonic() < deadline and pool.threads > 2:
            pool.submit(lambda: None)
            time.sleep(0.05)
        assert pool.threads <= 2, (
            f"pool never reaped: {pool.threads} threads after trickle"
        )
        pool.shutdown()

    def test_quiet_pool_reaps_fully(self):
        import time

        from tpu_pod_exporter.server import _WorkerPool

        pool = _WorkerPool(4, idle_expire_s=0.2)
        done = []
        for _ in range(4):
            pool.submit(lambda: done.append(1))
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and pool.threads:
            time.sleep(0.05)
        assert pool.threads == 0
        assert len(done) == 4
        pool.shutdown()
