"""Static concurrency contract analyzer (analysis/concurrency.py).

Synthetic mini-packages exercise each capability in isolation (lock
discovery, interprocedural edges, cycles, ownership, guarded flags,
witness cross-check); the real-tree tests pin the model the CI gate
actually enforces — the empty-baseline acceptance criterion lives here.
"""

import ast
from pathlib import Path

from tpu_pod_exporter.analysis import concurrency
from tpu_pod_exporter.analysis.concurrency import (
    ModeledEdge,
    OwnershipRule,
    build_model,
    cross_check,
)
from tpu_pod_exporter.analysis.engine import build_context, lint_package

_REPO_ROOT = str(Path(__file__).resolve().parent.parent)


def _trees(**modules: str) -> dict:
    """{"server": src} -> {"tpu_pod_exporter/server.py": ast}."""
    return {
        f"tpu_pod_exporter/{name.replace('.', '/')}.py": ast.parse(src)
        for name, src in modules.items()
    }


def _model(ownership=(), **modules: str):
    return build_model(_trees(**modules), ownership=ownership)


class TestLockDiscovery:
    def test_instance_class_module_and_local_locks(self):
        m = _model(a="""
import threading

_glock = threading.Lock()


class C:
    _clslock = threading.RLock()

    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition(threading.Lock())

    def f(self):
        tmp = threading.Lock()
        with tmp:
            pass
""")
        keys = set(m.locks)
        assert keys == {
            "a._glock", "a.C._clslock", "a.C._lock", "a.C._cv",
            "a.C.f.<tmp>",
        }
        assert m.locks["a.C._clslock"].kind == "rlock"
        assert m.locks["a.C._cv"].kind == "condition"
        # Creation-site lookup (the witness join key).
        glock = m.locks["a._glock"]
        assert m.lock_at("tpu_pod_exporter/a.py", glock.line) is glock

    def test_dataclass_field_lock_discovered(self):
        m = _model(a="""
import threading
from dataclasses import dataclass, field


@dataclass
class S:
    lock: threading.Lock = field(default_factory=threading.Lock)
""")
        assert "a.S.lock" in m.locks


class TestOrderGraph:
    def test_interprocedural_edge_and_no_false_cycle(self):
        m = _model(a="""
import threading


class Outer:
    def __init__(self):
        self._lock = threading.Lock()
        self._inner = Inner()

    def f(self):
        with self._lock:
            self._inner.g()


class Inner:
    def __init__(self):
        self._lock = threading.Lock()

    def g(self):
        with self._lock:
            pass
""")
        assert set(m.edges) == {("a.Outer._lock", "a.Inner._lock")}
        assert [d for d in m.findings if d.rule == "lock-order"] == []

    def test_opposite_orders_cycle(self):
        m = _model(a="""
import threading

_a = threading.Lock()
_b = threading.Lock()


def one():
    with _a:
        with _b:
            pass


def two():
    with _b:
        with _a:
            pass
""")
        cycles = [d for d in m.findings if d.rule == "lock-order"]
        assert len(cycles) == 1
        assert "a._a" in cycles[0].message and "a._b" in cycles[0].message

    def test_self_reacquire_through_call_chain_flagged(self):
        m = _model(a="""
import threading


class C:
    def __init__(self):
        self._lock = threading.Lock()

    def outer(self):
        with self._lock:
            self.helper()

    def helper(self):
        with self._lock:
            pass
""")
        finds = [d for d in m.findings if d.rule == "lock-order"]
        assert len(finds) == 1
        assert "re-acquisition" in finds[0].message

    def test_rlock_self_reacquire_not_flagged(self):
        m = _model(a="""
import threading


class C:
    def __init__(self):
        self._lock = threading.RLock()

    def outer(self):
        with self._lock:
            self.helper()

    def helper(self):
        with self._lock:
            pass
""")
        assert [d for d in m.findings if d.rule == "lock-order"] == []

    def test_cross_module_edge_via_import(self):
        m = _model(
            a="""
import threading
from tpu_pod_exporter.b import Buf


class Store:
    def __init__(self):
        self._lock = threading.Lock()
        self._buf = Buf()

    def replay(self):
        with self._lock:
            self._buf.scan()
""",
            b="""
import threading


class Buf:
    def __init__(self):
        self._lock = threading.Lock()

    def scan(self):
        with self._lock:
            pass
""")
        assert set(m.edges) == {("a.Store._lock", "b.Buf._lock")}


class TestIoChain:
    def test_transitive_io_under_lock_flagged(self):
        m = _model(a="""
import json
import threading


class C:
    def __init__(self):
        self._lock = threading.Lock()

    def serialize(self, doc):
        return json.dumps(doc)

    def bad(self, doc):
        with self._lock:
            return self.serialize(doc)

    def good(self, doc):
        with self._lock:
            snapshot = dict(doc)
        return self.serialize(snapshot)
""")
        finds = [d for d in m.findings if d.rule == "lock-io-chain"]
        assert len(finds) == 1
        assert "a.C.serialize" in finds[0].message
        # Anchored at the call site inside `bad`, not in `good`.
        assert finds[0].line == 15

    def test_call_after_release_not_flagged(self):
        m = _model(a="""
import os
import threading

_lock = threading.Lock()


def flush(f):
    os.fsync(f)


def fine(f):
    with _lock:
        pending = True
    if pending:
        flush(f)
""")
        assert [d for d in m.findings if d.rule == "lock-io-chain"] == []


class TestOwnership:
    _OWN = (OwnershipRule(
        "a.Buf.advance", ("sender-thread",), "single cursor mover"),)

    def test_wrong_thread_reach_flagged(self):
        m = _model(ownership=self._OWN, a="""
import threading


class Buf:
    def advance(self):
        pass


class Governor:
    def __init__(self, buf: Buf):
        self._buf = buf
        self._thread = threading.Thread(
            target=self._run, name="governor-thread", daemon=True)

    def _run(self):
        self._buf.advance()
""")
        finds = [d for d in m.findings if d.rule == "lock-ownership"]
        assert len(finds) == 1
        assert "governor-thread" in finds[0].message
        assert "single cursor mover" in finds[0].message

    def test_owner_thread_clean(self):
        m = _model(ownership=self._OWN, a="""
import threading


class Buf:
    def advance(self):
        pass


class Sender:
    def __init__(self, buf: Buf):
        self._buf = buf
        self._thread = threading.Thread(
            target=self._run, name="sender-thread", daemon=True)

    def _run(self):
        self._buf.advance()
""")
        assert [d for d in m.findings if d.rule == "lock-ownership"] == []

    def test_rotted_table_entry_is_a_finding(self):
        m = _model(
            ownership=(OwnershipRule("a.Gone.f", ("x",), "gone"),),
            a="import threading\n")
        finds = [d for d in m.findings if d.rule == "lock-ownership"]
        assert len(finds) == 1
        assert "table rotted" in finds[0].message

    def test_guarded_flag_read_outside_lock_flagged(self):
        own = (OwnershipRule("a.Cache.put", ("*",), "re-check under lock",
                             guarded_flag="_enabled"),)
        m = _model(ownership=own, a="""
import threading


class Cache:
    def __init__(self):
        self._lock = threading.Lock()
        self._enabled = True

    def put(self, k, v):
        if not self._enabled:
            return
        with self._lock:
            pass
""")
        finds = [d for d in m.findings if d.rule == "lock-ownership"]
        assert len(finds) == 1
        assert "outside the instance lock" in finds[0].message

    def test_guarded_flag_read_inside_lock_clean(self):
        own = (OwnershipRule("a.Cache.put", ("*",), "re-check under lock",
                             guarded_flag="_enabled"),)
        m = _model(ownership=own, a="""
import threading


class Cache:
    def __init__(self):
        self._lock = threading.Lock()
        self._enabled = True

    def put(self, k, v):
        with self._lock:
            if not self._enabled:
                return
""")
        assert [d for d in m.findings if d.rule == "lock-ownership"] == []


class TestThreadRoots:
    def test_roles_from_thread_names_and_closures(self):
        m = _model(a="""
import threading


def work():
    pass


def spawn():
    def closure():
        work()
    t = threading.Thread(target=closure, name="my-worker", daemon=True)
    t.start()
""")
        roots = {(r.role, r.func) for r in m.roots}
        assert ("my-worker", "a.spawn.<closure>") in roots
        # Role propagates through the call graph.
        assert "my-worker" in m.roles["a.work"]


class TestCrossCheck:
    def _real_model(self):
        return concurrency.get_model(build_context(_REPO_ROOT))

    def test_real_witnessed_edge_ok(self):
        m = self._real_model()
        store = next(k for k in m.locks.values()
                     if k.key == "store.FleetStore._lock")
        wal = next(k for k in m.locks.values()
                   if k.key == "persist.WalBuffer._lock")
        dump = {
            "locks": [
                {"site": f"{store.path}:{store.line}", "path": store.path,
                 "line": store.line},
                {"site": f"{wal.path}:{wal.line}", "path": wal.path,
                 "line": wal.line},
            ],
            "edges": [{"from": f"{store.path}:{store.line}",
                       "to": f"{wal.path}:{wal.line}",
                       "example": "test"}],
            "inversions": [],
        }
        assert cross_check(m, dump) == []

    def test_unknown_lock_fails(self):
        m = self._real_model()
        dump = {"locks": [{"site": "tpu_pod_exporter/server.py:1",
                           "path": "tpu_pod_exporter/server.py",
                           "line": 1}],
                "edges": [], "inversions": []}
        problems = cross_check(m, dump)
        assert len(problems) == 1
        assert "no static identity" in problems[0]

    def test_unexplained_edge_fails(self):
        m = self._real_model()
        store = m.locks["store.FleetStore._lock"]
        wal = m.locks["persist.WalBuffer._lock"]
        dump = {
            "locks": [
                {"site": f"{store.path}:{store.line}", "path": store.path,
                 "line": store.line},
                {"site": f"{wal.path}:{wal.line}", "path": wal.path,
                 "line": wal.line},
            ],
            # Reverse of the static edge: never derivable.
            "edges": [{"from": f"{wal.path}:{wal.line}",
                       "to": f"{store.path}:{store.line}",
                       "example": "test"}],
            "inversions": [],
        }
        problems = cross_check(m, dump)
        assert len(problems) == 1
        assert "absent from the static order graph" in problems[0]

    def test_witness_inversion_fails(self):
        m = self._real_model()
        dump = {"locks": [], "edges": [],
                "inversions": [{"kind": "order-inversion",
                                "detail": "A -> B inverts B -> A"}]}
        problems = cross_check(m, dump)
        assert len(problems) == 1
        assert "inversion" in problems[0]

    def test_modeled_edges_explain_witnessed_edges(self):
        m = self._real_model()
        store = m.locks["store.FleetStore._lock"]
        wal = m.locks["persist.WalBuffer._lock"]
        dump = {
            "locks": [
                {"site": f"{store.path}:{store.line}", "path": store.path,
                 "line": store.line},
                {"site": f"{wal.path}:{wal.line}", "path": wal.path,
                 "line": wal.line},
            ],
            "edges": [{"from": f"{wal.path}:{wal.line}",
                       "to": f"{store.path}:{store.line}",
                       "example": "test"}],
            "inversions": [],
        }
        saved = concurrency.MODELED_EDGES
        concurrency.MODELED_EDGES = (ModeledEdge(
            "persist.WalBuffer._lock", "store.FleetStore._lock",
            "test declaration"),)
        try:
            assert cross_check(m, dump) == []
        finally:
            concurrency.MODELED_EDGES = saved


class TestRealTree:
    """The acceptance criteria: empty baseline on the live package."""

    def test_no_concurrency_findings_on_real_tree(self):
        findings = [
            d for d in lint_package(_REPO_ROOT)
            if d.rule in ("lock-order", "lock-ownership", "lock-io-chain")
        ]
        assert findings == [], "\n".join(d.format() for d in findings)

    def test_real_tree_model_shape(self):
        """Pins the load-bearing facts of the committed lock graph: the
        known edges exist, every lock resolves, the contract threads are
        rooted. If this breaks, deploy/lock-graph.json needs review (and
        regeneration via make lock-graph)."""
        m = concurrency.get_model(build_context(_REPO_ROOT))
        assert len(m.locks) >= 35
        assert m.unresolved_acquires == []
        assert ("store.FleetStore._lock", "persist.WalBuffer._lock") \
            in m.edges
        roles = {r.role for r in m.roots}
        for expected in ("tpu-exporter-poll", "tpu-egress-sender",
                         "tpu-egress-writer", "tpu-exporter-pressure",
                         "tpu-exporter-persist",
                         "tpu-exporter-http-worker-*"):
            assert expected in roles, expected
        # Ownership table functions all exist (no rot).
        for rule in concurrency.OWNERSHIP:
            assert rule.func in m.functions, rule.func

    def test_sender_owns_enforce_caps(self):
        """The egress cap-enforcement path is reachable ONLY from the
        sender thread — the single-consumer discipline the prose in
        egress.py promises."""
        m = concurrency.get_model(build_context(_REPO_ROOT))
        roles = set(m.roles["egress.RemoteWriteShipper._enforce_caps"])
        assert roles == {"tpu-egress-sender"}

    def test_committed_lock_graph_matches_model(self):
        """deploy/lock-graph.json is a REVIEWED artifact: regenerating it
        must be a no-op against the committed copy (make lock-graph)."""
        import json
        committed = Path(_REPO_ROOT) / "deploy" / "lock-graph.json"
        m = concurrency.get_model(build_context(_REPO_ROOT))
        assert committed.exists(), "run: make lock-graph"
        assert json.loads(committed.read_text()) == json.loads(
            json.dumps(m.graph_json(), sort_keys=True)), \
            "stale deploy/lock-graph.json — run: make lock-graph"
