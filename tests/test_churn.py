"""Pod-churn stress (SURVEY.md §4.4, baseline config 5).

Attribution flips at high rate while a scraper hammers /metrics at ~1 s-like
cadence. Invariants under churn:
- every scrape parses and is internally consistent (no half-applied polls),
- no stale series: the set of pods in any scrape is a subset of pods that
  were ever assigned, and dead pods disappear within one poll,
- counters never regress,
- series count stays bounded (no leak across reassignments).
"""

import threading
import time
import urllib.request

import pytest
from prometheus_client.parser import text_string_to_metric_families

from tpu_pod_exporter.app import ExporterApp
from tpu_pod_exporter.attribution.fake import FakeAttribution, simple_allocation
from tpu_pod_exporter.backend.fake import FakeBackend, FakeChipScript
from tpu_pod_exporter.config import ExporterConfig

CHIPS = 8


@pytest.fixture
def churn_app():
    backend = FakeBackend(
        chips=CHIPS,
        script=FakeChipScript(
            hbm_total_bytes=16 * 1024**3,
            hbm_used_bytes=1024**3,
            ici_link_count=4,
            ici_bytes_per_step=10_000.0,
        ),
    )
    attr = FakeAttribution()
    cfg = ExporterConfig(port=0, host="127.0.0.1", interval_s=0.01, accelerator="v5e-8")
    app = ExporterApp(cfg, backend=backend, attribution=attr)
    app.start()
    yield app, attr
    app.stop()


def scrape(port):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics", timeout=5) as r:
        return r.read().decode()


class TestChurn:
    def test_churn_invariants(self, churn_app):
        app, attr = churn_app
        stop = threading.Event()
        generation = [0]

        def churner():
            g = 0
            while not stop.is_set():
                g += 1
                generation[0] = g
                # alternate: two pods splitting the chips / one pod / none
                phase = g % 3
                if phase == 0:
                    attr.set_allocations([])
                elif phase == 1:
                    attr.set_allocations(
                        [simple_allocation(f"pod-a-{g}", [str(i) for i in range(4)]),
                         simple_allocation(f"pod-b-{g}", [str(i) for i in range(4, 8)])]
                    )
                else:
                    attr.set_allocations(
                        [simple_allocation(f"solo-{g}", [str(i) for i in range(CHIPS)])]
                    )
                time.sleep(0.003)

        t = threading.Thread(target=churner, daemon=True)
        t.start()
        try:
            prev_polls = 0.0
            for _ in range(60):
                fams = {
                    f.name: f for f in text_string_to_metric_families(scrape(app.port))
                }
                used = fams["tpu_hbm_used_bytes"].samples
                # exactly one series per chip, always
                assert len(used) == CHIPS
                chip_ids = sorted(int(s.labels["chip_id"]) for s in used)
                assert chip_ids == list(range(CHIPS))
                # attribution is all-or-nothing per snapshot: any named pods
                # belong to a single churn generation's naming scheme
                pods = {s.labels["pod"] for s in used if s.labels["pod"]}
                gens = {p.rsplit("-", 1)[-1] for p in pods}
                assert len(gens) <= 1, f"mixed generations in one scrape: {pods}"
                # monotonic self-counter
                polls = fams["tpu_exporter_polls"].samples[0].value
                assert polls >= prev_polls
                prev_polls = polls
                time.sleep(0.005)
        finally:
            stop.set()
            t.join(timeout=2)

    def test_series_count_bounded_under_churn(self, churn_app):
        app, attr = churn_app
        # Warm up past the startup snapshot: ICI bandwidth series exist only
        # from the second sampled poll (a rate needs a dt window), and the
        # scrape-duration histogram's series exist only once a poll AFTER
        # the first scrape emits its observation — either appearing
        # mid-loop would skew the count (by 32 and 14 series respectively).
        deadline = time.time() + 5
        while time.time() < deadline:
            text = scrape(app.port)
            if (
                "tpu_ici_link_bandwidth_bytes_per_second{" in text
                and "tpu_exporter_scrape_duration_seconds_count" in text
            ):
                break
            time.sleep(0.01)
        counts = []
        for g in range(50):
            attr.set_allocations(
                [simple_allocation(f"pod-{g}", [str(i) for i in range(CHIPS)])]
            )
            time.sleep(0.01)
            fams = {f.name: f for f in text_string_to_metric_families(scrape(app.port))}
            counts.append(sum(len(f.samples) for f in fams.values()))
        # churned pods must not accumulate series: counts stay flat
        assert max(counts) - min(counts) <= 2, counts

    def test_counters_never_regress_across_reassignment(self, churn_app):
        app, attr = churn_app
        last = {}
        for g in range(20):
            attr.set_allocations(
                [simple_allocation(f"p{g}", [str(i) for i in range(CHIPS)])]
            )
            time.sleep(0.01)
            fams = {f.name: f for f in text_string_to_metric_families(scrape(app.port))}
            for s in fams["tpu_ici_transferred_bytes"].samples:
                key = (s.labels["chip_id"], s.labels["link"], s.labels["pod"])
                if key in last:
                    assert s.value >= last[key]
                last[key] = s.value
