"""Pod-churn stress (SURVEY.md §4.4, baseline config 5).

Attribution flips at high rate while a scraper hammers /metrics at ~1 s-like
cadence. Invariants under churn:
- every scrape parses and is internally consistent (no half-applied polls),
- no stale series: the set of pods in any scrape is a subset of pods that
  were ever assigned, and dead pods disappear within one poll,
- counters never regress,
- series count stays bounded (no leak across reassignments).
"""

import threading
import time
import urllib.request

import pytest
from prometheus_client.parser import text_string_to_metric_families

from tpu_pod_exporter.app import ExporterApp
from tpu_pod_exporter.attribution.fake import FakeAttribution, simple_allocation
from tpu_pod_exporter.backend.fake import FakeBackend, FakeChipScript
from tpu_pod_exporter.config import ExporterConfig

CHIPS = 8


@pytest.fixture
def churn_app():
    backend = FakeBackend(
        chips=CHIPS,
        script=FakeChipScript(
            hbm_total_bytes=16 * 1024**3,
            hbm_used_bytes=1024**3,
            ici_link_count=4,
            ici_bytes_per_step=10_000.0,
        ),
    )
    attr = FakeAttribution()
    cfg = ExporterConfig(port=0, host="127.0.0.1", interval_s=0.01, accelerator="v5e-8")
    app = ExporterApp(cfg, backend=backend, attribution=attr)
    app.start()
    yield app, attr
    app.stop()


def scrape(port):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics", timeout=5) as r:
        return r.read().decode()


class TestChurn:
    def test_churn_invariants(self, churn_app):
        app, attr = churn_app
        stop = threading.Event()
        generation = [0]

        def churner():
            g = 0
            while not stop.is_set():
                g += 1
                generation[0] = g
                # alternate: two pods splitting the chips / one pod / none
                phase = g % 3
                if phase == 0:
                    attr.set_allocations([])
                elif phase == 1:
                    attr.set_allocations(
                        [simple_allocation(f"pod-a-{g}", [str(i) for i in range(4)]),
                         simple_allocation(f"pod-b-{g}", [str(i) for i in range(4, 8)])]
                    )
                else:
                    attr.set_allocations(
                        [simple_allocation(f"solo-{g}", [str(i) for i in range(CHIPS)])]
                    )
                time.sleep(0.003)

        t = threading.Thread(target=churner, daemon=True)
        t.start()
        try:
            prev_polls = 0.0
            for _ in range(60):
                fams = {
                    f.name: f for f in text_string_to_metric_families(scrape(app.port))
                }
                used = fams["tpu_hbm_used_bytes"].samples
                # exactly one series per chip, always
                assert len(used) == CHIPS
                chip_ids = sorted(int(s.labels["chip_id"]) for s in used)
                assert chip_ids == list(range(CHIPS))
                # attribution is all-or-nothing per snapshot: any named pods
                # belong to a single churn generation's naming scheme
                pods = {s.labels["pod"] for s in used if s.labels["pod"]}
                gens = {p.rsplit("-", 1)[-1] for p in pods}
                assert len(gens) <= 1, f"mixed generations in one scrape: {pods}"
                # monotonic self-counter
                polls = fams["tpu_exporter_polls"].samples[0].value
                assert polls >= prev_polls
                prev_polls = polls
                time.sleep(0.005)
        finally:
            stop.set()
            t.join(timeout=2)

    def test_series_count_bounded_under_churn(self, churn_app):
        app, attr = churn_app
        # Warm up past the startup snapshot: ICI bandwidth series exist only
        # from the second sampled poll (a rate needs a dt window), the
        # scrape-duration histogram's series exist only once a poll AFTER
        # the first scrape emits its observation, and the three
        # allocation-dependent series (pod rollups + kubelet allocated)
        # exist only once the first allocation is polled — any of them
        # appearing mid-loop would skew the count (by 32, 14, and 3
        # series respectively), so seed an allocation and wait for all of
        # them before counting.
        attr.set_allocations(
            [simple_allocation("pod-warm", [str(i) for i in range(CHIPS)])]
        )
        deadline = time.time() + 5
        while time.time() < deadline:
            text = scrape(app.port)
            if (
                "tpu_ici_link_bandwidth_bytes_per_second{" in text
                and "tpu_exporter_scrape_duration_seconds_count" in text
                and "tpu_pod_chip_count{" in text
            ):
                break
            time.sleep(0.01)
        counts = []
        for g in range(50):
            attr.set_allocations(
                [simple_allocation(f"pod-{g}", [str(i) for i in range(CHIPS)])]
            )
            time.sleep(0.01)
            fams = {f.name: f for f in text_string_to_metric_families(scrape(app.port))}
            counts.append(sum(len(f.samples) for f in fams.values()))
        # churned pods must not accumulate series: counts stay flat
        assert max(counts) - min(counts) <= 2, counts

    def test_counters_never_regress_across_reassignment(self, churn_app):
        app, attr = churn_app
        last = {}
        for g in range(20):
            attr.set_allocations(
                [simple_allocation(f"p{g}", [str(i) for i in range(CHIPS)])]
            )
            time.sleep(0.01)
            fams = {f.name: f for f in text_string_to_metric_families(scrape(app.port))}
            for s in fams["tpu_ici_transferred_bytes"].samples:
                key = (s.labels["chip_id"], s.labels["link"], s.labels["pod"])
                if key in last:
                    assert s.value >= last[key]
                last[key] = s.value


class TestParseCacheChurnBounds:
    """The round-5 parse-path caches under sustained worst-case churn:
    label VALUES change every round (fresh pod names — the string memo's
    worst case) while one target flaps across the layout-cache cap. A
    5-minute live soak (12.5k rounds) showed flat RSS; this fast version
    pins the bounded-invariant behavior that makes that true."""

    def test_caches_stay_bounded_and_rollups_stay_exact(self, monkeypatch):
        from tests.test_aggregate import make_host_text

        import tpu_pod_exporter.metrics.parse as parse_mod
        from tpu_pod_exporter.aggregate import SliceAggregator
        from tpu_pod_exporter.metrics import SnapshotStore

        # Shrink the global cache caps so 200 churn rounds actually CROSS
        # them (at production caps this workload never would, making the
        # closing asserts vacuous — code-review r5): every wholesale-clear
        # path runs many times during the loop, and correctness of the
        # rollups is asserted every round on top of it.
        monkeypatch.setattr(parse_mod, "_STR_MEMO_MAX", 64)
        monkeypatch.setattr(parse_mod, "_BLOCK_CACHE_MAX_BYTES", 4000)
        parse_mod._STR_MEMO.clear()

        base = make_host_text(0, chips=8)

        class ChurnFetch:
            round = 0

            def __call__(self, target, timeout_s):
                body = base.replace(
                    'pod="llm-train-0"', f'pod="job-{self.round}"'
                )
                if target == "flap:8000" and self.round % 2:
                    body = body * 3  # over the cap
                return body

        fetch = ChurnFetch()
        store = SnapshotStore()
        agg = SliceAggregator(("h0:8000", "flap:8000"), store, fetch=fetch)
        flap_layout = agg._parse_layouts["flap:8000"]
        for lo in agg._parse_layouts.values():
            lo.max_entries = base.count("\n") + 10
        key = {"slice_name": "slice-a", "accelerator": "v5p-64", "family": "tpu"}
        try:
            for r in range(200):
                fetch.round = r
                agg.poll_once()
                snap = store.current()
                # Rollups exact every round regardless of which parse path
                # (cached / uncached / re-cached) served each target:
                # h0 contributes 8 chips; flap contributes 8, or 24 when
                # its body is tripled (duplicate rows fold per-sample).
                expect = 8.0 + (24.0 if r % 2 else 8.0)
                assert snap.value("tpu_slice_chip_count", key) == expect, r
                assert flap_layout.oversize_logged == bool(r % 2), r
            fetch.round = 200  # one final under-cap round: flap re-caches
            agg.poll_once()
        finally:
            agg.close()
        # Bounded invariants that keep long-run RSS flat — non-vacuous
        # because the shrunken caps above were crossed repeatedly. The
        # block-cache invariant the code actually guarantees is "cleared
        # BEFORE the insert that would exceed the cap", so the counter may
        # legitimately sit one max-cost entry above it after an insert
        # (code-review r5 — asserting <= cap exactly would pass only by
        # luck of the fixture's label widths).
        assert len(parse_mod._STR_MEMO) <= parse_mod._STR_MEMO_MAX
        max_entry_cost = 200 + 8 * parse_mod._BLOCK_CACHE_MAX_ENTRY
        assert parse_mod._block_cache_bytes <= (
            parse_mod._BLOCK_CACHE_MAX_BYTES + max_entry_cost
        )
        assert flap_layout.entries and not flap_layout.oversize_logged  # re-cached
