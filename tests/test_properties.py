"""Property-based tests (hypothesis) for the exposition and counter layers.

SURVEY.md §4 calls for a pytest+hypothesis harness; these lock the two most
corruption-prone invariants:
- any label value / any float survives encode → Prometheus-parser roundtrip,
- CounterStore never regresses regardless of the raw counter sequence.
"""

import math

from hypothesis import HealthCheck, given, settings, strategies as st
from prometheus_client.parser import text_string_to_metric_families

from tpu_pod_exporter.metrics.registry import (
    CounterStore,
    MetricSpec,
    SnapshotBuilder,
    format_value,
)

# Any printable-ish text, plus the escape-relevant characters; NULs are
# stripped by design (they would truncate the native render path).
label_values = st.text(
    alphabet=st.characters(blacklist_categories=("Cs",), blacklist_characters="\x00"),
    max_size=50,
)

finite_floats = st.floats(allow_nan=False, allow_infinity=False, width=64)


class TestExpositionRoundtrip:
    @given(value=label_values, metric_value=finite_floats)
    @settings(max_examples=200, deadline=None)
    def test_any_label_value_roundtrips(self, value, metric_value):
        spec = MetricSpec(name="m", help="h", label_names=("l",))
        b = SnapshotBuilder()
        b.add(spec, metric_value, (value,))
        text = b.build().encode().decode()
        fams = {f.name: f for f in text_string_to_metric_families(text)}
        (sample,) = fams["m"].samples
        assert sample.labels["l"] == value
        assert sample.value == metric_value or (
            math.isnan(sample.value) and math.isnan(metric_value)
        )

    @given(values=st.lists(finite_floats, min_size=1, max_size=20, unique=True))
    @settings(max_examples=100, deadline=None)
    def test_distinct_series_all_survive(self, values):
        spec = MetricSpec(name="m", help="h", label_names=("i",))
        b = SnapshotBuilder()
        for i, v in enumerate(values):
            b.add(spec, v, (str(i),))
        text = b.build().encode().decode()
        fams = {f.name: f for f in text_string_to_metric_families(text)}
        assert len(fams["m"].samples) == len(values)

    @given(v=st.floats(width=64))
    @settings(max_examples=300, deadline=None)
    def test_format_value_roundtrips_every_float(self, v):
        s = format_value(v)
        parsed = float(s.replace("+Inf", "inf").replace("-Inf", "-inf"))
        if math.isnan(v):
            assert math.isnan(parsed)
        else:
            assert parsed == v

    @given(help_text=label_values)
    @settings(max_examples=100, deadline=None)
    def test_any_help_text_parses(self, help_text):
        spec = MetricSpec(name="m", help=help_text)
        b = SnapshotBuilder()
        b.add(spec, 1.0)
        list(text_string_to_metric_families(b.build().encode().decode()))


class TestCounterMonotonicity:
    @given(raws=st.lists(st.floats(min_value=0, max_value=1e15), min_size=1, max_size=50))
    @settings(max_examples=200, deadline=None)
    def test_observe_total_never_regresses(self, raws):
        c = CounterStore()
        prev = 0.0
        for raw in raws:
            out = c.observe_total("n", (), raw)
            assert out >= prev
            prev = out

    @given(
        deltas=st.lists(
            st.floats(min_value=-100, max_value=1e9, allow_nan=False), max_size=50
        )
    )
    @settings(max_examples=100, deadline=None)
    def test_inc_never_regresses(self, deltas):
        c = CounterStore()
        prev = 0.0
        for d in deltas:
            out = c.inc("n", (), d)
            assert out >= prev
            prev = out


class TestOwnParserRoundtrip:
    """Our renderer → OUR parser (metrics/parse.py, the aggregator's input
    path) must agree for any label value and any float — the same invariant
    the prometheus_client parser locks above, now for the in-house parser."""

    @given(value=label_values, metric_value=finite_floats)
    @settings(max_examples=200, deadline=None)
    def test_any_label_value_roundtrips_through_own_parser(self, value, metric_value):
        from tpu_pod_exporter.metrics.parse import parse_exposition

        spec = MetricSpec(name="m", help="h", label_names=("l",))
        b = SnapshotBuilder()
        b.add(spec, metric_value, (value,))
        text = b.build().encode().decode()
        (sample,) = parse_exposition(text)
        assert sample.labels["l"] == value
        assert sample.value == metric_value or (
            math.isnan(sample.value) and math.isnan(metric_value)
        )

    @given(v=st.floats(width=64))
    @settings(max_examples=200, deadline=None)
    def test_every_float_roundtrips_through_own_parser(self, v):
        from tpu_pod_exporter.metrics.parse import parse_exposition

        (sample,) = parse_exposition(f"m {format_value(v)}\n")
        assert sample.value == v or (math.isnan(sample.value) and math.isnan(v))


class TestFastBlockParseEquivalence:
    """The non-regex fast path must be a strict subset of the regex parser:
    wherever it answers at all, the answer is byte-identical; anything it
    declines falls back (so overall accepted grammar never widens)."""

    @given(
        pairs=st.lists(
            st.tuples(
                st.from_regex(r"[A-Za-z_][A-Za-z0-9_]{0,15}", fullmatch=True),
                label_values,
            ),
            min_size=0, max_size=6,
        )
    )
    @settings(max_examples=300)
    def test_fast_path_matches_regex_on_rendered_blocks(self, pairs):
        from tpu_pod_exporter.metrics.parse import (
            _parse_block_fast,
            _parse_block_uncached,
        )

        def esc(v):
            return v.replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")

        block = ",".join(f'{k}="{esc(v)}"' for k, v in pairs)
        want = _parse_block_uncached(block, block)
        got = _parse_block_fast(block)
        if got is not None:
            assert got == want
        # A decline is always safe: the caller falls back to the regex
        # parser (asserted by `want` parsing above), so accepted grammar
        # and results are unchanged. Declines beyond the obvious ones
        # (escapes, no trailing quote) exist — e.g. a value ending in a
        # comma makes the quote-comma split ambiguous, and the fast path
        # correctly refuses rather than guess.

    def test_fast_path_actually_accepts_the_common_shape(self):
        """Guard that the optimization applies at all: the exact block
        shape the collector renders must take the fast path (a regression
        to always-decline would silently lose the perf the path exists
        for)."""
        from tpu_pod_exporter.metrics.parse import _parse_block_fast

        block = (
            'chip_id="0",device_path="/dev/accel0",accelerator="v5p-64",'
            'slice_name="s",host="h0",worker_id="0",pod="p",namespace="ml",'
            'container="main"'
        )
        assert _parse_block_fast(block) == {
            "chip_id": "0", "device_path": "/dev/accel0",
            "accelerator": "v5p-64", "slice_name": "s", "host": "h0",
            "worker_id": "0", "pod": "p", "namespace": "ml",
            "container": "main",
        }

    @given(block=st.text(max_size=60))
    @settings(max_examples=300)
    def test_fast_path_never_accepts_what_regex_rejects(self, block):
        from tpu_pod_exporter.metrics.parse import (
            ParseError,
            _parse_block_fast,
            _parse_block_uncached,
        )

        got = _parse_block_fast(block)
        if got is None:
            return
        try:
            want = _parse_block_uncached(block, block)
        except ParseError:
            raise AssertionError(
                f"fast path accepted a block the regex rejects: {block!r}"
            ) from None
        assert got == want


class TestHistogramInvariants:
    """Histogram exposition invariants for ANY observation sequence:
    buckets cumulative non-decreasing, +Inf bucket == _count, _sum == the
    float sum, and the strict OpenMetrics parser accepts the output."""

    # Non-negative domain: strict OpenMetrics forbids a histogram _sum with
    # negative buckets or observations, and every histogram this exporter
    # defines is a duration (>= 0 by construction).
    @given(
        observations=st.lists(
            st.floats(min_value=0.0, max_value=1e6, allow_nan=False),
            min_size=1, max_size=100,
        )
    )
    @settings(max_examples=100, deadline=None)
    def test_histogram_invariants_hold(self, observations):
        from prometheus_client.openmetrics.parser import (
            text_string_to_metric_families as om_parse,
        )

        from tpu_pod_exporter.metrics.registry import (
            HistogramSpec,
            HistogramStore,
        )

        spec = HistogramSpec(
            name="h", help="h", buckets=(0.0, 0.5, 100.0)
        )
        store = HistogramStore(spec)
        for v in observations:
            store.observe(v)
        b = SnapshotBuilder()
        store.emit(b)
        om = b.build(timestamp=1.0).encode_openmetrics().decode()
        fams = {f.name: f for f in om_parse(om)}
        fam = fams["h"]
        assert fam.type == "histogram"
        buckets = [s for s in fam.samples if s.name == "h_bucket"]
        counts = [s.value for s in buckets]
        assert counts == sorted(counts)  # cumulative, non-decreasing
        count = next(s.value for s in fam.samples if s.name == "h_count")
        assert buckets[-1].labels["le"] == "+Inf"
        assert buckets[-1].value == count == len(observations)
        total = next(s.value for s in fam.samples if s.name == "h_sum")
        assert math.isclose(total, math.fsum(observations), rel_tol=1e-9, abs_tol=1e-6)
        # Exact bucket math, recomputed independently: each le bucket holds
        # the number of observations <= bound.
        for s, bound in zip(buckets[:-1], spec.buckets):
            assert s.value == sum(1 for v in observations if v <= bound)


class TestLayoutParserDifferential:
    """parse_exposition_layout must agree with parse_exposition on EVERY
    body — including corrupted ones — through any warm/cold cache state
    (code-review r5: the hit path once accepted brace-corrupted lines the
    reference parser rejects; the NATIVE whole-body path once accepted
    strtod's nan(123) payloads Python float() rejects). Parametrized over
    both parse paths so native coverage never depends on test order."""

    import pytest as _pytest

    @_pytest.fixture(params=["native", "pure"], autouse=True)
    def _parse_path(self, request, monkeypatch):
        if request.param == "pure":
            monkeypatch.setattr(
                "tpu_pod_exporter.metrics.parse._native_parse_layout",
                lambda layout, text: None,
            )
        else:
            from tpu_pod_exporter import nativelib

            if nativelib.load() is None:
                self._pytest.skip("native lib unavailable")

    _names = st.sampled_from(["m", "tpu_x", "other", "sk"])
    _line = st.one_of(
        # well-formed samples, labeled and bare, with/without timestamps
        st.tuples(
            _names,
            st.lists(
                st.tuples(
                    st.sampled_from(["a", "b", "host"]),
                    st.text(
                        alphabet=st.characters(
                            blacklist_categories=("Cs",),
                            blacklist_characters='\x00"\\\n',
                        ),
                        max_size=8,
                    ),
                ),
                max_size=3,
            ),
            st.floats(allow_nan=False, width=32),
            st.booleans(),
        ).map(
            lambda t: (
                t[0]
                + (
                    "{"
                    + ",".join(f'{k}="{v}"' for k, v in t[1])
                    + "}"
                    if t[1]
                    else ""
                )
                + f" {t[2]!r}"
                + (" 1700000000" if t[3] else "")
            )
        ),
        # comments / blanks
        st.sampled_from(["# HELP m h", "# TYPE m gauge", "", "# EOF"]),
        # junk/corruption shapes (incl. the brace-in-tail repro)
        st.sampled_from(
            [
                'm{a="1"} 5 m{a="2"} 6',
                "m",
                'm{a="x} 1',
                "m2 1",
                'tpu_x 5 {oops} 1',
                "m nope",
                # strtod-wider-than-float() shapes the native path must
                # decline (it did not always — code-review r5):
                "m nan(123)",
                "m 0x1p3",
                "m 1_0",
                "m 1,5",
                "m Infinity",
                "tpu_x -inf 1700000000",
            ]
        ),
    )

    @given(bodies=st.lists(st.lists(_line, max_size=12), min_size=1, max_size=4))
    @settings(
        max_examples=150, deadline=None,
        suppress_health_check=[HealthCheck.function_scoped_fixture],
    )
    def test_layout_parser_matches_reference_through_any_cache_state(
        self, bodies
    ):
        from tpu_pod_exporter.metrics.parse import (
            LayoutCache,
            ParseError,
            parse_exposition,
            parse_exposition_layout,
        )

        names = frozenset({"m", "tpu_x"})
        layout = LayoutCache()
        for lines in bodies:
            text = "\n".join(lines) + "\n"
            try:
                want = [
                    (s.name, s.labels, s.value)
                    for s in parse_exposition(text, names=names)
                ]
                want_err = None
            except ParseError as e:
                want, want_err = None, e
            if want_err is None:
                got = parse_exposition_layout(text, names, layout)
                assert got == want, text
            else:
                entries_before = layout.entries
                try:
                    parse_exposition_layout(text, names, layout)
                except ParseError:
                    pass
                else:
                    raise AssertionError(
                        f"layout parser accepted what reference rejects: {text!r}"
                    )
                assert layout.entries is entries_before  # cache untouched

    @given(
        bodies=st.lists(st.lists(_line, max_size=12), min_size=1, max_size=4),
        cap=st.integers(min_value=1, max_value=10),
    )
    @settings(
        max_examples=150, deadline=None,
        suppress_health_check=[HealthCheck.function_scoped_fixture],
    )
    def test_layout_parser_matches_reference_under_tiny_cache_cap(
        self, bodies, cap
    ):
        """Same differential, but with a cap small enough that bodies cross
        it freely — the oversize fast path, the small↔oversize transitions,
        and the flag state machine all get fuzzed. Invariants after every
        successful round: results equal the reference parser's regardless
        of cache state; oversize_logged mirrors whether THIS body was over
        the cap; an oversize round leaves nothing cached. After a
        ParseError round: every piece of cache state is untouched."""
        from tpu_pod_exporter.metrics.parse import (
            LayoutCache,
            ParseError,
            parse_exposition,
            parse_exposition_layout,
        )

        names = frozenset({"m", "tpu_x"})
        layout = LayoutCache(max_entries=cap)
        for lines in bodies:
            text = "\n".join(lines) + "\n"
            over = text.count("\n") + 1 > cap
            try:
                want = [
                    (s.name, s.labels, s.value)
                    for s in parse_exposition(text, names=names)
                ]
                want_err = None
            except ParseError as e:
                want, want_err = None, e
            if want_err is None:
                got = parse_exposition_layout(text, names, layout)
                assert [tuple(s) for s in got] == want, text
                assert layout.oversize_logged == over, text
                if over:
                    assert layout.entries == []
                    assert layout.native_built_for is None
                    assert layout.samples_template is None
            else:
                entries_before = layout.entries
                flag_before = layout.oversize_logged
                with self._pytest.raises(ParseError):
                    parse_exposition_layout(text, names, layout)
                assert layout.entries is entries_before
                assert layout.oversize_logged == flag_before
