"""Recording-rules ⇄ aggregator equivalence (VERDICT r3 #6).

``deploy/prometheus-rules.yaml`` promises that its recording rules compute
the *same* rollups the in-process aggregator serves (the file says "use one
or the other"). ``test_deploy.py`` only checks that referenced metric names
exist; this test actually **evaluates** every recording rule — via a tiny
PromQL-subset evaluator — against the same N-host exposition input the
aggregator consumes, and asserts numeric equality per label set. Editing a
rule expression (sum→avg, a dropped by-label, a renamed operand) now fails
CI instead of silently skewing dashboards.

Supported expression subset (everything the rules file uses):
  - ``sum by (l1, l2) (metric)`` / ``avg by (l1, l2) (metric)``
  - ``100 * <recorded> / <recorded>`` (label-joined on the shared by-set)
"""

from __future__ import annotations

import re
from pathlib import Path

import pytest
import yaml

from tpu_pod_exporter.aggregate import SliceAggregator
from tpu_pod_exporter.attribution.fake import FakeAttribution, simple_allocation
from tpu_pod_exporter.backend.fake import FakeBackend, FakeChipScript
from tpu_pod_exporter.collector import Collector
from tpu_pod_exporter.metrics import SnapshotStore
from tpu_pod_exporter.metrics.parse import parse_exposition
from tpu_pod_exporter.topology import HostTopology

RULES = Path(__file__).resolve().parent.parent / "deploy" / "prometheus-rules.yaml"
GIB = 1024**3

# record name → the aggregator series it must equal (checked exhaustively:
# an unmapped new recording rule fails the test until a mapping is added).
RECORD_TO_AGG = {
    "slice:tpu_hbm_used_bytes:sum": "tpu_slice_hbm_used_bytes",
    "slice:tpu_hbm_total_bytes:sum": "tpu_slice_hbm_total_bytes",
    "slice:tpu_hbm_used_percent:ratio": "tpu_slice_hbm_used_percent",
    "slice:tpu_tensorcore_duty_cycle_percent:avg":
        "tpu_slice_tensorcore_duty_cycle_avg_percent",
    "slice:tpu_ici_link_bandwidth_bytes_per_second:sum":
        "tpu_slice_ici_bytes_per_second",
    "slice:tpu_dcn_link_bandwidth_bytes_per_second:sum":
        "tpu_slice_dcn_bytes_per_second",
    "multislice:tpu_chip_info:count": "tpu_multislice_chip_count",
    "multislice:tpu_hbm_used_bytes:sum": "tpu_multislice_hbm_used_bytes",
    "multislice:tpu_dcn_link_bandwidth_bytes_per_second:sum":
        "tpu_multislice_dcn_bytes_per_second",
    "multislice:slices_reporting:count": "tpu_multislice_slices_reporting",
    "workload:tpu_pod_chip_count:sum": "tpu_workload_chip_count",
    "workload:tpu_pod_hbm_used_bytes:sum": "tpu_workload_hbm_used_bytes",
}

_AGG_RE = re.compile(r"^(sum|avg)\s+by\s+\(([^)]*)\)\s+\((\S+)\)$")
_RATIO_RE = re.compile(r"^100\s*\*\s*(\S+)\s*/\s*(\S+)$")
# The multi-slice info-series join:
#   sum|count by (G) ( metric * on (J) group_left (K)
#                      max by (M) (tpu_host_info{multislice_group!=""}) )
_JOIN_RE = re.compile(
    r"^(sum|count)\s+by\s+\(([^)]*)\)\s+\(\s*(\S+)\s*\*\s*on\s+\(([^)]*)\)"
    r"\s+group_left\s+\(([^)]*)\)\s+max\s+by\s+\(([^)]*)\)"
    r'\s+\((\w+)\{multislice_group!=""\}\)\s*\)$'
)
# Nested slice count over the join (slices REPORTING CHIPS, not merely
# having a live exporter):
#   count by (O) ( count by (I) ( metric * on (J) group_left (K)
#                  max by (M) (tpu_host_info{multislice_group!=""}) ) )
_NESTED_COUNT_JOIN_RE = re.compile(
    r"^count\s+by\s+\(([^)]*)\)\s+\(\s*count\s+by\s+\(([^)]*)\)\s+"
    r"\(\s*(\S+)\s*\*\s*on\s+\(([^)]*)\)\s+group_left\s+\(([^)]*)\)"
    r"\s+max\s+by\s+\(([^)]*)\)"
    r'\s+\((\w+)\{multislice_group!=""\}\)\s*\)\s*\)$'
)


def _split(raw: str) -> tuple[str, ...]:
    return tuple(l.strip() for l in raw.split(","))


def eval_rule(expr: str, samples, recorded):
    """Evaluate one rule expression.

    Returns ``(by_labels, {label_values_tuple: value})``. ``samples`` is the
    flat parsed-sample list; ``recorded`` maps already-evaluated record
    names to their results (rules may reference earlier records).
    """
    expr = " ".join(expr.split())  # yaml `>` folds keep stray newlines
    m = _AGG_RE.match(expr)
    if m:
        op, by_raw, metric = m.groups()
        by = tuple(l.strip() for l in by_raw.split(","))
        groups: dict[tuple, list[float]] = {}
        for s in samples:
            if s.name == metric:
                key = tuple(s.labels.get(l, "") for l in by)
                groups.setdefault(key, []).append(s.value)
        out = {
            k: (sum(v) if op == "sum" else sum(v) / len(v))
            for k, v in groups.items()
        }
        return by, out
    m = _JOIN_RE.match(expr)
    if m:
        op, by_raw, metric, on_raw, gl_raw, _max_by, info_name = m.groups()
        by = _split(by_raw)
        on = _split(on_raw)
        gl = _split(gl_raw)
        # Membership map from the info series (max-by dedup is implicit:
        # the value is always 1 and hosts of one slice agree on the group).
        member: dict[tuple, dict[str, str]] = {}
        for s in samples:
            if s.name == info_name and s.labels.get("multislice_group", ""):
                member[tuple(s.labels.get(l, "") for l in on)] = {
                    l: s.labels.get(l, "") for l in gl
                }
        groups: dict[tuple, list[float]] = {}
        for s in samples:
            if s.name != metric:
                continue
            extra = member.get(tuple(s.labels.get(l, "") for l in on))
            if extra is None:
                continue  # unmatched join drops the sample, like PromQL
            joined = {**s.labels, **extra}
            key = tuple(joined.get(l, "") for l in by)
            groups.setdefault(key, []).append(s.value)
        out = {
            k: (float(len(v)) if op == "count" else sum(v))
            for k, v in groups.items()
        }
        return by, out
    m = _NESTED_COUNT_JOIN_RE.match(expr)
    if m:
        outer_raw, inner_raw, metric, on_raw, gl_raw, _max_by, info_name = (
            m.groups()
        )
        outer = _split(outer_raw)
        inner = _split(inner_raw)
        on = _split(on_raw)
        gl = _split(gl_raw)
        member: dict[tuple, dict[str, str]] = {}
        for s in samples:
            if s.name == info_name and s.labels.get("multislice_group", ""):
                member[tuple(s.labels.get(l, "") for l in on)] = {
                    l: s.labels.get(l, "") for l in gl
                }
        inner_keys = set()
        for s in samples:
            if s.name != metric:
                continue
            extra = member.get(tuple(s.labels.get(l, "") for l in on))
            if extra is None:
                continue
            joined = {**s.labels, **extra}
            inner_keys.add(tuple(joined.get(l, "") for l in inner))
        groups: dict[tuple, int] = {}
        for ik in inner_keys:
            labels = dict(zip(inner, ik))
            key = tuple(labels.get(l, "") for l in outer)
            groups[key] = groups.get(key, 0) + 1
        return outer, {k: float(v) for k, v in groups.items()}
    m = _RATIO_RE.match(expr)
    if m:
        a_name, b_name = m.groups()
        if a_name not in recorded or b_name not in recorded:
            raise AssertionError(
                f"ratio rule references unrecorded series: {expr}"
            )
        (by_a, a), (by_b, b) = recorded[a_name], recorded[b_name]
        assert by_a == by_b, f"ratio operands disagree on labels: {expr}"
        return by_a, {
            k: 100.0 * v / b[k] for k, v in a.items() if b.get(k)
        }
    raise AssertionError(f"rule expression outside the supported subset: {expr}")


def build_hosts():
    """Heterogeneous 2-slice fleet: per-host duty/HBM variation, multi-host
    pods, an unattributed chip, live ICI/DCN rates (needs two polls), and
    multi-slice membership (both slices share one group) so the multislice
    join rules evaluate against real host_info series."""
    texts = []
    for slice_name, accel, workers in (
        ("slice-a", "v5p-32", 4),
        ("slice-b", "v5e-16", 2),
    ):
        for w in range(workers):
            backend = FakeBackend(
                chips=4,
                script=FakeChipScript(
                    hbm_total_bytes=96 * GIB,
                    hbm_used_bytes=(w + 1) * 3 * GIB,
                    duty_cycle_percent=20.0 * (w + 1),
                    ici_link_count=3,
                    ici_bytes_per_step=1_000_000.0 * (w + 1),
                    dcn_link_count=1,
                    dcn_bytes_per_step=250_000.0 * (w + 1),
                ),
            )
            allocs = [
                simple_allocation(f"{slice_name}-train", ["0", "1"], namespace="ml")
            ]
            if w % 2 == 0:
                allocs.append(
                    simple_allocation(f"{slice_name}-eval", ["2"], namespace="research")
                )
            # chip 3 stays unattributed (pod="") on every host.
            store = SnapshotStore()
            fake_now = [0.0]
            c = Collector(
                backend,
                FakeAttribution(allocs),
                store,
                topology=HostTopology(
                    accelerator=accel, slice_name=slice_name,
                    host=f"{slice_name}-host-{w}", worker_id=str(w),
                    multislice_group="ms-rules-group", num_slices="2",
                ),
                clock=lambda: fake_now[0],
            )
            c.poll_once()
            fake_now[0] += 2.0
            c.poll_once()  # second poll: ICI bandwidth series exist
            texts.append(store.current().encode().decode())
    # One host of a THIRD slice whose device backend is dead: it publishes
    # tpu_host_info (live exporter, group member) but zero chip series.
    # Both the aggregator and the recording rule must treat slice-dead as
    # NOT reporting — counting it would hide exactly the whole-slice
    # telemetry loss the slices-missing alert exists for (code-review r5).
    dead_backend = FakeBackend(chips=4)
    dead_backend.fail_next(10)
    store = SnapshotStore()
    Collector(
        dead_backend, FakeAttribution(), store,
        topology=HostTopology(
            accelerator="v5p-32", slice_name="slice-dead",
            host="slice-dead-host-0", worker_id="0",
            multislice_group="ms-rules-group", num_slices="2",
        ),
    ).poll_once()
    text = store.current().encode().decode()
    assert "tpu_host_info{" in text and "tpu_chip_info{" not in text
    texts.append(text)
    return texts


class TestRecordingRulesEquivalence:
    @pytest.fixture(scope="class")
    def evaluated(self):
        texts = build_hosts()

        # Path 1: the aggregator over the host expositions.
        agg_store = SnapshotStore()
        targets = tuple(f"host://{i}" for i in range(len(texts)))
        by_target = dict(zip(targets, texts))
        agg = SliceAggregator(
            targets, agg_store, fetch=lambda t, timeout_s: by_target[t]
        )
        agg.poll_once()
        agg.close()

        # Path 2: the recording rules over the identical samples.
        samples = [s for text in texts for s in parse_exposition(text)]
        doc = yaml.safe_load(RULES.read_text())
        recorded: dict = {}
        for group in doc["groups"]:
            for rule in group.get("rules", []):
                if "record" in rule:
                    recorded[rule["record"]] = eval_rule(
                        rule["expr"], samples, recorded
                    )
        return agg_store.current(), recorded

    def test_every_recording_rule_has_a_mapping(self, evaluated):
        _, recorded = evaluated
        assert set(recorded) == set(RECORD_TO_AGG), (
            "recording rules and the equivalence map drifted: "
            f"{set(recorded) ^ set(RECORD_TO_AGG)}"
        )

    @pytest.mark.parametrize("record", sorted(RECORD_TO_AGG))
    def test_rule_equals_aggregator(self, evaluated, record):
        snap, recorded = evaluated
        by, values = recorded[record]
        agg_name = RECORD_TO_AGG[record]
        assert values, f"{record} evaluated to no series"
        for key, rule_value in values.items():
            labels = dict(zip(by, key))
            if agg_name.startswith("tpu_slice_"):
                # The aggregator's slice rollups carry the accelerator-
                # family key (SLICE_LABELS); the PromQL rules aggregate
                # tpu_* node series only, so their output is implicitly
                # the TPU family (a mixed fleet's gpu_* families need the
                # parallel rules sketched in prometheus-rules.yaml).
                labels["family"] = "tpu"
            if "pod" in by and labels.get("pod", "") == "":
                # The aggregator (like the exporter) never mints a
                # workload series for unattributed chips; the PromQL sum
                # can't produce one either because tpu_pod_* series only
                # exist for real pods. Seeing one here means the input
                # changed shape — fail loudly.
                raise AssertionError("workload input grew a pod=\"\" series")
            agg_value = snap.value(agg_name, labels)
            assert agg_value is not None, (
                f"{agg_name}{labels} missing from aggregator output"
            )
            assert agg_value == pytest.approx(rule_value, rel=1e-9), (
                f"{record}{labels}: rule={rule_value} aggregator={agg_value}"
            )

    def test_divergent_rule_edit_fails(self):
        """Meta-check: a rule silently changed to a different aggregation
        must produce a different result (the equality test would catch it)."""
        texts = build_hosts()
        samples = [s for text in texts for s in parse_exposition(text)]
        by, good = eval_rule(
            "sum by (slice_name, accelerator) (tpu_hbm_used_bytes)",
            samples, {},
        )
        _, bad = eval_rule(
            "avg by (slice_name, accelerator) (tpu_hbm_used_bytes)",
            samples, {},
        )
        assert good != bad


# --------------------------------------------- alert importer round-trip


class TestAlertImportEquivalence:
    """The OTHER half of the rules file: its alerting rules must import
    into the native grammar (``python -m tpu_pod_exporter.alerting
    --import``) losslessly. Checked three ways: every YAML alert arrives
    with its for/labels/annotations intact, the canonical renderer is a
    parse fixpoint, and — the part that catches translation bugs the
    field checks can't — every imported rule EVALUATES identically to
    its render→re-parse twin on a recorded fixture round, non-vacuously
    (at least one alert must actually match instances on the fixture)."""

    @pytest.fixture(scope="class")
    def imported(self):
        from tpu_pod_exporter.alerting import (
            import_prometheus_rules, parse_alert_rules)
        text = import_prometheus_rules(RULES.read_text())
        return parse_alert_rules(text), text

    @pytest.fixture(scope="class")
    def yaml_alerts(self):
        doc = yaml.safe_load(RULES.read_text())
        return {
            rule["alert"]: rule
            for group in doc["groups"]
            for rule in group.get("rules", [])
            if "alert" in rule
        }

    def test_every_yaml_alert_imports_with_its_clauses(
            self, imported, yaml_alerts):
        from tpu_pod_exporter.alerting import parse_duration
        rules, _ = imported
        by_name = {r.name: r for r in rules}
        assert set(by_name) == set(yaml_alerts), (
            "importer dropped or invented alerts: "
            f"{set(by_name) ^ set(yaml_alerts)}"
        )
        for name, yrule in yaml_alerts.items():
            r = by_name[name]
            want_for = (parse_duration(str(yrule["for"]))
                        if yrule.get("for") else 0.0)
            assert r.for_s == want_for, name
            assert dict(r.labels) == {
                k: str(v) for k, v in (yrule.get("labels") or {}).items()
            }, name
            assert dict(r.annotations) == {
                k: str(v)
                for k, v in (yrule.get("annotations") or {}).items()
            }, name

    def test_render_is_a_parse_fixpoint(self, imported):
        from tpu_pod_exporter.alerting import parse_alert_rules, render_rules
        rules, _ = imported
        rendered = render_rules(rules)
        assert render_rules(parse_alert_rules(rendered)) == rendered

    def test_suppression_injected_exactly_where_declared(self, imported):
        from tpu_pod_exporter.alerting import DEFAULT_SUPPRESSIONS
        rules, _ = imported
        for r in rules:
            if r.name in DEFAULT_SUPPRESSIONS:
                assert r.suppress is not None, r.name
                assert r.suppress_text == DEFAULT_SUPPRESSIONS[r.name]
            else:
                assert r.suppress is None, (
                    f"{r.name} grew a suppression the table never declared"
                )

    def test_imported_rules_evaluate_like_their_roundtrip_twins(
            self, imported):
        from tpu_pod_exporter.alerting import (
            _SPEC_BY_NAME, AlertEvaluator, EvalContext, parse_alert_rules,
            render_rules)
        from tpu_pod_exporter.metrics.registry import SnapshotBuilder

        rules, _ = imported
        twins = parse_alert_rules(render_rules(rules))

        # One recorded fixture round: the same heterogeneous fleet the
        # recording-rule equivalence runs on (its dead-backend host makes
        # tpu_exporter_up == 0 style alerts match non-vacuously).
        b = SnapshotBuilder()
        for text in build_hosts():
            for s in parse_exposition(text):
                spec = _SPEC_BY_NAME.get(s.name)
                if spec is None:
                    continue
                b.add(spec, s.value,
                      tuple(s.labels.get(l, "") for l in spec.label_names))
        snap = b.build()

        ev = AlertEvaluator(rules)
        vectors = ev._ingest(snap, 0.0)
        ctx = EvalContext(0.0, lambda name: vectors.get(name, {}),
                          lambda name, w: {})
        matched = 0
        for r, twin in zip(rules, twins):
            assert r.name == twin.name
            got = r.expr.evaluate(ctx)
            again = twin.expr.evaluate(ctx)
            assert got == again, (
                f"{r.name}: imported and round-tripped expressions "
                f"diverge on the fixture round"
            )
            if isinstance(got, dict) and got:
                matched += 1
        assert matched >= 1, (
            "every alert evaluated empty — the fixture exercises nothing "
            "and the equivalence above is vacuous"
        )
