"""Chaos harness tests (chaos.py): spec parsing, deterministic schedules,
garbage-value robustness, and the headline wedge scenario — a hung device
backend is abandoned at the phase deadline, the breaker opens, the backend
is reconnected, and the exporter converges back to up=1, all while /metrics
keeps answering from the stale snapshot."""

import json
import threading
import time
import urllib.request

import pytest

from tpu_pod_exporter.app import ExporterApp
from tpu_pod_exporter.backend.fake import FakeBackend
from tpu_pod_exporter.chaos import (
    ChaosError,
    ChaosRule,
    ChaosWrapper,
    apply_chaos,
    parse_chaos_spec,
)
from tpu_pod_exporter.config import ExporterConfig


class TestSpecParsing:
    def test_issue_example_spec(self):
        rules = parse_chaos_spec(
            "hang:device:0.01,err:attribution:0.05,slow:procscan:500ms"
        )
        assert [(r.kind, r.source) for r in rules] == [
            ("hang", "device"), ("err", "attribution"), ("slow", "procscan"),
        ]
        assert rules[0].prob == 0.01
        assert rules[0].effective_duration_s == 3600.0  # hang default
        assert rules[1].prob == 0.05
        assert rules[2].prob == 1.0                     # duration-only rule
        assert rules[2].effective_duration_s == 0.5

    def test_duration_count_and_prob_tokens_in_any_order(self):
        (r,) = parse_chaos_spec("hang:device:x3:10s:0.5")
        assert (r.prob, r.duration_s, r.max_count) == (0.5, 10.0, 3)
        (r,) = parse_chaos_spec("slow:procscan:0.25:250ms")
        assert (r.prob, r.duration_s) == (0.25, 0.25)

    @pytest.mark.parametrize("bad", [
        "explode:device:0.1",      # unknown kind
        "hang:gpu:0.1",            # unknown source
        "hang",                    # no source
        "hang:device:2",           # bare number > 1: ambiguous
        "hang:device:10sec",       # bad unit
        "hang:device:x3.5",        # non-integer count
        "",                        # no rules
        " , ,",                    # nothing but separators
    ])
    def test_malformed_specs_fail_loudly(self, bad):
        with pytest.raises(ValueError):
            parse_chaos_spec(bad)


class TestDeterminism:
    def _schedule(self, seed, calls=200):
        rules = [ChaosRule(kind="err", source="device", prob=0.3)]
        w = ChaosWrapper(FakeBackend(chips=1), "device", rules, seed=seed)
        for _ in range(calls):
            try:
                w.sample()
            except ChaosError:
                pass
        return list(w.injected)

    def test_same_seed_same_schedule(self):
        assert self._schedule(seed=7) == self._schedule(seed=7)

    def test_different_seed_different_schedule(self):
        assert self._schedule(seed=7) != self._schedule(seed=8)

    def test_count_cap_and_exhaustion_keeps_later_rules_stable(self):
        # Every rule consumes one draw per call regardless of what earlier
        # rules did, so a later rule's own hit schedule is a stable
        # function of (seed, call index) — capping rule 1 can only hand
        # rule 2 MORE of its scheduled hits, never move them.
        def run(cap):
            rules = [
                ChaosRule(kind="err", source="device", prob=0.5,
                          max_count=cap),
                ChaosRule(kind="slow", source="device", prob=0.2,
                          duration_s=0.0),
            ]
            w = ChaosWrapper(FakeBackend(chips=1), "device", rules, seed=3,
                             sleep=lambda s: None)
            for _ in range(100):
                try:
                    w.sample()
                except ChaosError:
                    pass
            return w

        capped, uncapped = run(2), run(None)
        assert capped.rules[0].fired == 2
        slow_hits = lambda w: {i for i, k in w.injected if k == "slow"}  # noqa: E731
        assert slow_hits(capped) >= slow_hits(uncapped)
        assert slow_hits(uncapped)  # the invariant actually got exercised

    def test_garbage_payloads_do_not_shift_the_schedule(self):
        # Payload contents draw from a dedicated rng; the schedule stream
        # stays one-draw-per-rule-per-call, so capping (or effectively
        # removing) the garbage rule never moves a later rule's hits.
        def run(cap):
            rules = [
                ChaosRule(kind="garbage", source="device", prob=0.5,
                          max_count=cap),
                ChaosRule(kind="err", source="device", prob=0.2),
            ]
            w = ChaosWrapper(FakeBackend(chips=1), "device", rules, seed=11)
            for _ in range(100):
                try:
                    w.sample()
                except ChaosError:
                    pass
            return {i for i, k in w.injected if k == "err"}

        assert run(cap=2) >= run(cap=None)
        assert run(cap=None)  # err actually fired in the uncapped run

    def test_slow_injection_sleeps_then_proceeds(self):
        slept = []
        rules = [ChaosRule(kind="slow", source="device", prob=1.0,
                           duration_s=0.123)]
        w = ChaosWrapper(FakeBackend(chips=1), "device", rules, seed=0,
                         sleep=slept.append)
        sample = w.sample()
        assert slept == [0.123]
        assert len(sample.chips) == 1  # the real call still ran


class TestGarbage:
    def test_garbage_device_sample_does_not_crash_collector(self):
        from tpu_pod_exporter.attribution.fake import FakeAttribution
        from tpu_pod_exporter.collector import Collector
        from tpu_pod_exporter.metrics import SnapshotStore

        rules = [ChaosRule(kind="garbage", source="device", prob=1.0)]
        backend = ChaosWrapper(FakeBackend(chips=2), "device", rules, seed=1)
        store = SnapshotStore()
        c = Collector(backend, FakeAttribution(), store)
        stats = c.poll_once()
        # A garbage sample is a *successful* read of hostile values: the
        # chip publishes, partial errors are counted, and the exposition
        # still renders (NaN duty, negative HBM, regressed counter).
        assert "device_partial" in stats.errors
        text = store.current().encode().decode()
        assert "tpu_chip_info" in text
        assert 'chip_id="999"' in text
        c.close()

    def test_garbage_attribution_is_label_hostile_but_contained(self):
        from tpu_pod_exporter.attribution.fake import FakeAttribution
        from tpu_pod_exporter.collector import Collector
        from tpu_pod_exporter.metrics import SnapshotStore

        rules = [ChaosRule(kind="garbage", source="attribution", prob=1.0)]
        attr = ChaosWrapper(FakeAttribution(), "attribution", rules, seed=1)
        store = SnapshotStore()
        c = Collector(FakeBackend(chips=1), attr, store)
        stats = c.poll_once()
        assert stats.ok
        # The exposition must still parse: hostile pod names are escaped.
        from prometheus_client.parser import text_string_to_metric_families

        list(text_string_to_metric_families(store.current().encode().decode()))
        c.close()


class TestApplyChaos:
    def test_only_matching_sources_wrapped(self):
        from tpu_pod_exporter.attribution.fake import FakeAttribution

        b, a, s, wrappers = apply_chaos(
            "err:device:0.5", 1, FakeBackend(chips=1), FakeAttribution(), None
        )
        assert isinstance(b, ChaosWrapper)
        assert isinstance(a, FakeAttribution)  # untouched
        assert s is None
        assert set(wrappers) == {"device"}

    def test_wrapper_passes_through_introspection(self):
        b, _, _, _ = apply_chaos(
            "err:device:0", 1, FakeBackend(chips=1), None, None
        )
        b.fail_next(1)  # FakeBackend API reachable through the wrapper
        assert b.name.startswith("chaos(")


def _metric_value(body: str, prefix: str) -> float | None:
    for line in body.splitlines():
        if line.startswith(prefix):
            try:
                return float(line.rsplit(" ", 1)[1])
            except ValueError:
                return None
    return None


def _scrape(port: int, path: str = "/metrics") -> str:
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=5
    ) as r:
        return r.read().decode()


class TestWedgedDeviceBackend:
    """Acceptance scenario (ISSUE 2): a device-backend hang must be survived
    visibly — up drops within one phase deadline, scrapes stay fast on the
    stale snapshot, the breaker opens, the backend is reconnected, and up
    converges back to 1. Scaled-down timings; deterministic x3 hang count."""

    DEADLINE_S = 0.25

    @pytest.fixture
    def wedged_app(self):
        cfg = ExporterConfig(
            port=0, host="127.0.0.1", interval_s=0.05,
            backend="fake", fake_chips=2, attribution="none",
            phase_deadline_s=self.DEADLINE_S,
            breaker_failures=2, breaker_backoff_s=0.1,
            breaker_backoff_max_s=0.3,
            # First three device reads hang (each worker unblocks after 3 s
            # and exits); everything after is healthy.
            chaos_spec="hang:device:1:3s:x3", chaos_seed=42,
            history_retention_s=0.0,
        )
        app = ExporterApp(cfg)
        app.start()
        yield app
        app.stop()

    def test_wedge_abandon_reconnect_recover(self, wedged_app):
        app = wedged_app
        # (1) up drops: the very first poll hit the hang and was abandoned
        # at the deadline, so the serving snapshot already reports up=0.
        body = _scrape(app.port)
        assert _metric_value(body, "tpu_exporter_up ") == 0.0

        # (2) scrapes stay fast during the wedge (stale snapshot served):
        # well under the phase deadline, let alone the hang duration.
        t0 = time.monotonic()
        _scrape(app.port)
        assert time.monotonic() - t0 < self.DEADLINE_S

        # (3) breaker opens and the backend is reconnected; up returns to 1.
        deadline = time.monotonic() + 15.0
        saw_open = False
        while time.monotonic() < deadline:
            body = _scrape(app.port)
            state = _metric_value(
                body, 'tpu_exporter_source_breaker_state{source="device"}'
            )
            saw_open = saw_open or state in (1.0, 2.0)
            if (
                saw_open
                and _metric_value(body, "tpu_exporter_up ") == 1.0
                and state == 0.0
            ):
                break
            time.sleep(0.05)
        else:
            raise AssertionError(
                f"never recovered (saw_open={saw_open}): "
                + app.supervisors["device"].stats().__repr__()
            )

        # (4) the mechanism is visible in the exposition: calls were
        # abandoned, the breaker cycled, the backend was reconnected.
        assert _metric_value(
            body, 'tpu_exporter_source_calls_abandoned_total{source="device"}'
        ) == 3.0
        assert _metric_value(
            body, 'tpu_exporter_source_reconnects_total{source="device"}'
        ) >= 1.0
        assert _metric_value(
            body,
            'tpu_exporter_source_breaker_transitions_total'
            '{source="device",state="closed"}',
        ) >= 1.0
        # The wedge never killed the loop.
        assert _metric_value(body, "tpu_exporter_polls_total ") > 0

        # (5) skip-vs-error split: quarantine skips were plentiful but only
        # the 3 real failures (deadline abandonments) count as poll errors —
        # the TpuExporterPollErrors alert must not fire on designed backoff.
        assert _metric_value(
            body, 'tpu_exporter_poll_errors_total{source="device_read"}'
        ) == 3.0
        assert _metric_value(
            body, 'tpu_exporter_source_calls_skipped_total{source="device"}'
        ) >= 1.0

    def test_chaos_state_visible_in_debug_vars(self, wedged_app):
        app = wedged_app
        dv = json.loads(_scrape(app.port, "/debug/vars"))
        assert "device" in dv["supervisors"]
        assert dv["chaos"]["device"]["calls"] >= 1


class TestReadyzDegradedDetail:
    def test_persistently_wedged_source_reported_degraded(self):
        cfg = ExporterConfig(
            port=0, host="127.0.0.1", interval_s=0.02,
            backend="fake", fake_chips=1, attribution="none",
            phase_deadline_s=2.0,
            breaker_failures=1, breaker_backoff_s=0.02,
            breaker_backoff_max_s=0.05,
            history_retention_s=0.0,
        )
        app = ExporterApp(cfg)
        try:
            app.backend.fail_next(10_000)
            app.start()
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                if app.supervisors["device"].stats()["reopens"] >= 3:
                    break
                time.sleep(0.02)
            else:
                raise AssertionError("breaker never re-opened 3 times")
            with urllib.request.urlopen(
                f"http://127.0.0.1:{app.port}/readyz", timeout=5
            ) as r:
                body = json.loads(r.read())
            assert r.status == 200  # degraded is detail, not unreadiness
            assert body["ready"] is True
            sources = [d["source"] for d in body["degraded_sources"]]
            assert "device" in sources
        finally:
            app.stop()


@pytest.mark.slow
class TestChaosSoak:
    def test_converges_after_every_wedge(self):
        """Repeated injected wedges; the exporter must converge back to
        up=1 after each one."""
        cfg = ExporterConfig(
            port=0, host="127.0.0.1", interval_s=0.02,
            backend="fake", fake_chips=2, attribution="none",
            phase_deadline_s=0.15,
            breaker_failures=2, breaker_backoff_s=0.05,
            breaker_backoff_max_s=0.2,
            history_retention_s=0.0,
        )
        app = ExporterApp(cfg)
        app.start()
        try:
            wrapper = None
            for burst in range(3):
                # Inject a fresh 3-call hang burst directly into the chaos
                # layer... which is absent (no --chaos-spec), so wedge via
                # a blocking sample wrapper instead.
                release = threading.Event()
                inner = app.backend.sample
                remaining = [3]

                def wedged(inner=inner, release=release, remaining=remaining):
                    if remaining[0] > 0:
                        remaining[0] -= 1
                        release.wait(3.0)
                    return inner()

                app.backend.sample = wedged
                deadline = time.monotonic() + 10.0
                while time.monotonic() < deadline:
                    if _metric_value(_scrape(app.port),
                                     "tpu_exporter_up ") == 0.0:
                        break
                    time.sleep(0.02)
                else:
                    raise AssertionError(f"burst {burst}: up never dropped")
                release.set()
                app.backend.sample = inner
                deadline = time.monotonic() + 10.0
                while time.monotonic() < deadline:
                    if _metric_value(_scrape(app.port),
                                     "tpu_exporter_up ") == 1.0:
                        break
                    time.sleep(0.02)
                else:
                    raise AssertionError(f"burst {burst}: never recovered")
        finally:
            app.stop()
