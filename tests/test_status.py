"""Status CLI tests — drive main() with fake-backend flags, capture stdout."""

import pytest

from tpu_pod_exporter import status


@pytest.fixture
def run_status(capsys, monkeypatch):
    def run(argv):
        # isolate from the host's TPU env
        for var in ("TPU_ACCELERATOR_TYPE", "TPU_WORKER_ID", "TPU_SLICE_NAME"):
            monkeypatch.delenv(var, raising=False)
        rc = status.main(argv)
        out = capsys.readouterr()
        return rc, out.out, out.err

    return run


class TestStatusCli:
    def test_zero_devices(self, run_status):
        rc, out, _ = run_status(["--backend", "fake", "--fake-chips", "0",
                                 "--attribution", "none"])
        assert rc == 0
        assert "no TPU chips found" in out

    def test_chip_table(self, run_status):
        rc, out, _ = run_status(["--backend", "fake", "--fake-chips", "4",
                                 "--attribution", "none", "--accelerator", "v4-8"])
        assert rc == 0
        assert "accelerator: v4-8" in out
        assert "(4 chips / 1 hosts slice-wide)" in out
        for chip in range(4):
            assert f"/dev/accel{chip}" in out

    def test_recorded_trace(self, run_status, tmp_path):
        from tpu_pod_exporter.backend.fake import FakeBackend
        from tpu_pod_exporter.backend.recorded import RecordingBackend

        path = str(tmp_path / "t.jsonl")
        rec = RecordingBackend(FakeBackend(chips=2), path)
        rec.sample()
        rec.close()
        rc, out, _ = run_status(["--backend", "recorded", "--recording-path", path,
                                 "--attribution", "none"])
        assert rc == 0
        assert "chip" in out and "/dev/accel1" in out

    def test_fmt_bytes(self):
        assert status.fmt_bytes(0) == "0B"
        assert status.fmt_bytes(1024) == "1.0KiB"
        assert status.fmt_bytes(32 * 1024**3) == "32.0GiB"

    def test_holder_column_with_process_metrics(self, run_status, tmp_path):
        import os

        d = tmp_path / "77" / "fd"
        d.mkdir(parents=True)
        os.symlink("/dev/accel1", d / "3")
        (tmp_path / "77" / "comm").write_text("jax_worker\n")
        (tmp_path / "77" / "cgroup").write_text("0::/user.slice\n")
        rc, out, _ = run_status([
            "--backend", "fake", "--fake-chips", "2", "--attribution", "none",
            "--process-metrics", "--proc-root", str(tmp_path),
        ])
        assert rc == 0
        assert "holder" in out
        assert "77/jax_worker" in out

    def test_watch_flag_parses_and_passes_rest(self, run_status, monkeypatch):
        # One render then interrupt out of the sleep.
        import time as time_mod

        def boom(_):
            raise KeyboardInterrupt

        monkeypatch.setattr(time_mod, "sleep", boom)
        rc, out, _ = run_status([
            "--watch", "5", "--backend", "fake", "--fake-chips", "1",
            "--attribution", "none",
        ])
        assert rc == 0
        assert "/dev/accel0" in out

    def test_json_output(self, run_status, tmp_path):
        import json
        import os

        d = tmp_path / "42" / "fd"
        d.mkdir(parents=True)
        os.symlink("/dev/accel0", d / "3")
        (tmp_path / "42" / "comm").write_text("w\n")
        (tmp_path / "42" / "cgroup").write_text("0::/x\n")
        rc, out, _ = run_status([
            "--backend", "fake", "--fake-chips", "2", "--attribution", "none",
            "--accelerator", "v4-8", "--json",
            "--process-metrics", "--proc-root", str(tmp_path),
        ])
        assert rc == 0
        doc = json.loads(out)
        assert doc["accelerator"] == "v4-8"
        assert len(doc["chips"]) == 2
        chip0 = doc["chips"][0]
        assert chip0["device_path"] == "/dev/accel0"
        assert chip0["holders"] == [{"pid": 42, "comm": "w", "pod_uid": ""}]
        assert isinstance(chip0["ici"], dict)  # per-link counters (r4)
        assert doc["partial_errors"] == []
        assert doc["pods"] == []

    def test_json_zero_chips(self, run_status):
        import json

        rc, out, _ = run_status([
            "--backend", "fake", "--fake-chips", "0", "--attribution", "none",
            "--json",
        ])
        assert rc == 0
        assert json.loads(out)["chips"] == []

    def test_table_pod_rollup_counts_each_chip_once(self, run_status, tmp_path):
        # Regression: the per-pod table once double-counted chips/HBM when
        # the rollup block existed on both sides of the --json split.
        import json

        ckpt = tmp_path / "ckpt.json"
        ckpt.write_text(json.dumps({
            "Data": {
                "PodDeviceEntries": [
                    {
                        "PodUID": "u-1",
                        "ContainerName": "main",
                        "ResourceName": "google.com/tpu",
                        "DeviceIDs": ["0", "1"],
                    }
                ]
            }
        }))
        rc, out, _ = run_status([
            "--backend", "fake", "--fake-chips", "2",
            "--attribution", "checkpoint", "--checkpoint-path", str(ckpt),
        ])
        assert rc == 0
        pod_line = [l for l in out.splitlines() if "uid:u-1" in l and "GiB" not in l]
        # pods table row: "<ns>/<pod>  <chips>  <hbm>"
        assert any(" 2 " in l or l.rstrip().endswith("2  0B") or "  2  " in l
                   for l in pod_line), out
