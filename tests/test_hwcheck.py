"""The hardware-validation harness, orchestrated against the fake backend
(VERDICT r1 #4/#5: the instrument ships and is proven hardware-free; real
runs produce the round artifact when an accelerator runtime is reachable)."""

import json

from tpu_pod_exporter.hwcheck import main, run_check


class TestRunCheck:
    def test_fake_backend_full_pass(self, tmp_path):
        trace = tmp_path / "trace.jsonl"
        report = run_check(
            backend="fake", idle_s=0.6, load_s=0.8, record_to=str(trace),
            libtpu_addr=f"unix://{tmp_path}/absent.sock",
        )
        assert report["ok"] is True
        assert report["checks"]["hbm_rises_under_load"] is True
        assert report["checks"]["hbm_falls_after_release"] is True
        assert report["checks"]["duty_cycle_responds"] is True
        assert report["phases"]["load"]["hbm_used_bytes"] > (
            report["phases"]["idle"]["hbm_used_bytes"]
        )
        # the recorded trace captured all three phases end-to-end
        lines = [json.loads(l) for l in trace.read_text().splitlines()]
        assert len(lines) >= 3
        # unreachable libtpu service is documented, not fatal
        assert report["libtpu"]["reachable"] is False

    def test_fake_backend_failure_detected(self, tmp_path):
        # A stimulus that does nothing must fail the rise/fall checks —
        # the harness can't report success for an exporter that ignores load.
        class Inert:
            def start(self):
                pass

            def stop(self):
                pass

        report = run_check(
            backend="fake", idle_s=0.4, load_s=0.4,
            libtpu_addr=f"unix://{tmp_path}/absent.sock",
            _stimulus=Inert(),
        )
        assert report["ok"] is False
        assert report["checks"]["hbm_rises_under_load"] is False

    def test_cli_writes_artifact(self, tmp_path, capsys):
        out = tmp_path / "HWCHECK.json"
        rc = main([
            "--backend", "fake", "--idle-s", "0.4", "--load-s", "0.5",
            "--libtpu-addr", f"unix://{tmp_path}/absent.sock",
            "--out", str(out),
        ])
        assert rc == 0
        doc = json.loads(out.read_text())
        assert doc["ok"] is True
        assert json.loads(capsys.readouterr().out) == doc
