"""Unit tests for the source supervision layer (supervisor.py): breaker
state machine, backoff+jitter, deadline abandonment, fenced workers,
reconnect-on-probe, and the abandoned-worker cap."""

import logging
import random
import threading
import time

import pytest

from tpu_pod_exporter.supervisor import (
    CLOSED,
    HALF_OPEN,
    OPEN,
    PROBATION_SUCCESSES,
    STATE_VALUES,
    CircuitBreaker,
    SourceSkipped,
    SourceSupervisor,
    SourceTimeout,
)


class FixedRng:
    """random.Random stand-in whose random() is constant (jitter factor 1)."""

    def __init__(self, value: float = 0.5) -> None:
        self.value = value

    def random(self) -> float:
        return self.value


def make_breaker(clock, **kw):
    kw.setdefault("failure_threshold", 3)
    kw.setdefault("backoff_base_s", 1.0)
    kw.setdefault("backoff_max_s", 8.0)
    kw.setdefault("rng", FixedRng())
    return CircuitBreaker(clock=lambda: clock[0], **kw)


class TestCircuitBreaker:
    def test_stays_closed_below_threshold(self):
        clock = [0.0]
        br = make_breaker(clock)
        for _ in range(5):
            br.record_failure()
            br.record_failure()
            br.record_success()  # non-consecutive failures never open
        assert br.state == CLOSED
        assert br.transitions[OPEN] == 0

    def test_opens_on_consecutive_failures_and_probes_after_backoff(self):
        clock = [0.0]
        br = make_breaker(clock)
        for _ in range(3):
            assert br.decide() == "call"
            br.record_failure()
        assert br.state == OPEN
        assert br.decide() == "skip"          # backoff pending
        clock[0] = 0.99
        assert br.decide() == "skip"
        clock[0] = 1.0                        # base backoff, jitter factor 1
        assert br.decide() == "probe"
        assert br.state == HALF_OPEN
        assert br.decide() == "skip"          # single-probe rule
        br.record_success()
        assert br.state == CLOSED
        assert br.transitions == {CLOSED: 1, OPEN: 1, HALF_OPEN: 1}

    def test_backoff_doubles_and_caps(self):
        clock = [0.0]
        br = make_breaker(clock)  # base 1, max 8
        waits = []
        for _ in range(6):
            for _ in range(3 if br.state == CLOSED else 1):
                if br.state == OPEN:
                    clock[0] += br.seconds_until_probe
                    assert br.decide() == "probe"
                br.record_failure()
            waits.append(br.seconds_until_probe)
        assert waits == [1.0, 2.0, 4.0, 8.0, 8.0, 8.0]

    def test_sustained_success_resets_backoff(self):
        clock = [0.0]
        br = make_breaker(clock)
        for _ in range(3):
            br.record_failure()
        clock[0] += br.seconds_until_probe
        assert br.decide() == "probe"
        br.record_success()
        # The probe success alone is probation, not amnesty (see the
        # flapping-partition hardening): the reopen count survives until
        # PROBATION_SUCCESSES follow-up successes land.
        assert br.state == CLOSED
        assert br.reopens == 1
        for _ in range(PROBATION_SUCCESSES):
            br.record_success()
        assert br.reopens == 0
        for _ in range(3):
            br.record_failure()
        # A fresh incident starts over at the base backoff, not 2x.
        assert br.seconds_until_probe == pytest.approx(1.0)

    def test_probe_success_into_flapping_cut_keeps_backoff_memory(self):
        """The scenario-drill hardening: a half-open probe that succeeds
        into a flapping partition (immediately followed by failures) must
        resume from the retained backoff, not restart the incident at the
        base — a flapping cut settles at the ceiling instead of probe-
        storming at base cadence forever."""
        clock = [0.0]
        br = make_breaker(clock)  # base 1, max 8
        waits = []
        for _flap in range(5):
            # Fail to (re-)open: 3 consecutive from closed, 1 from probe.
            while br.state != OPEN:
                if br.state == HALF_OPEN:
                    br.record_failure()
                    continue
                br.record_failure()
            waits.append(br.seconds_until_probe)
            clock[0] += br.seconds_until_probe
            assert br.decide() == "probe"
            br.record_success()  # the flap's open window lets one through
            assert br.state == CLOSED
        # Monotone non-decreasing toward the ceiling: no reset-to-base.
        assert waits == sorted(waits)
        assert waits[-1] == pytest.approx(8.0)
        assert waits[0] == pytest.approx(1.0)
        assert br.reopens == 5  # the whole flap incident is one incident

    def test_jitter_bounds(self):
        for draw in (0.0, 0.25, 0.75, 1.0 - 1e-9):
            clock = [0.0]
            br = make_breaker(clock, rng=FixedRng(draw), jitter=0.2)
            for _ in range(3):
                br.record_failure()
            assert 0.8 <= br.seconds_until_probe <= 1.2

    def test_jitter_uses_injectable_rng_deterministically(self):
        def schedule(seed):
            clock = [0.0]
            br = make_breaker(clock, rng=random.Random(seed))
            out = []
            for _ in range(4):
                for _ in range(3 if br.state == CLOSED else 1):
                    if br.state == OPEN:
                        clock[0] += br.seconds_until_probe
                        br.decide()
                    br.record_failure()
                out.append(round(br.seconds_until_probe, 6))
            return out

        assert schedule(7) == schedule(7)
        assert schedule(7) != schedule(8)

    def test_state_values_cover_all_states(self):
        assert set(STATE_VALUES) == {CLOSED, OPEN, HALF_OPEN}

    def test_validation(self):
        with pytest.raises(ValueError):
            CircuitBreaker(failure_threshold=0)
        with pytest.raises(ValueError):
            CircuitBreaker(backoff_base_s=5.0, backoff_max_s=1.0)


class TestSourceSupervisor:
    def test_passthrough_result_and_exceptions(self):
        sup = SourceSupervisor("s", lambda: 42, deadline_s=1.0)
        try:
            assert sup.call() == 42
            boom = RuntimeError("boom")

            def bad():
                raise boom

            sup2 = SourceSupervisor("s2", bad, deadline_s=1.0)
            with pytest.raises(RuntimeError) as ei:
                sup2.call()
            assert ei.value is boom  # the ORIGINAL exception, relayed
            sup2.shutdown()
        finally:
            sup.shutdown()

    def test_deadline_abandons_worker_and_next_call_succeeds(self):
        release = threading.Event()
        state = {"blocked": 0}

        def fn():
            if state["blocked"] == 0:
                state["blocked"] = 1
                release.wait(10.0)
                return "late"
            return "ok"

        sup = SourceSupervisor(
            "wedge", fn, deadline_s=0.1,
            breaker=CircuitBreaker(failure_threshold=99),
        )
        try:
            t0 = time.monotonic()
            with pytest.raises(SourceTimeout):
                sup.call()
            # The abandon returned at the deadline, NOT after the block.
            assert time.monotonic() - t0 < 5.0
            assert sup.abandoned == 1
            assert sup.stats()["abandoned_alive"] == 1
            # A fresh worker serves the next call while the old one is
            # still blocked.
            assert sup.call() == "ok"
            # Release the wedge: the fenced worker exits on its own.
            release.set()
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                sup._prune_fenced()
                if sup.stats()["abandoned_alive"] == 0:
                    break
                time.sleep(0.01)
            assert sup.stats()["abandoned_alive"] == 0
        finally:
            release.set()
            sup.shutdown()

    def test_abandoned_cap_refuses_new_workers(self):
        release = threading.Event()
        calls = {"n": 0}

        def fn():
            calls["n"] += 1
            release.wait(10.0)

        sup = SourceSupervisor(
            "cap", fn, deadline_s=0.05, max_abandoned=2,
            breaker=CircuitBreaker(failure_threshold=99),
        )
        try:
            for _ in range(2):
                with pytest.raises(SourceTimeout):
                    sup.call()
            assert calls["n"] == 2
            # Cap reached: fails fast WITHOUT spawning/calling again.
            with pytest.raises(SourceTimeout):
                sup.call()
            assert calls["n"] == 2
            assert sup.abandoned == 2  # the refusal is not an abandonment
        finally:
            release.set()
            sup.shutdown()

    def test_breaker_skip_raises_skipped_without_calling(self):
        calls = {"n": 0}

        def fn():
            calls["n"] += 1
            raise RuntimeError("down")

        clock = [0.0]
        sup = SourceSupervisor(
            "skip", fn, deadline_s=1.0,
            breaker=make_breaker(clock, failure_threshold=2),
        )
        try:
            for _ in range(2):
                with pytest.raises(RuntimeError):
                    sup.call()
            with pytest.raises(SourceSkipped):
                sup.call()
            assert calls["n"] == 2
            assert sup.skipped == 1
        finally:
            sup.shutdown()

    def test_probe_reconnects_then_calls(self):
        events = []
        healthy = {"v": False}

        def fn():
            events.append("call")
            if not healthy["v"]:
                raise RuntimeError("down")
            return "data"

        clock = [0.0]
        sup = SourceSupervisor(
            "rc", fn, reconnect=lambda: events.append("reconnect"),
            deadline_s=1.0, breaker=make_breaker(clock, failure_threshold=2),
        )
        try:
            for _ in range(2):
                with pytest.raises(RuntimeError):
                    sup.call()
            clock[0] += 10.0  # past backoff: next call is a half-open probe
            healthy["v"] = True
            assert sup.call() == "data"
            assert events == ["call", "call", "reconnect", "call"]
            assert sup.reconnects == 1
            assert sup.breaker.state == CLOSED
        finally:
            sup.shutdown()

    def test_recovery_logs_warning_unconditionally(self, caplog):
        flip = {"fail": True}

        def fn():
            if flip["fail"]:
                raise RuntimeError("down")
            return 1

        sup = SourceSupervisor(
            "rlog", fn, deadline_s=1.0,
            breaker=CircuitBreaker(failure_threshold=99),
        )
        try:
            with caplog.at_level(logging.WARNING,
                                 logger="tpu_pod_exporter.supervisor"):
                for _ in range(3):
                    with pytest.raises(RuntimeError):
                        sup.call()
                flip["fail"] = False
                sup.call()
            msgs = [r.getMessage() for r in caplog.records]
            assert any(
                "healthy again after 3 failure(s)" in m for m in msgs
            )
        finally:
            sup.shutdown()

    def test_degraded_after_reopens(self):
        clock = [0.0]
        sup = SourceSupervisor(
            "deg", lambda: (_ for _ in ()).throw(RuntimeError("down")),
            deadline_s=1.0, breaker=make_breaker(clock, failure_threshold=1),
        )
        try:
            for _ in range(3):
                clock[0] += 100.0
                with pytest.raises((RuntimeError, SourceSkipped)):
                    sup.call()
            assert sup.breaker.reopens >= 3
            assert sup.degraded
            assert sup.stats()["degraded"] is True
        finally:
            sup.shutdown()

    def test_worker_thread_is_named_for_debug_stacks(self):
        seen = {}

        def fn():
            seen["name"] = threading.current_thread().name
            return 1

        sup = SourceSupervisor("device", fn, deadline_s=1.0)
        try:
            sup.call()
            assert seen["name"].startswith("tpu-sup-device-")
        finally:
            sup.shutdown()

    def test_shutdown_releases_idle_worker(self):
        sup = SourceSupervisor("sd", lambda: 1, deadline_s=1.0)
        sup.call()
        worker = sup._worker
        sup.shutdown()
        worker.thread.join(timeout=5.0)
        assert not worker.thread.is_alive()
