"""The sanitized-subprocess selftest path — exactly what the driver's
``dryrun_multichip`` gate runs (see ``tpu_pod_exporter.jaxenv`` for why a
child process is required on this machine)."""

import importlib.util
import sys
from pathlib import Path

import pytest

from tpu_pod_exporter.jaxenv import HAZARD_ENV_VARS, cpu_subprocess_env
from tpu_pod_exporter.loadgen.selftest import run_subprocess

REPO = Path(__file__).resolve().parent.parent


def test_cpu_subprocess_env_sanitizes():
    base = {
        "PALLAS_AXON_POOL_IPS": "127.0.0.1",
        "JAX_PLATFORMS": "axon",
        "XLA_FLAGS": "--xla_foo --xla_force_host_platform_device_count=2",
        "PATH": "/usr/bin",
    }
    env = cpu_subprocess_env(4, base=base)
    for var in HAZARD_ENV_VARS:
        assert var not in env
    assert env["JAX_PLATFORMS"] == "cpu"
    assert "--xla_force_host_platform_device_count=4" in env["XLA_FLAGS"]
    assert "device_count=2" not in env["XLA_FLAGS"]
    assert "--xla_foo" in env["XLA_FLAGS"]  # unrelated flags preserved
    assert env["PATH"] == "/usr/bin"


def test_dryrun_multichip_entrypoint():
    """The driver's gate end-to-end: __graft_entry__.dryrun_multichip spawns
    the sanitized selftest child and asserts its report."""
    if importlib.util.find_spec("jax") is None:
        pytest.skip("jax not installed")
    sys.path.insert(0, str(REPO))
    import __graft_entry__ as ge

    ge.dryrun_multichip(4)


def test_selftest_rejects_unknown_check():
    proc = run_subprocess(2, checks="nope", timeout=60)
    assert proc.returncode == 2
    assert "unknown checks" in proc.stdout
