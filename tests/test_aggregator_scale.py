"""Aggregator performance guard at slice-scale inputs (VERDICT r1 #8, r4 #6).

Kept in its own module — away from test_multihost.py's live exporters —
because module-scoped fixtures there keep 8 collector loops polling at 20 Hz
until module teardown, and that background CPU load alone can triple the
measured aggregator round on a busy CI machine.
"""

import time

from tests.test_aggregate import StaticFetch, make_host_text

from tpu_pod_exporter.aggregate import SliceAggregator
from tpu_pod_exporter.metrics import SnapshotStore


class TestAggregatorAtSliceScale:
    """VERDICT r1 #8: aggregator perf at v5p-128-scale inputs — 64 targets,
    ~16k total chip-series parsed per round (parse cost is O(total series)).
    The assertion bound is deliberately loose (CI machines vary wildly);
    the measured number is published in BASELINE.md by bench_aggregate.py."""

    def test_round_duration_64_hosts(self):
        body = make_host_text(0, chips=256)
        pages = {}
        for w in range(64):
            # Re-label per host without re-running a 256-chip collector 64x.
            pages[f"h{w}:8000"] = body.replace('host="host-0"', f'host="host-{w}"')
        store = SnapshotStore()
        agg = SliceAggregator(tuple(pages), store, fetch=StaticFetch(pages))
        try:
            t0 = time.perf_counter()
            agg.poll_once()
            cold = time.perf_counter() - t0
            snap = store.current()
            key = {"slice_name": "slice-a", "accelerator": "v5p-64", "family": "tpu"}
            assert snap.value("tpu_slice_chip_count", key) == 64 * 256.0
            assert snap.value("tpu_slice_hosts_reporting", key) == 64.0
            assert cold < 10.0, f"cold aggregator round took {cold:.2f}s at 64x256"
            # Steady state: the per-target layout cache re-parses values only
            # (~0.34 s measured — bench_aggregate.py / BASELINE.md); the
            # round-5 guard locks that fast path in with headroom for slow
            # CI machines. Best-of-3: this repo's CI can be a 1-core box
            # where a single scheduler hiccup or GC pause doubles one
            # measurement; the MINIMUM is the contention-free number the
            # guard is actually about.
            warm = min(self._timed_round(agg) for _ in range(3))
            snap = store.current()
            assert snap.value("tpu_slice_chip_count", key) == 64 * 256.0
            assert warm < 3.0, f"warm aggregator round took {warm:.2f}s at 64x256"
        finally:
            # Release the 16-thread scrape pool: leaked idle threads are
            # background noise for every later timing test in the session.
            agg.close()

    @staticmethod
    def _timed_round(agg) -> float:
        t0 = time.perf_counter()
        agg.poll_once()
        return time.perf_counter() - t0
