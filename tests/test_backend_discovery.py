"""Device discovery tests against fake /dev trees."""

from tpu_pod_exporter.backend.discovery import (
    discover_chips,
    list_device_paths,
    local_chip_count,
)


def make_dev_tree(tmp_path, names):
    (tmp_path / "dev").mkdir(exist_ok=True)
    for n in names:
        (tmp_path / "dev" / n).touch()
    return str(tmp_path)


class TestDiscovery:
    def test_accel_nodes(self, tmp_path):
        root = make_dev_tree(tmp_path, ["accel0", "accel1", "accel2", "accel3"])
        assert local_chip_count(root) == 4
        chips = discover_chips(root)
        assert [c.chip_id for c in chips] == [0, 1, 2, 3]
        assert chips[0].device_path.endswith("/dev/accel0")
        assert chips[2].device_ids == ("2",)

    def test_numeric_sort_not_lexicographic(self, tmp_path):
        root = make_dev_tree(tmp_path, [f"accel{i}" for i in range(12)])
        chips = discover_chips(root)
        assert [c.chip_id for c in chips] == list(range(12))

    def test_vfio_nodes(self, tmp_path):
        (tmp_path / "dev" / "vfio").mkdir(parents=True)
        for i in range(4):
            (tmp_path / "dev" / "vfio" / str(i)).touch()
        paths = list_device_paths(str(tmp_path))
        assert len(paths) == 4

    def test_empty_host(self, tmp_path):
        assert local_chip_count(str(tmp_path)) == 0
        assert discover_chips(str(tmp_path)) == []

    def test_non_numeric_accel_suffix_ignored(self, tmp_path):
        root = make_dev_tree(tmp_path, ["accel0", "accelfoo", "accel_dbg"])
        assert local_chip_count(root) == 1
        assert [c.chip_id for c in discover_chips(root)] == [0]

    def test_vfio_ignored_when_accel_present(self, tmp_path):
        root = make_dev_tree(tmp_path, ["accel0", "accel1"])
        (tmp_path / "dev" / "vfio").mkdir()
        (tmp_path / "dev" / "vfio" / "7").touch()  # unrelated passthrough group
        assert local_chip_count(root) == 2
        assert len(list_device_paths(root)) == 2

    def test_sysfs_fallback_when_no_dev_nodes(self, tmp_path):
        accel = tmp_path / "sys" / "class" / "accel"
        accel.mkdir(parents=True)
        for i in range(4):
            (accel / f"accel{i}").mkdir()
        (accel / "accelctl").mkdir()  # non-numeric ignored
        root = str(tmp_path)
        assert local_chip_count(root) == 4
        assert [c.chip_id for c in discover_chips(root)] == [0, 1, 2, 3]
        assert discover_chips(root)[0].device_path == "/dev/accel0"

    def test_dev_nodes_beat_sysfs(self, tmp_path):
        make_dev_tree(tmp_path, ["accel0"])
        accel = tmp_path / "sys" / "class" / "accel"
        accel.mkdir(parents=True)
        for i in range(4):
            (accel / f"accel{i}").mkdir()
        assert local_chip_count(str(tmp_path)) == 1

    def test_python_and_native_scans_agree(self, tmp_path):
        from tpu_pod_exporter import nativelib

        lib = nativelib.load()
        if lib is None:
            import pytest

            pytest.skip("native lib not built")
        for names in (["accel0", "accel1", "accelx"], [], ["accel3"]):
            import shutil

            shutil.rmtree(tmp_path / "dev", ignore_errors=True)
            root = make_dev_tree(tmp_path, names)
            assert lib.tpumon_count_devices(root.encode()) == local_chip_count(root)
