"""Full-stack churn soak (BASELINE config 5's shape; VERDICT r3 #5, r4 #3).

Eight in-process exporters (fake 4-chip backends) forming a TWO-SLICE
multi-slice group (4 hosts per slice, shared multislice_group, per-chip DCN
links), scraped over real HTTP by one SliceAggregator, with continuous pod
churn, injected backend/attribution faults, and a mid-soak host outage
window — all at the production 1 s interval for ≥60 s of wall clock.
Asserts the properties the per-poll tests can't: no stale series survive
churn over many generations, per-slice hosts_reporting tracks an outage and
recovers, cross-slice group rollups stay consistent with their per-slice
parts, CPU/RSS stay bounded, and no poll thread dies. Contrast the
reference, whose loop dies on the first NVML/apiserver hiccup
(main.go:119-137) and leaks stale series forever (SURVEY.md §2.6).

Scale knob: TPE_SOAK_SECONDS (default 60; the marker is ``slow``).
"""

from __future__ import annotations

import os
import resource
import time
import urllib.request

import pytest

from tpu_pod_exporter.aggregate import SliceAggregator, default_fetch
from tpu_pod_exporter.app import ExporterApp
from tpu_pod_exporter.attribution.fake import FakeAttribution, simple_allocation
from tpu_pod_exporter.backend.fake import FakeBackend, FakeChipScript
from tpu_pod_exporter.config import ExporterConfig
from tpu_pod_exporter.metrics import SnapshotStore

GIB = 1024**3
NUM_HOSTS = 8
HOSTS_PER_SLICE = 4
CHIPS_PER_HOST = 4
SOAK_S = float(os.environ.get("TPE_SOAK_SECONDS", "60"))
INTERVAL_S = 1.0
OUTAGE_HOST = 3  # in slice-a
MULTISLICE_GROUP = "ms-soak-group"


def _slice_of(worker_id: int) -> str:
    return "slice-a" if worker_id < HOSTS_PER_SLICE else "slice-b"


SLICE_A = {"slice_name": "slice-a", "accelerator": "v5p-64"}
SLICE_B = {"slice_name": "slice-b", "accelerator": "v5p-64"}
GROUP_KEY = {"multislice_group": MULTISLICE_GROUP}


def _read_rss_bytes() -> int:
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmRSS:"):
                return int(line.split()[1]) * 1024
    return 0


def _scrape(port: int, path: str = "/metrics") -> str:
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=5
    ) as r:
        return r.read().decode()


# One host runs a backend whose HBM is unreadable every poll (the tunnel
# shape, HARDWARE.md): it must stay in hosts_reporting/chip counts for the
# whole soak while publishing no tpu_hbm_* series.
HBM_LESS_HOST = 7


class _HbmLessBackend(FakeBackend):
    def sample(self):
        from tpu_pod_exporter.backend import HostSample

        real = super().sample()
        return HostSample(
            chips=tuple(
                # _replace nulls ONLY the HBM fields — every other (and any
                # future) ChipSample field keeps flowing, so the soak shape
                # stays a real backend's shape minus HBM.
                c._replace(hbm_used_bytes=None, hbm_total_bytes=None,
                           hbm_peak_bytes=None)
                for c in real.chips
            ),
            partial_errors=real.partial_errors
            + tuple(f"device {c.info.chip_id}: memory_stats empty" for c in real.chips),
        )


def _make_host(worker_id: int):
    cls = _HbmLessBackend if worker_id == HBM_LESS_HOST else FakeBackend
    backend = cls(
        chips=CHIPS_PER_HOST,
        script=FakeChipScript(
            hbm_total_bytes=96 * GIB,
            hbm_used_bytes=8 * GIB,
            duty_cycle_percent=70.0,
            ici_link_count=6,
            ici_bytes_per_step=1_000_000.0,
            # Cross-slice fabric: every chip carries one DCN link so the
            # slice and group DCN rollups are exercised for the whole soak.
            dcn_link_count=1,
            dcn_bytes_per_step=250_000.0,
        ),
    )
    attr = FakeAttribution(
        [simple_allocation("job-gen0", [str(i) for i in range(CHIPS_PER_HOST)],
                           namespace="ml")]
    )
    cfg = ExporterConfig(
        port=0,
        host="127.0.0.1",
        interval_s=INTERVAL_S,
        accelerator="v5p-64",
        slice_name=_slice_of(worker_id),
        node_name=f"host-{worker_id}",
        worker_id=str(worker_id % HOSTS_PER_SLICE),
        multislice_group=MULTISLICE_GROUP,
    )
    return ExporterApp(cfg, backend=backend, attribution=attr), backend, attr


@pytest.mark.slow
def test_full_stack_churn_soak():
    # expected_slices comes from the GKE multi-slice environment.
    os.environ["MEGASCALE_NUM_SLICES"] = "2"
    hosts = [_make_host(w) for w in range(NUM_HOSTS)]
    apps = [h[0] for h in hosts]
    for app in apps:
        app.start()
    down: set[str] = set()

    def fetch(target: str, timeout_s: float) -> str:
        if target in down:
            raise ConnectionError("induced outage")
        return default_fetch(target, timeout_s)

    targets = tuple(
        f"http://127.0.0.1:{app.port}/metrics" for app in apps
    )
    agg_store = SnapshotStore()
    agg = SliceAggregator(targets, agg_store, fetch=fetch)

    generation = 0
    outage_rounds_checked = 0
    recovered_rounds_checked = 0
    dcn_rounds_checked = 0
    ru0 = resource.getrusage(resource.RUSAGE_SELF)
    t_start = time.monotonic()
    rss_warm = None
    try:
        deadline = t_start + SOAK_S
        tick = 0
        while time.monotonic() < deadline:
            round_t0 = time.monotonic()
            tick += 1
            elapsed = round_t0 - t_start

            # Churn: every 5 ticks every host's allocation moves to a new
            # pod generation (JobSet restart), so stale-series GC is
            # exercised across many generations.
            if tick % 5 == 0:
                generation += 1
                for _, _, attr in hosts:
                    attr.set_allocations(
                        [simple_allocation(
                            f"job-gen{generation}",
                            [str(i) for i in range(CHIPS_PER_HOST)],
                            namespace="ml",
                        )]
                    )
            # Faults: a backend read failure and an attribution failure
            # land on rotating hosts — both must be contained (error
            # budget), never killing a poll thread.
            if tick % 7 == 0:
                hosts[tick % NUM_HOSTS][1].fail_next(1)
            if tick % 11 == 0:
                hosts[(tick + 3) % NUM_HOSTS][2].fail_next(1)

            # Outage window: one host unreachable for the middle ~third.
            frac = elapsed / SOAK_S
            in_outage = 0.4 <= frac < 0.65
            if in_outage:
                down.add(targets[OUTAGE_HOST])
            else:
                down.discard(targets[OUTAGE_HOST])

            agg.poll_once()
            snap = agg_store.current()
            rep_a = snap.value("tpu_slice_hosts_reporting", SLICE_A) or 0.0
            rep_b = snap.value("tpu_slice_hosts_reporting", SLICE_B) or 0.0
            # An injected backend fault hides one MORE host for one round
            # (the collector deliberately serves no stale device data —
            # collector.py phase 1), so the hard bounds allow one extra
            # missing host while the exact value must still be observed in
            # several rounds of each regime. The outage host is in slice-a.
            if in_outage:
                assert HOSTS_PER_SLICE - 2 <= rep_a <= HOSTS_PER_SLICE - 1, (
                    f"t={elapsed:.0f}s outage: slice-a got {rep_a}"
                )
                if rep_a == float(HOSTS_PER_SLICE - 1):
                    outage_rounds_checked += 1
            elif elapsed > 2.0 and frac >= 0.7:
                assert rep_a >= HOSTS_PER_SLICE - 1, (
                    f"t={elapsed:.0f}s recovered: slice-a got {rep_a}"
                )
                if rep_a == float(HOSTS_PER_SLICE):
                    recovered_rounds_checked += 1
            if elapsed > 2.0:
                # slice-b never has the outage; one fault-hidden host max.
                assert rep_b >= HOSTS_PER_SLICE - 1, (
                    f"t={elapsed:.0f}s slice-b got {rep_b}"
                )
                # Cross-slice (multi-slice group) rollups must agree with
                # their per-slice parts EVERY round, through churn, faults,
                # and the outage window (VERDICT r4 #3).
                assert snap.value(
                    "tpu_multislice_slices_reporting", GROUP_KEY
                ) == 2.0
                assert snap.value(
                    "tpu_multislice_expected_slices", GROUP_KEY
                ) == 2.0
                assert snap.value(
                    "tpu_multislice_hosts_reporting", GROUP_KEY
                ) == rep_a + rep_b
                chips_a = snap.value("tpu_slice_chip_count", SLICE_A) or 0.0
                chips_b = snap.value("tpu_slice_chip_count", SLICE_B) or 0.0
                assert snap.value(
                    "tpu_multislice_chip_count", GROUP_KEY
                ) == chips_a + chips_b
                dcn = snap.value(
                    "tpu_multislice_dcn_bytes_per_second", GROUP_KEY
                )
                if dcn is not None and dcn > 0:
                    dcn_rounds_checked += 1

            if rss_warm is None and elapsed >= 5.0:
                rss_warm = _read_rss_bytes()

            # Hold the 1 s cadence (work time is subtracted, like the
            # exporters' own drift-free loops).
            sleep_left = INTERVAL_S - (time.monotonic() - round_t0)
            if sleep_left > 0:
                time.sleep(sleep_left)

        wall = time.monotonic() - t_start
        assert outage_rounds_checked >= 3
        assert recovered_rounds_checked >= 3
        assert dcn_rounds_checked >= 3  # cross-slice DCN rollup was live

        # Let every exporter complete a poll on the final generation, then
        # take one settled aggregation round before end-state checks.
        time.sleep(2 * INTERVAL_S + 0.2)
        agg.poll_once()

        # --- end-state assertions -------------------------------------
        final_pod = f"job-gen{generation}"
        for i, app in enumerate(apps):
            text = _scrape(app.port)
            # Poll thread alive and polling (up=1, healthz 200).
            assert "tpu_exporter_up 1" in text, f"host {i} poll loop died"
            assert app.loop._thread is not None and app.loop._thread.is_alive()
            assert "ok" in _scrape(app.port, "/healthz")
            # No stale series: every generation before the last must be
            # fully GC'd from the exporter's own exposition.
            assert f'pod="{final_pod}"' in text
            for g in range(generation):
                assert f'pod="job-gen{g}"' not in text, (
                    f"host {i} leaked series of generation {g}"
                )
            if i == HBM_LESS_HOST:
                # Unreadable HBM for the whole soak: presence series yes,
                # HBM series never (absent beats fake-zero), and the
                # partial errors were counted every poll.
                assert "tpu_hbm_used_bytes{" not in text
                assert text.count("tpu_chip_info{") == CHIPS_PER_HOST
                assert 'source="device_partial"' in text
        # Aggregator rebuilt per round: its workload rollup carries only
        # the live generation too (keyed per slice).
        agg_snap = agg_store.current()
        for sname in ("slice-a", "slice-b"):
            assert agg_snap.value(
                "tpu_workload_chip_count",
                {"pod": final_pod, "namespace": "ml", "slice_name": sname},
            ) == float(HOSTS_PER_SLICE * CHIPS_PER_HOST)

        # --- resource bounds ------------------------------------------
        ru1 = resource.getrusage(resource.RUSAGE_SELF)
        cpu_s = (ru1.ru_utime - ru0.ru_utime) + (ru1.ru_stime - ru0.ru_stime)
        cpu_frac = cpu_s / wall
        # 8 exporters + aggregator + this driver in one process; the
        # budget is generous vs the <1%/exporter target because the test
        # process also runs scrapes and assertions.
        assert cpu_frac < 0.5, f"soak burned {cpu_frac:.0%} CPU"
        rss_end = _read_rss_bytes()
        assert rss_warm is not None
        growth = rss_end - rss_warm
        assert growth < 64 * 1024 * 1024, (
            f"RSS grew {growth / 1e6:.1f} MB over the soak "
            f"({rss_warm / 1e6:.1f} → {rss_end / 1e6:.1f})"
        )
    finally:
        os.environ.pop("MEGASCALE_NUM_SLICES", None)
        agg.close()
        for app in apps:
            app.stop()
