"""Fleet scenario engine tests (ISSUE 9).

Covers:

- the scenario timeline DSL (tpu_pod_exporter.scenario): every event
  kind's happy path plus the actionable-error contract — unknown kinds,
  bad coordinates, bad modes/edges, overlapping same-identity events;
- parse_leaf_timeline error paths (the PR-8 grammar the satellite names);
- the partition switchboard (chaos.PartitionState / PartitionedFetch /
  PartitionedSend): tier vs instance selectors, symmetric cuts, seeded
  deterministic flapping, heal, blocked accounting;
- ChaosReceiver's scenario outage switch (503s without consuming the
  seeded rule schedule);
- RootAggregator stale-serve: last-known views merged while a leaf is
  unreachable (leaf_up 0, stale_served 1, partition_suspected with a
  reachable twin, zero series lost), expiry past the budget, and the
  /readyz degradation contract at root and flat-aggregator tiers;
- status --tree --watch's unreachable-root rendering;
- a small end-to-end run of the scenario engine, plus the negative
  control proving the invariants catch a disabled hardening.
"""

from __future__ import annotations

import pytest

from tpu_pod_exporter import scenario as sc
from tpu_pod_exporter import shard as sh
from tpu_pod_exporter.aggregate import SliceAggregator
from tpu_pod_exporter.chaos import (
    ChaosReceiver,
    PartitionError,
    PartitionState,
    PartitionedFetch,
    PartitionedSend,
    parse_chaos_spec,
    parse_leaf_timeline,
)
from tpu_pod_exporter.metrics import SnapshotStore
from tpu_pod_exporter.metrics.parse import parse_families


# ----------------------------------------------------------- timeline DSL


class TestScenarioGrammar:
    def test_every_kind_parses(self):
        evs = sc.parse_scenario(
            "partition(leaf<->root, symmetric)@3+2; "
            "partition(node<->leaf, flapping)@9+2, "
            "preempt(slice-2)@6+3; restart_wave(6, stagger=2)@12; "
            "churn_storm(8)@15+2; hotspot(job-3)@18+2; recv_outage()@21+4"
        )
        kinds = [e.kind for e in evs]
        assert kinds == ["partition", "preempt", "partition",
                         "restart_wave", "churn_storm", "hotspot",
                         "recv_outage"]
        part = evs[0]
        assert part.edge == ("leaf", "root")
        assert part.mode == "symmetric"
        assert (part.at_round, part.duration) == (3, 2)
        wave = evs[3]
        assert wave.count == 6
        assert wave.stagger == 2
        assert wave.duration == 3  # ceil(6/2), derived

    def test_named_scenarios_all_parse(self):
        for name, scn in sc.SCENARIOS.items():
            evs = scn.events()
            assert evs, name
            assert sc.total_rounds(evs, scn.settle_rounds) > max(
                e.end_round for e in evs
            )

    @pytest.mark.parametrize("spec,needle", [
        ("frobnicate(x)@1", "unknown event kind 'frobnicate'"),
        ("partition(leaf<->root)@1", "exactly (tierA<->tierB, mode)"),
        ("partition(leaf->root, symmetric)@1", "bad edge 'leaf->root'"),
        ("partition(leaf<->leaf, symmetric)@1", "connects 'leaf' to itself"),
        ("partition(node<->root, symmetric)@1", "no node<->root seam"),
        ("partition(leaf<->root, sometimes)@1", "unknown partition mode"),
        ("partition(leaf<->rooot, symmetric)@1", "unknown tier 'rooot'"),
        ("partition(leaf<->root, symmetric)", "want kind(args)@round"),
        ("partition(leaf<->root, symmetric)@-2", "round -2 is negative"),
        ("partition(leaf<->root, symmetric)@2+0", "must be at least +1"),
        ("preempt(slice-x)@1", "bad slice coordinate 'slice-x'"),
        ("preempt()@1", "exactly (slice-N)"),
        ("restart_wave(zero)@1", "bad host count 'zero'"),
        ("restart_wave(4, skew=2)@1", "unknown restart_wave option"),
        ("restart_wave(4, stagger=0)@1", "stagger 0 must be >= 1"),
        ("restart_wave(4, stagger=2)@1+7", "derives its duration"),
        ("churn_storm(1)@1", "churn size 1 must be >= 2"),
        ("hotspot()@1", "exactly (podname)"),
        ("recv_outage(now)@1", "takes no arguments"),
        ("", "contains no events"),
    ])
    def test_actionable_errors(self, spec, needle):
        with pytest.raises(ValueError) as ei:
            sc.parse_scenario(spec)
        assert needle in str(ei.value)

    def test_overlap_same_identity_rejected(self):
        with pytest.raises(ValueError) as ei:
            sc.parse_scenario("preempt(slice-1)@2+3; preempt(slice-1)@4")
        msg = str(ei.value)
        assert "overlap" in msg
        assert "preempt(slice-1)@2+3" in msg

    def test_overlap_different_identity_allowed(self):
        evs = sc.parse_scenario(
            "preempt(slice-1)@2+3; preempt(slice-2)@2+3; "
            "partition(leaf<->root, flapping)@2+4"
        )
        assert len(evs) == 3

    def test_partition_edges_order_insensitive(self):
        a = sc.parse_event("partition(root<->leaf, symmetric)@1")
        b = sc.parse_event("partition(leaf<->root, symmetric)@1")
        assert a.overlap_key() == b.overlap_key()


class TestLeafTimelineGrammar:
    """parse_leaf_timeline (PR 8) error paths — bad coordinates and
    unknown kinds must be actionable messages, not tracebacks."""

    def test_valid(self):
        evs = parse_leaf_timeline("kill:1a@3#12,restart:1a@6")
        assert [(e.action, e.leaf, e.round_idx, e.at_call) for e in evs] == [
            ("kill", "1a", 3, 12), ("restart", "1a", 6, None),
        ]

    @pytest.mark.parametrize("spec,needle", [
        ("explode:1a@3", "unknown action 'explode'"),
        ("kill:1a", "want action:leaf@round"),
        ("kill@3", "want action:leaf@round"),
        ("kill:1a@x", "want action:leaf@round"),
        ("kill:1a@-3", "want action:leaf@round"),
        ("kill:1a@3#x", "want action:leaf@round"),
        ("restart:1a@3#4", "#call only applies to kill"),
        ("", "contains no events"),
        (" , ", "contains no events"),
    ])
    def test_actionable_errors(self, spec, needle):
        with pytest.raises(ValueError) as ei:
            parse_leaf_timeline(spec)
        assert needle in str(ei.value)


# --------------------------------------------------- partition switchboard


class TestPartitionState:
    def test_symmetric_cut_and_heal(self):
        net = PartitionState(seed=1)
        net.cut("root", "leaf")
        assert net.is_cut("root", "leaf:1a")
        assert net.is_cut("root", "leaf:0b")
        assert not net.is_cut("leaf:1a", "node:3")  # other edges open
        net.heal("root", "leaf")
        assert not net.is_cut("root", "leaf:1a")
        assert not net.any_cuts()

    def test_instance_selector_cuts_only_that_instance(self):
        net = PartitionState(seed=1)
        net.cut("root", "leaf:1a")
        assert net.is_cut("root", "leaf:1a")
        assert not net.is_cut("root", "leaf:1b")

    def test_flapping_is_round_keyed_and_seed_deterministic(self):
        def schedule(seed):
            net = PartitionState(seed=seed)
            net.cut("root", "leaf", flapping=True)
            out = []
            for r in range(8):
                net.advance(r)
                out.append(net.is_cut("root", "leaf:0a"))
            return out

        a, b = schedule(7), schedule(7)
        assert a == b                      # deterministic under one seed
        assert True in a and False in a    # actually flaps
        assert all(a[i] != a[i + 1] for i in range(7))  # alternates/round

    def test_active_lists_only_effective_cuts(self):
        net = PartitionState(seed=3)
        net.cut("root", "recv")
        net.cut("root", "leaf", flapping=True)
        net.advance(0)
        eff0 = net.active()
        net.advance(1)
        eff1 = net.active()
        # The static cut is always effective; the flapping one only on
        # alternating rounds.
        assert ("root", "recv", False) in eff0
        assert ("root", "recv", False) in eff1
        assert (("root", "leaf", True) in eff0) != (
            ("root", "leaf", True) in eff1)
        assert net.any_cuts()

    def test_partitioned_fetch_blocks_and_counts(self):
        net = PartitionState(seed=1)
        calls = []

        def inner(target, timeout_s):
            calls.append(target)
            return "body"

        pf = PartitionedFetch(net, "leaf:1a", lambda t: f"node:{t}", inner)
        assert pf("7", 1.0) == "body"
        net.cut("leaf", "node:7")
        with pytest.raises(PartitionError):
            pf("7", 1.0)
        assert pf.blocked == 1
        assert calls == ["7"]  # the cut call never reached the wire
        assert isinstance(PartitionError("x"), ConnectionError)

    def test_partitioned_send_blocks(self):
        net = PartitionState(seed=1)
        sent = []

        def inner(url, body, headers, timeout_s):
            sent.append(url)
            return 200

        ps = PartitionedSend(net, "root", "recv", inner)
        assert ps("http://r/w", b"x", {}, 1.0) == 200
        net.cut("root", "recv")
        with pytest.raises(PartitionError):
            ps("http://r/w", b"x", {}, 1.0)
        assert ps.blocked == 1
        assert sent == ["http://r/w"]


class TestReceiverOutage:
    def test_outage_503s_without_consuming_schedule(self):
        import urllib.error
        import urllib.request

        recv = ChaosReceiver(parse_chaos_spec("err:recv:1:x1"), seed=0)
        recv.start()
        try:
            recv.set_outage(True)
            for _ in range(2):
                with pytest.raises(urllib.error.HTTPError) as ei:
                    urllib.request.urlopen(  # noqa: S310 — loopback test
                        urllib.request.Request(
                            recv.url, data=b"x", method="POST"),
                        timeout=5)
                assert ei.value.code == 503
            stats = recv.stats()
            assert stats["outage_responses"] == 2
            # The seeded rule schedule was NOT consumed by outage answers.
            assert stats["calls"] == 0
            assert stats["injected"] == []
            recv.set_outage(False)
            # First scheduled request now draws the err rule → 500.
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(  # noqa: S310 — loopback test
                    urllib.request.Request(
                        recv.url, data=b"x", method="POST"),
                    timeout=5)
            assert ei.value.code == 500
            assert recv.stats()["injected"] == [(0, "err")]
        finally:
            recv.stop()


# ------------------------------------------------------- root stale-serve


def _node_body(idx: int, rnd: int = 0) -> str:
    cl = (f'chip_id="0",device_path="",accelerator="sim",'
          f'slice_name="slice-{idx % 2}",host="host-{idx}",'
          f'worker_id="{idx}",pod="job-{idx % 3}",namespace="s",'
          f'container="w"')
    hbm = float((idx + 1) * 2**20 + rnd * 4096)
    return (
        f'tpu_chip_info{{{cl},device_kind="",coords=""}} 1\n'
        f'tpu_hbm_used_bytes{{{cl}}} {hbm:.1f}\n'
        f'tpu_hbm_total_bytes{{{cl}}} {float(2**30):.1f}\n'
    )


def _build_ha_tree(stale_serve_s: float, wallclock):
    """One HA shard over injected fetches; returns (root, store, state)
    where state controls which leaves are reachable."""
    targets = tuple(f"h{i}:8000" for i in range(4))
    rnd = [0]

    def node_fetch(target, timeout_s):
        return _node_body(int(target.split(":")[0][1:]), rnd[0])

    smap = sh.ShardMap(sh.default_shards(1))
    leaves = {}
    for leaf_id in ("0a", "0b"):
        store = SnapshotStore()
        agg = sh.LeafAggregator(
            "shard-0", leaf_id, smap, targets=targets, store=store,
            fetch=node_fetch, wallclock=wallclock,
        )
        leaves[f"leaf-{leaf_id}:9100"] = (agg, store)
    state = {"down": set(), "rnd": rnd}

    def leaf_fetch(addr, timeout_s):
        if addr in state["down"]:
            raise ConnectionError(f"{addr} unreachable (cut)")
        return leaves[addr][1].current().encode().decode()

    root_store = SnapshotStore()
    root = sh.RootAggregator(
        {"shard-0": tuple(leaves)}, root_store, fetch=leaf_fetch,
        stale_serve_s=stale_serve_s, wallclock=wallclock,
        breaker_failures=0,
    )
    return root, root_store, state, leaves


def _poll_all(root, leaves, rnd_bump=True):
    for agg, _store in leaves.values():
        agg.poll_once()
    root.poll_once()


class TestRootStaleServe:
    def _fams(self, store):
        return parse_families(store.current().encode().decode())

    def test_stale_serve_keeps_series_and_labels_them(self):
        clock = [1000.0]
        root, store, state, leaves = _build_ha_tree(
            stale_serve_s=30.0, wallclock=lambda: clock[0])
        _poll_all(root, leaves)
        fams = self._fams(store)
        baseline = {
            (s.name, tuple(sorted(s.labels.items())))
            for name in ("tpu_slice_chip_count", "tpu_aggregator_target_up",
                         "tpu_workload_chip_count")
            for s in fams.get(name, ())
        }
        # Cut BOTH leaves (symmetric partition): everything unreachable.
        state["down"] = set(leaves)
        clock[0] += 5.0
        root.poll_once()
        fams = self._fams(store)
        now = {
            (s.name, tuple(sorted(s.labels.items())))
            for name in ("tpu_slice_chip_count", "tpu_aggregator_target_up",
                         "tpu_workload_chip_count")
            for s in fams.get(name, ())
        }
        assert baseline <= now  # zero series lost
        ups = {s.labels["leaf"]: s.value
               for s in fams["tpu_root_leaf_up"]}
        served = {s.labels["leaf"]: s.value
                  for s in fams["tpu_root_leaf_stale_served"]}
        assert set(ups.values()) == {0.0}   # honestly down…
        assert set(served.values()) == {1.0}  # …but stale-served
        stale = {s.labels["leaf"]: s.value
                 for s in fams["tpu_root_leaf_staleness_seconds"]}
        assert all(v >= 5.0 for v in stale.values())
        # No twin reachable → partition suspicion stays 0 (could be a
        # dead tier, not a one-sided cut).
        suspected = {s.labels["leaf"]: s.value
                     for s in fams["tpu_root_leaf_partition_suspected"]}
        assert set(suspected.values()) == {0.0}
        # readyz detail degrades.
        detail = root.ready_detail()
        assert detail["leaf_tier"]["reachable"] == 0
        assert detail["degraded_sources"]

    def test_one_sided_cut_suspects_partition_and_twin_covers(self):
        clock = [1000.0]
        root, store, state, leaves = _build_ha_tree(
            stale_serve_s=30.0, wallclock=lambda: clock[0])
        _poll_all(root, leaves)
        victim = next(iter(leaves))
        state["down"] = {victim}
        clock[0] += 2.0
        root.poll_once()
        fams = self._fams(store)
        by_leaf = {s.labels["leaf"]: s.value
                   for s in fams["tpu_root_leaf_partition_suspected"]}
        assert by_leaf[victim] == 1.0
        assert all(v == 0.0 for leaf, v in by_leaf.items() if leaf != victim)
        # Twin fresh → the merged view keeps every series, values live.
        assert len(fams["tpu_aggregator_target_up"]) == 4
        # Reachable twins keep the root un-degraded.
        assert "degraded_sources" not in root.ready_detail()

    def test_stale_serve_expires_past_budget(self):
        clock = [1000.0]
        root, store, state, leaves = _build_ha_tree(
            stale_serve_s=10.0, wallclock=lambda: clock[0])
        _poll_all(root, leaves)
        state["down"] = set(leaves)
        clock[0] += 60.0  # way past the budget
        root.poll_once()
        fams = self._fams(store)
        assert not fams.get("tpu_slice_chip_count")
        served = {s.value for s in fams["tpu_root_leaf_stale_served"]}
        assert served == {0.0}

    def test_disabled_stale_serve_keeps_old_behavior(self):
        clock = [1000.0]
        root, store, state, leaves = _build_ha_tree(
            stale_serve_s=0.0, wallclock=lambda: clock[0])
        _poll_all(root, leaves)
        state["down"] = set(leaves)
        root.poll_once()
        fams = self._fams(store)
        assert not fams.get("tpu_slice_chip_count")

    def test_freshest_wins_stable_under_flapping_reachability(self):
        """The freshest-wins winner must not flap while one HA leaf's
        reachability strobes: the cached view keeps its frozen round_ts,
        so the live twin stays the winner for every shared group."""
        clock = [1000.0]
        root, store, state, leaves = _build_ha_tree(
            stale_serve_s=30.0, wallclock=lambda: clock[0])
        victim = next(iter(leaves))
        values = []
        for i in range(6):
            state["rnd"][0] = i
            for addr, (agg, _s) in leaves.items():
                if addr != victim:
                    agg.poll_once()
            # Flap the victim's reachability every other root round; its
            # body (when reachable) is one leaf-round stale.
            state["down"] = {victim} if i % 2 else set()
            clock[0] += 1.0
            root.poll_once()
            fams = self._fams(store)
            hbm = sum(s.value
                      for s in fams.get("tpu_slice_hbm_used_bytes", ()))
            values.append(hbm)
        # The live twin's fresh values win every round: the published sum
        # tracks the advancing rounds monotonically, never dips back to a
        # stale flap value.
        assert values == sorted(values)


class TestAggregatorReadyDetail:
    def test_all_targets_dark_degrades(self):
        store = SnapshotStore()

        def fetch(target, timeout_s):
            raise ConnectionError("cut")

        agg = SliceAggregator(("h0:1", "h1:1"), store, fetch=fetch,
                              breaker_failures=0)
        agg.poll_once()
        detail = agg.ready_detail()
        assert detail["scrape_plane"] == {
            "targets_ok": 0, "quarantined": 0, "targets": 2}
        assert "partition suspected" in detail["degraded_sources"][0]

    def test_partial_outage_is_detail_not_degradation(self):
        store = SnapshotStore()

        def fetch(target, timeout_s):
            if target == "h0:1":
                raise ConnectionError("down")
            return _node_body(1)

        agg = SliceAggregator(("h0:1", "h1:1"), store, fetch=fetch,
                              breaker_failures=0)
        agg.poll_once()
        detail = agg.ready_detail()
        assert detail["scrape_plane"]["targets_ok"] == 1
        assert "degraded_sources" not in detail

    def test_served_through_readyz_http(self):
        import json
        import urllib.request

        from tpu_pod_exporter.server import MetricsServer

        store = SnapshotStore()

        def fetch(target, timeout_s):
            raise ConnectionError("cut")

        agg = SliceAggregator(("h0:1",), store, fetch=fetch,
                              breaker_failures=0)
        agg.poll_once()
        server = MetricsServer(store, host="127.0.0.1", port=0,
                               ready_detail_fn=agg.ready_detail)
        server.start()
        try:
            with urllib.request.urlopen(  # noqa: S310 — loopback test
                    f"http://127.0.0.1:{server.port}/readyz",
                    timeout=5) as r:
                doc = json.loads(r.read())
            assert doc["state"] == "degraded"
            assert doc["scrape_plane"]["targets"] == 1
        finally:
            server.stop()


# ------------------------------------------------------ status --tree watch


class TestTreeWatchRender:
    DOC = {
        "root": "r:9100",
        "shards": {
            "shard-0": {
                "targets": 4, "quarantined": 0,
                "leaves": {"l0a": {"up": 1.0, "staleness_s": 0.4},
                           "l0b": {"up": 1.0, "staleness_s": 1.2}},
                "freshest": "l0a",
            },
        },
        "fleet": {"targets": 4, "targets_up": 4, "chips": 8.0,
                  "dedup_stale_wins_total": 0.0,
                  "reshard_moves_total": 0.0,
                  "last_round_ts": None, "round_duration_s": 0.1},
    }

    def test_unreachable_with_last_known_state(self):
        from tpu_pod_exporter.status import render_tree_screen

        out = render_tree_screen("r:9100", self.DOC,
                                 error=ConnectionError("refused"),
                                 unreachable_s=12.3)
        assert "shard-0" in out           # last-known table still renders
        assert "unreachable (12s)" in out
        assert "showing last-known state" in out

    def test_unreachable_before_any_fetch(self):
        from tpu_pod_exporter.status import render_tree_screen

        out = render_tree_screen("r:9100", None,
                                 error=ConnectionError("refused"),
                                 unreachable_s=3.0)
        assert "no tree fetched yet" in out

    def test_healthy_frame_has_no_footer(self):
        from tpu_pod_exporter.status import render_tree_screen

        out = render_tree_screen("r:9100", self.DOC)
        assert "unreachable" not in out


# --------------------------------------------------------- engine end-to-end


@pytest.fixture
def quiet_logs():
    import logging

    logging.disable(logging.WARNING)
    yield
    logging.disable(logging.NOTSET)


class TestFuzzDerivedDrills:
    """The fuzz_* SCENARIOS entries are ddmin'd fuzzer finds promoted to
    named regression drills; they must stay canonical so the fuzzer's
    replay/minimize tooling round-trips them byte-for-byte."""

    FUZZ_DRILLS = ("fuzz_root_restart_egress", "fuzz_hotspot_churn")

    @pytest.mark.parametrize("name", FUZZ_DRILLS)
    def test_timeline_is_canonical_fixpoint(self, name):
        scn = sc.SCENARIOS[name]
        assert sc.render_timeline(sc.parse_scenario(scn.timeline)) \
            == scn.timeline

    @pytest.mark.parametrize("name", FUZZ_DRILLS)
    def test_provenance_documented(self, name):
        scn = sc.SCENARIOS[name]
        assert scn.uses_egress, name
        assert "fuzz" in scn.description.lower(), (
            "fuzz-derived drills must document their provenance")

    def test_headline_find_cites_replay_coordinates(self):
        desc = sc.SCENARIOS["fuzz_root_restart_egress"].description
        assert "seed 1 trial 7" in desc


class TestScenarioEngine:
    def test_asymmetric_partition_end_to_end(self, tmp_path, quiet_logs):
        from tpu_pod_exporter.loadgen.scenario import _Run

        run = _Run(sc.SCENARIOS["partition_asymmetric"], 16, 2, 2,
                   str(tmp_path / "state"), seed=42)
        result = run.run()
        assert result["ok"], result.get("problems")
        assert result["recovered"]
        assert result["readyz_state"] == "ready"
        eg = result["egress"]
        assert eg["accepted"] == eg["batches"] > 0
        assert eg["duplicate_seqs"] == 0
        assert eg["duplicate_samples"] == 0
        assert run.trace  # per-tick invariant records exist

    def test_negative_control_catches_disabled_hardening(
            self, tmp_path, quiet_logs):
        """With stale-serve OFF, the symmetric-partition drill must FAIL
        (series vanish / not stale-served) — the invariants are not
        vacuous, they read the same exposition the hardening feeds."""
        from tpu_pod_exporter.loadgen.scenario import _Run

        run = _Run(sc.SCENARIOS["partition_symmetric"], 12, 2, 2,
                   str(tmp_path / "state"), seed=42, stale_serve_s=0.0)
        result = run.run()
        assert not result["ok"]
        assert any("lost during partition" in p or "not stale-served" in p
                   for p in result["problems"])
