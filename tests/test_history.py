"""Flight-recorder history store + /api/v1 query surface (ISSUE 1).

Covers the ring-buffer mechanics (wraparound, eviction, retention GC), the
counter-aware window rate (reset tolerance — the ICI/DCN fold semantics),
the JSON endpoints' clean 4xx contract, and the full integration path:
fake backend → collector → history → HTTP query.
"""

import json
import urllib.error
import urllib.request

import pytest

from tpu_pod_exporter.history import HISTORY_TRACKED_METRICS, HistoryStore
from tpu_pod_exporter.metrics import SnapshotStore
from tpu_pod_exporter.server import MetricsServer, debug_client_allowed


class FakeClock:
    def __init__(self, t0: float = 0.0) -> None:
        self.t = t0

    def __call__(self) -> float:
        return self.t


def make_store(capacity=4, max_series=8, retention_s=0.0, t0=0.0):
    clock = FakeClock(t0)
    store = HistoryStore(
        capacity=capacity, max_series=max_series, retention_s=retention_s,
        clock=clock, wallclock=lambda: 1000.0 + clock.t,
    )
    return store, clock


class TestRing:
    def test_wraparound_keeps_newest_capacity_samples(self):
        h, clock = make_store(capacity=4)
        for i in range(10):
            clock.t = float(i)
            h.append("m", {"x": "1"}, float(i * 100))
        [row] = h.query_range("m", {"x": "1"}, start=0.0, end=2000.0)
        # Only the last 4 survive, oldest first, timestamps intact.
        assert row["values"] == [
            [1006.0, 600.0], [1007.0, 700.0], [1008.0, 800.0], [1009.0, 900.0]
        ]
        assert h.stats()["samples"] == 4

    def test_append_is_preallocated_o1(self):
        # The ring never grows: stats' memory estimate is flat from sample 1.
        h, clock = make_store(capacity=8)
        h.append("m", {}, 1.0)
        before = h.stats()["memory_bytes"]
        for i in range(100):
            clock.t = float(i)
            h.append("m", {}, float(i))
        assert h.stats()["memory_bytes"] == before


class TestEviction:
    def test_capacity_eviction_drops_least_recently_fed_series(self):
        h, clock = make_store(max_series=2)
        h.append("m", {"s": "a"}, 1.0)
        clock.t = 1.0
        h.append("m", {"s": "b"}, 2.0)
        clock.t = 2.0
        h.append("m", {"s": "a"}, 3.0)  # refresh a: b is now least recent
        clock.t = 3.0
        h.append("m", {"s": "c"}, 4.0)  # evicts b
        labels = {tuple(s["labels"].items()) for s in h.series_list()}
        assert labels == {(("s", "a"),), (("s", "c"),)}
        assert h.stats()["evicted"]["capacity"] == 1
        assert h.query_range("m", {"s": "b"}, start=0, end=1e9) == []

    def test_retention_gc_expires_idle_series(self):
        h, clock = make_store(retention_s=10.0)
        h.append("m", {"s": "old"}, 1.0)
        clock.t = 20.0
        h.append("m", {"s": "new"}, 2.0)  # append triggers GC
        assert [s["labels"] for s in h.series_list()] == [{"s": "new"}]
        assert h.stats()["evicted"]["retention"] == 1

    def test_eviction_mid_snapshot_never_caches_ghost_series(self):
        # Code-review PR1: with max_series below one family's size, an
        # eviction can claim a series created earlier in the SAME
        # append_snapshot walk. Caching that walk's layout would let later
        # fast-path polls feed ghost series — samples silently lost while
        # the eviction counter sits still. Invariants: the sample gauge
        # matches what is actually queryable, and evictions keep counting.
        from tpu_pod_exporter.metrics import SnapshotBuilder, schema

        def pod_snapshot(n):
            b = SnapshotBuilder()
            for i in range(n):
                b.add(schema.TPU_POD_CHIP_COUNT, 4.0,
                      (f"pod{i}", "ns", "acc", "s", "h", "0"))
            return b.build(timestamp=1000.0)

        h, clock = make_store(capacity=4, max_series=3)
        snap = pod_snapshot(5)
        for poll in range(3):
            clock.t = float(poll)
            h.append_snapshot(snap, now_mono=clock.t, now_wall=1000.0 + clock.t)
        st = h.stats()
        queryable = sum(s["samples"] for s in h.series_list())
        assert st["samples"] == queryable
        assert st["series"] == 3
        # every poll re-evicts (the cap is genuinely too small): the loss
        # stays visible in the counter instead of stopping after poll 1
        assert st["evicted"]["capacity"] >= 4

    def test_sample_accounting_survives_eviction(self):
        h, clock = make_store(capacity=4, max_series=1)
        for i in range(6):
            clock.t = float(i)
            h.append("m", {"s": "a"}, 1.0)
        h.append("m", {"s": "b"}, 1.0)  # evicts a (4 retained samples)
        st = h.stats()
        assert st["series"] == 1
        assert st["samples"] == 1


class TestWindowStats:
    def test_gauge_stats_and_null_rate(self):
        h, clock = make_store(capacity=8)
        for i, v in enumerate([5.0, 1.0, 3.0]):
            clock.t = float(i)
            h.append("tpu_hbm_used_bytes", {"chip_id": "0"}, v)
        [row] = h.window_stats("tpu_hbm_used_bytes", window_s=60.0)
        s = row["stats"]
        assert (s["min"], s["max"], s["first"], s["last"]) == (1.0, 5.0, 5.0, 3.0)
        assert s["mean"] == pytest.approx(3.0)
        assert s["samples"] == 3
        assert s["rate"] is None  # gauges never rate

    def test_counter_rate_tolerates_reset(self):
        # Raw counter resets mid-window (device reset): the negative delta
        # contributes nothing — same monotonic-fold semantics as the
        # collector's ICI/DCN counters.
        h, clock = make_store(capacity=8)
        for i, v in enumerate([0.0, 100.0, 200.0, 50.0, 150.0]):
            clock.t = float(i)
            h.append("tpu_ici_transferred_bytes_total",
                     {"link": "0"}, v)
        [row] = h.window_stats("tpu_ici_transferred_bytes_total", window_s=60.0)
        # positive deltas 100+100+100 over 4 s
        assert row["stats"]["rate"] == pytest.approx(300.0 / 4.0)

    def test_window_excludes_older_samples(self):
        h, clock = make_store(capacity=8)
        for i in range(5):
            clock.t = float(i) * 10.0
            h.append("m", {}, float(i))
        clock.t = 40.0
        [row] = h.window_stats("m", window_s=15.0)
        assert row["stats"]["samples"] == 2  # t=30 and t=40 only
        assert row["stats"]["first"] == 3.0

    def test_match_filters_series(self):
        h, _ = make_store()
        h.append("m", {"chip_id": "0"}, 1.0)
        h.append("m", {"chip_id": "1"}, 2.0)
        rows = h.window_stats("m", {"chip_id": "1"}, window_s=60.0)
        assert [r["labels"] for r in rows] == [{"chip_id": "1"}]


class TestQueryRange:
    def test_step_alignment_carries_last_sample_forward(self):
        h, clock = make_store(capacity=8)
        for i, v in enumerate([10.0, 20.0, 30.0]):
            clock.t = float(i)
            h.append("m", {}, v)  # wall times 1000, 1001, 1002
        [row] = h.query_range("m", start=1000.0, end=1004.0, step=1.0)
        # Each grid point takes the most recent sample at-or-before it;
        # the lookback (max(2*step, 10 s)) keeps 1003/1004 carrying 30.
        assert row["values"] == [
            [1000.0, 10.0], [1001.0, 20.0], [1002.0, 30.0],
            [1003.0, 30.0], [1004.0, 30.0],
        ]

    def test_left_edge_uses_sample_just_before_start(self):
        # Code-review PR1: a sample slightly OLDER than `start` must still
        # back the first grid points (it is the most recent sample at or
        # before them, within the lookback) — otherwise forensics queries
        # show a fake gap at the left edge of the incident window.
        h, clock = make_store(capacity=8)
        clock.t = -5.0
        h.append("m", {}, 42.0)  # wall time 995
        clock.t = 5.0
        h.append("m", {}, 43.0)  # wall time 1005
        [row] = h.query_range("m", start=1000.0, end=1010.0, step=5.0)
        assert row["values"] == [
            [1000.0, 42.0], [1005.0, 43.0], [1010.0, 43.0]
        ]

    def test_stale_series_does_not_project_past_lookback(self):
        h, _ = make_store(capacity=8)
        h.append("m", {}, 1.0)  # wall time 1000
        [row] = h.query_range("m", start=1000.0, end=1100.0, step=20.0)
        # lookback = 2*step = 40 s: grid points beyond 1040 are absent.
        assert [t for t, _v in row["values"]] == [1000.0, 1020.0, 1040.0]


@pytest.fixture
def history_server():
    h, clock = make_store(capacity=16)
    store = SnapshotStore()
    server = MetricsServer(store, host="127.0.0.1", port=0, history=h)
    server.start()
    yield h, clock, f"http://127.0.0.1:{server.port}"
    server.stop()


def get_json(url):
    try:
        resp = urllib.request.urlopen(url, timeout=5)
        return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


class TestApiEndpoints:
    def test_series_lists_label_sets(self, history_server):
        h, _, base = history_server
        h.append("tpu_hbm_used_bytes", {"chip_id": "0"}, 1.0)
        status, doc = get_json(base + "/api/v1/series")
        assert status == 200 and doc["status"] == "ok"
        assert doc["data"] == [
            {"metric": "tpu_hbm_used_bytes", "labels": {"chip_id": "0"},
             "samples": 1}
        ]

    def test_unknown_metric_is_clean_404_json(self, history_server):
        _, _, base = history_server
        for path in (
            "/api/v1/query_range?metric=tpu_nope",
            "/api/v1/window_stats?metric=tpu_nope",
        ):
            status, doc = get_json(base + path)
            assert status == 404
            assert doc["status"] == "error"
            assert "tpu_nope" in doc["error"]

    def test_empty_match_is_clean_404_json(self, history_server):
        h, _, base = history_server
        h.append("m", {"chip_id": "0"}, 1.0)
        status, doc = get_json(
            base + "/api/v1/query_range?metric=m&match[chip_id]=9"
        )
        assert status == 404 and doc["status"] == "error"

    def test_malformed_params_are_400_json(self, history_server):
        _, _, base = history_server
        cases = (
            "/api/v1/query_range",                      # missing metric
            "/api/v1/query_range?metric=m&start=abc",   # non-numeric
            "/api/v1/query_range?metric=m&step=-1",     # negative step
            "/api/v1/query_range?metric=m&start=9&end=1",  # inverted range
            "/api/v1/window_stats",                     # missing metric
            "/api/v1/window_stats?metric=m&window=0",   # non-positive window
            # grid-walk DoS guards (code-review PR1): a billion-point or
            # infinite grid must be refused before the store walks it
            "/api/v1/query_range?metric=m&start=0&step=1",   # ~1.7e9 points
            "/api/v1/query_range?metric=m&end=inf&step=1",   # infinite loop
            "/api/v1/query_range?metric=m&start=-inf",
            "/api/v1/query_range?metric=m&step=nan",
        )
        for path in cases:
            status, doc = get_json(base + path)
            assert status == 400, path
            assert doc["status"] == "error"

    def test_unknown_api_path_404(self, history_server):
        _, _, base = history_server
        status, doc = get_json(base + "/api/v1/nope")
        assert status == 404 and doc["status"] == "error"

    def test_api_concurrency_fence_429s_excess_queries(self):
        # Code-review PR1: /api/v1 sits outside the scrape fences but has
        # its own small cap — a query flood must 429, not pile handler
        # threads onto the history lock against the poll thread.
        h, _ = make_store(capacity=16)
        h.append("m", {}, 1.0)
        server = MetricsServer(SnapshotStore(), host="127.0.0.1", port=0,
                               history=h)
        server.start()
        base = f"http://127.0.0.1:{server.port}"
        handler = server._httpd.RequestHandlerClass
        assert handler.api_sem is not None
        assert handler.api_sem.acquire(timeout=1)
        assert handler.api_sem.acquire(timeout=1)  # both permits held
        try:
            status, doc = get_json(base + "/api/v1/series")
            assert status == 429
            assert "too many" in doc["error"]
            # the scrape/health surface is unaffected by the api fence
            assert urllib.request.urlopen(
                base + "/healthz", timeout=5
            ).status == 200
        finally:
            handler.api_sem.release()
            handler.api_sem.release()
            try:
                assert get_json(base + "/api/v1/series")[0] == 200
            finally:
                server.stop()

    def test_query_copies_are_outside_the_lock(self):
        # The under-lock phase of a query copies raw arrays only; the store
        # must remain appendable from another thread while a slow consumer
        # iterates the result (i.e. results don't alias live rings).
        h, clock = make_store(capacity=8)
        h.append("m", {}, 1.0)
        rows = h._rows_for("m", {})
        clock.t = 1.0
        h.append("m", {}, 2.0)  # mutates the live ring
        items = HistoryStore._row_items(rows[0])
        assert [v for (_tm, _tw, v) in items] == [1.0]  # snapshot, not alias

    def test_non_finite_samples_serialize_as_null(self, history_server):
        # Code-review PR1: backends can report NaN samples; bare NaN is not
        # JSON and breaks every strict parser mid-incident. The API maps
        # non-finite floats to null.
        h, clock, base = history_server
        h.append("m", {}, float("nan"))
        clock.t = 1.0
        h.append("m", {}, float("inf"))
        status, doc = get_json(base + "/api/v1/window_stats?metric=m&window=60")
        assert status == 200  # and json.loads above already proves validity
        s = doc["data"]["result"][0]["stats"]
        assert s["first"] is None and s["last"] is None
        assert s["samples"] == 2
        status, doc = get_json(
            base + "/api/v1/query_range?metric=m&start=0&end=2000"
        )
        assert status == 200
        assert [v for _t, v in doc["data"]["result"][0]["values"]] == [None, None]

    def test_api_404s_when_history_disabled(self):
        store = SnapshotStore()
        server = MetricsServer(store, host="127.0.0.1", port=0)  # no history
        server.start()
        try:
            status, doc = get_json(
                f"http://127.0.0.1:{server.port}/api/v1/series"
            )
            assert status == 404
            assert "history disabled" in doc["error"]
        finally:
            server.stop()


class TestCollectorIntegration:
    def _collector(self, history, chips=2):
        from tpu_pod_exporter.attribution.fake import (
            FakeAttribution,
            simple_allocation,
        )
        from tpu_pod_exporter.backend.fake import FakeBackend, FakeChipScript
        from tpu_pod_exporter.collector import Collector

        backend = FakeBackend(
            chips=chips,
            script=FakeChipScript(
                hbm_total_bytes=8e9,
                hbm_used_bytes=lambda step: 1e9 + step * 1e8,
                duty_cycle_percent=50.0,
                ici_bytes_per_step=1000.0,
            ),
        )
        attr = FakeAttribution(
            [simple_allocation("train", ["0", "1"], namespace="ml")]
        )
        return Collector(backend, attr, SnapshotStore(), history=history)

    def test_collector_feeds_tracked_families(self):
        h, _ = make_store(capacity=16, max_series=256)
        c = self._collector(h)
        c.poll_once()
        c.poll_once()
        metrics = {s["metric"] for s in h.series_list()}
        assert "tpu_hbm_used_bytes" in metrics
        assert "tpu_chip_info" in metrics
        assert "tpu_ici_transferred_bytes_total" in metrics
        assert "tpu_pod_chip_count" in metrics
        assert metrics <= HISTORY_TRACKED_METRICS
        [row] = h.query_range(
            "tpu_hbm_used_bytes", {"chip_id": "0"}, start=0, end=1e12
        )
        assert [v for _t, v in row["values"]] == [1e9, 1.1e9]

    def test_history_self_metrics_reach_exposition(self):
        h, _ = make_store(capacity=16, max_series=256)
        c = self._collector(h)
        c.poll_once()
        c.poll_once()
        text = c._store.current().encode().decode()
        assert "tpu_exporter_history_series " in text
        assert 'tpu_exporter_history_evicted_series_total{reason="capacity"} 0' in text
        assert "tpu_exporter_history_append_seconds " in text
        # size gauges lag one poll (append runs after the swap) but after
        # two polls they must be nonzero
        line = next(
            l for l in text.splitlines()
            if l.startswith("tpu_exporter_history_samples ")
        )
        assert float(line.split()[-1]) > 0

    def test_query_range_over_http_after_two_polls(self):
        """Acceptance: >= 2 correctly timestamped samples for a chip HBM
        series after two fake-backend polls, via the real HTTP endpoint."""
        import time

        h = HistoryStore(capacity=16, max_series=256, retention_s=300.0)
        c = self._collector(h)
        t0 = time.time()
        c.poll_once()
        c.poll_once()
        t1 = time.time()
        server = MetricsServer(
            SnapshotStore(), host="127.0.0.1", port=0, history=h
        )
        server.start()
        try:
            base = f"http://127.0.0.1:{server.port}"
            status, doc = get_json(
                base + "/api/v1/query_range?metric=tpu_hbm_used_bytes"
                f"&match[chip_id]=0&start={t0 - 1}&end={t1 + 1}"
            )
            assert status == 200
            [row] = doc["data"]["result"]
            assert row["labels"]["chip_id"] == "0"
            assert row["labels"]["pod"] == "train"
            values = row["values"]
            assert len(values) >= 2
            for ts, _v in values:
                assert t0 - 1 <= ts <= t1 + 1
            assert [v for _t, v in values] == [1e9, 1.1e9]
        finally:
            server.stop()

    def test_history_disabled_costs_nothing(self):
        c = self._collector(None)
        c.poll_once()
        text = c._store.current().encode().decode()
        assert "tpu_exporter_history_series" not in text


class TestExporterAppWiring:
    def test_app_builds_history_and_serves_api(self):
        from tpu_pod_exporter.app import ExporterApp
        from tpu_pod_exporter.attribution.fake import FakeAttribution
        from tpu_pod_exporter.backend.fake import FakeBackend
        from tpu_pod_exporter.config import ExporterConfig

        app = ExporterApp(
            ExporterConfig(port=0, host="127.0.0.1", interval_s=30.0,
                           backend="fake", fake_chips=1, attribution="none"),
            backend=FakeBackend(chips=1), attribution=FakeAttribution(),
        )
        app.start()
        try:
            base = f"http://127.0.0.1:{app.port}"
            status, doc = get_json(base + "/api/v1/series")
            assert status == 200
            assert any(
                s["metric"] == "tpu_chip_info" for s in doc["data"]
            )
        finally:
            app.stop()

    def test_retention_zero_disables_history(self):
        from tpu_pod_exporter.app import ExporterApp
        from tpu_pod_exporter.attribution.fake import FakeAttribution
        from tpu_pod_exporter.backend.fake import FakeBackend
        from tpu_pod_exporter.config import ExporterConfig

        app = ExporterApp(
            ExporterConfig(port=0, host="127.0.0.1", backend="fake",
                           attribution="none", history_retention_s=0.0),
            backend=FakeBackend(chips=0), attribution=FakeAttribution(),
        )
        assert app.history is None


class TestAggregatorFallback:
    HOST_BODY = (
        "# HELP tpu_chip_info x\n"
        "# TYPE tpu_chip_info gauge\n"
        'tpu_chip_info{chip_id="0",device_path="",accelerator="v5p-8",'
        'slice_name="s",host="h0",worker_id="0",pod="",namespace="",'
        'container="",device_kind="",coords=""} 1\n'
        'tpu_hbm_used_bytes{chip_id="0",device_path="",accelerator="v5p-8",'
        'slice_name="s",host="h0",worker_id="0",pod="",namespace="",'
        'container=""} 100\n'
    )

    @staticmethod
    def _hist_fetch(url, timeout_s):
        labels = {"chip_id": "0", "host": "h1", "slice_name": "s",
                  "accelerator": "v5p-8"}
        if "tpu_chip_info" in url:
            return {"data": {"result": [
                {"labels": labels, "stats": {"last": 1.0, "rate": None}}
            ]}}
        if "tpu_hbm_used_bytes" in url:
            return {"data": {"result": [
                {"labels": labels, "stats": {"last": 77.0, "rate": None}}
            ]}}
        if "tpu_ici_transferred_bytes_total" in url:
            return {"data": {"result": [
                {"labels": {**labels, "link": "0"},
                 "stats": {"last": 1e6, "rate": 1234.0}}
            ]}}
        if "tpu_pod_chip_count" in url:
            return {"data": {"result": [
                {"labels": {"pod": "train", "namespace": "ml",
                            "slice_name": "s", "host": "h1"},
                 "stats": {"last": 4.0, "rate": None}}
            ]}}
        raise urllib.error.HTTPError(url, 404, "no samples", None, None)

    def _aggregate(self, history_fetch, window=15.0):
        from tpu_pod_exporter.aggregate import SliceAggregator

        def fetch(target, timeout_s):
            if target == "h1:8000":
                raise ConnectionError("down")
            return self.HOST_BODY

        store = SnapshotStore()
        agg = SliceAggregator(
            ("h0:8000", "h1:8000"), store, fetch=fetch,
            history_fallback_window_s=window, history_fetch=history_fetch,
        )
        try:
            agg.poll_once()
        finally:
            agg.close()
        return store.current()

    def test_missed_round_keeps_slice_continuity(self):
        snap = self._aggregate(self._hist_fetch)
        key = ("s", "v5p-8", "tpu")
        # h1's chips stay in the rollups via its flight recorder...
        assert snap.value("tpu_slice_hosts_reporting", key) == 2.0
        assert snap.value("tpu_slice_chip_count", key) == 2.0
        assert snap.value("tpu_slice_hbm_used_bytes", key) == 177.0
        # ...counter history contributes its window rate as bandwidth...
        assert snap.value("tpu_slice_ici_bytes_per_second", key) == 1234.0
        # ...and workload rollups stay continuous too, not just slice ones
        assert snap.value(
            "tpu_workload_chip_count", ("train", "ml", "s")
        ) == 4.0
        # ...but the target still honestly reports down, and the
        # substitution is counted.
        assert snap.value("tpu_aggregator_target_up", ("h1:8000",)) == 0.0
        assert snap.value(
            "tpu_aggregator_history_fallbacks_total", ("h1:8000",)
        ) == 1.0

    def test_fallback_failure_degrades_to_plain_miss(self):
        def dead(url, timeout_s):
            raise ConnectionError("history down too")

        snap = self._aggregate(dead)
        key = ("s", "v5p-8", "tpu")
        assert snap.value("tpu_slice_hosts_reporting", key) == 1.0
        assert snap.value("tpu_slice_chip_count", key) == 1.0
        assert snap.value(
            "tpu_aggregator_history_fallbacks_total", ("h1:8000",)
        ) is None

    def test_connection_failure_aborts_after_one_fetch(self):
        # Code-review PR1: a black-holed target must cost ONE history
        # timeout, not six — the fallback bails on the first
        # connection-level failure instead of probing every metric.
        calls = []

        def dead(url, timeout_s):
            calls.append(url)
            raise ConnectionError("black hole")

        self._aggregate(dead)
        assert len(calls) == 1

    def test_http_404_keeps_probing_remaining_metrics(self):
        # ...while an ANSWERED 404 (family has no samples) is cheap and the
        # loop keeps going: partial history beats none.
        calls = []

        def sparse(url, timeout_s):
            calls.append(url)
            if "tpu_hbm_used_bytes" in url:
                return self._hist_fetch(url, timeout_s)
            raise urllib.error.HTTPError(url, 404, "no samples", None, None)

        snap = self._aggregate(sparse)
        assert len(calls) == 8  # every TPU fallback metric probed (gpu_* probes are gated on the target having ever served a gpu_ family)
        key = ("s", "v5p-8", "tpu")
        assert snap.value("tpu_slice_hbm_used_bytes", key) == 177.0

    def test_disabled_by_default(self):
        from tpu_pod_exporter.aggregate import SliceAggregator

        def fetch(target, timeout_s):
            raise ConnectionError("down")

        def exploding(url, timeout_s):  # must never be called when off
            raise AssertionError("history fetch called with window=0")

        store = SnapshotStore()
        agg = SliceAggregator(("h1:8000",), store, fetch=fetch,
                              history_fetch=exploding)
        try:
            agg.poll_once()
        finally:
            agg.close()
        assert store.current().value(
            "tpu_aggregator_target_up", ("h1:8000",)
        ) == 0.0

    def test_aggregator_cli_has_debug_addr_flag(self):
        # Code-review PR1: the loopback-only /debug/* default applies to
        # the aggregator's server too, so its CLI must expose the same
        # escape hatch as the exporter.
        import subprocess
        import sys

        out = subprocess.run(
            [sys.executable, "-m", "tpu_pod_exporter.aggregate", "--help"],
            capture_output=True, text=True, timeout=60,
        )
        assert out.returncode == 0
        assert "--debug-addr" in out.stdout

    def test_target_base_url(self):
        from tpu_pod_exporter.aggregate import target_base_url

        assert target_base_url("h0:8000") == "http://h0:8000"
        assert target_base_url("http://h0:8000/metrics") == "http://h0:8000"
        assert target_base_url("https://h0:9000") == "https://h0:9000"


class TestStatusWatchTrends:
    def test_trend_cell_arrows(self):
        from tpu_pod_exporter.status import _fmt_delta_bytes, trend_cell

        h, clock = make_store(capacity=16)
        assert trend_cell(h, "tpu_hbm_used_bytes", 0, 60.0,
                          _fmt_delta_bytes, 1.0) == "-"
        h.append("tpu_hbm_used_bytes", {"chip_id": "0"}, 1024.0**3)
        assert trend_cell(h, "tpu_hbm_used_bytes", 0, 60.0,
                          _fmt_delta_bytes, 1.0) == "-"  # one sample: no delta
        clock.t = 1.0
        h.append("tpu_hbm_used_bytes", {"chip_id": "0"}, 3 * 1024.0**3)
        cell = trend_cell(h, "tpu_hbm_used_bytes", 0, 60.0,
                          _fmt_delta_bytes, 1024.0**2)
        assert cell == "↑+2.0GiB"
        clock.t = 2.0
        h.append("tpu_hbm_used_bytes", {"chip_id": "0"}, 1024.0**3)
        cell = trend_cell(h, "tpu_hbm_used_bytes", 0, 60.0,
                          _fmt_delta_bytes, 1024.0**2)
        assert cell.startswith("→")  # net zero over the window

    def test_watch_table_includes_delta_columns(self, capsys):
        from tpu_pod_exporter.attribution.fake import FakeAttribution
        from tpu_pod_exporter.backend.fake import FakeBackend, FakeChipScript
        from tpu_pod_exporter.config import ExporterConfig
        from tpu_pod_exporter.status import _run
        from tpu_pod_exporter.topology import detect_host_topology

        backend = FakeBackend(
            chips=1,
            script=FakeChipScript(
                hbm_total_bytes=8e9,
                hbm_used_bytes=lambda step: 1e9 * (step + 1),
                duty_cycle_percent=lambda step: 10.0 * (step + 1),
            ),
        )
        h, _ = make_store(capacity=16)
        cfg = ExporterConfig()
        topo = detect_host_topology()
        for _ in range(2):
            rc = _run(cfg, topo, backend, FakeAttribution(),
                      history=h, trend_window_s=60.0)
            assert rc == 0
        out = capsys.readouterr().out
        assert "Δhbm" in out and "Δduty" in out
        assert "↑" in out


class TestDebugLoopbackPolicy:
    def test_policy_function(self):
        assert debug_client_allowed("127.0.0.1", "127.0.0.1")
        assert debug_client_allowed("::1", "127.0.0.1")
        assert debug_client_allowed("::ffff:127.0.0.1", "127.0.0.1")
        assert not debug_client_allowed("10.0.0.5", "127.0.0.1")
        assert not debug_client_allowed("10.0.0.5", "")
        # explicit opt-in restores remote debug reads
        assert debug_client_allowed("10.0.0.5", "0.0.0.0")
        assert debug_client_allowed("10.0.0.5", "*")
        # loopback can never lock itself out
        assert debug_client_allowed("127.0.0.1", "0.0.0.0")

    def test_loopback_client_still_served(self):
        store = SnapshotStore()
        server = MetricsServer(store, host="127.0.0.1", port=0,
                               debug_vars=lambda: {"ok": True})
        server.start()
        try:
            base = f"http://127.0.0.1:{server.port}"
            status, doc = get_json(base + "/debug/vars")
            assert status == 200 and doc == {"ok": True}
            resp = urllib.request.urlopen(base + "/debug/stacks", timeout=5)
            assert resp.status == 200
        finally:
            server.stop()


class TestHistoryDemo:
    def test_replay_demo_runs_on_r5_fixture(self, capsys):
        from tpu_pod_exporter.history import main

        rc = main(["--replay", "tests/fixtures/real-trace-r5.jsonl",
                   "--polls", "10"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "replayed 10 polls" in out
        # The r5 hardware serves no HBM (absent-beats-fake-zero), so chip
        # presence is the recorded story.
        assert "tpu_chip_info" in out
