"""Collector unit/behavior tests: join, labels, GC, faults, rates.

Covers the reference-defect inversions (SURVEY.md §2.6): correct device-ID
join, per-chip labels, stale-series GC, error containment.
"""

import pytest

from tpu_pod_exporter.attribution.fake import FakeAttribution, simple_allocation
from tpu_pod_exporter.backend.fake import FakeBackend, FakeChipScript
from tpu_pod_exporter.collector import Collector, PollStats
from tpu_pod_exporter.metrics import SnapshotStore
from tpu_pod_exporter.topology import HostTopology


def make_collector(backend, attribution, store, **kw):
    topo = HostTopology(
        accelerator="v4-8", slice_name="s0", host="host0", worker_id="0"
    )
    return Collector(backend, attribution, store, topology=topo, **kw)


def chip_labels(chip_id, pod="", namespace="", container="", device_path=None):
    return {
        "chip_id": str(chip_id),
        "device_path": device_path if device_path is not None else f"/dev/accel{chip_id}",
        "accelerator": "v4-8",
        "slice_name": "s0",
        "host": "host0",
        "worker_id": "0",
        "pod": pod,
        "namespace": namespace,
        "container": container,
    }


class TestJoin:
    def test_attributed_chip_carries_pod_labels(self, store, four_chip_backend, one_pod_attribution):
        c = make_collector(four_chip_backend, one_pod_attribution, store)
        c.poll_once()
        snap = store.current()
        labels = chip_labels(0, pod="train-job-0", namespace="ml", container="main")
        assert snap.value("tpu_hbm_used_bytes", labels) == 4 * 1024**3
        assert snap.value("tpu_hbm_total_bytes", labels) == 32 * 1024**3
        assert snap.value("tpu_hbm_used_percent", labels) == 12.5
        assert snap.value("tpu_tensorcore_duty_cycle_percent", labels) == 50.0

    def test_unallocated_chip_has_empty_pod_labels(self, store, four_chip_backend):
        c = make_collector(four_chip_backend, FakeAttribution(), store)
        c.poll_once()
        snap = store.current()
        assert snap.value("tpu_hbm_used_bytes", chip_labels(2)) == 4 * 1024**3

    def test_multi_pod_partition(self, store, four_chip_backend):
        attr = FakeAttribution(
            [
                simple_allocation("pod-a", ["0", "1"], namespace="ns1"),
                simple_allocation("pod-b", ["2", "3"], namespace="ns2", container="c2"),
            ]
        )
        c = make_collector(four_chip_backend, attr, store)
        c.poll_once()
        snap = store.current()
        assert (
            snap.value("tpu_hbm_used_bytes", chip_labels(1, "pod-a", "ns1", "main"))
            is not None
        )
        assert (
            snap.value("tpu_hbm_used_bytes", chip_labels(3, "pod-b", "ns2", "c2"))
            is not None
        )

    def test_same_pod_name_different_namespace_do_not_collide(self, store):
        # The reference keys by pod name only (main.go:113) — namespaces collide.
        backend = FakeBackend(chips=2)
        attr = FakeAttribution(
            [
                simple_allocation("job", ["0"], namespace="alpha"),
                simple_allocation("job", ["1"], namespace="beta"),
            ]
        )
        c = make_collector(backend, attr, store)
        c.poll_once()
        snap = store.current()
        assert snap.value("tpu_hbm_used_bytes", chip_labels(0, "job", "alpha", "main")) is not None
        assert snap.value("tpu_hbm_used_bytes", chip_labels(1, "job", "beta", "main")) is not None

    def test_pod_rollups(self, store, four_chip_backend, one_pod_attribution):
        c = make_collector(four_chip_backend, one_pod_attribution, store)
        c.poll_once()
        snap = store.current()
        rollup = {
            "pod": "train-job-0",
            "namespace": "ml",
            "accelerator": "v4-8",
            "slice_name": "s0",
            "host": "host0",
            "worker_id": "0",
        }
        assert snap.value("tpu_pod_chip_count", rollup) == 4
        assert snap.value("tpu_pod_hbm_used_bytes", rollup) == 4 * 4 * 1024**3


class TestKubeletInventory:
    def test_allocatable_and_allocated_gauges(self, store, four_chip_backend):
        attr = FakeAttribution(
            [simple_allocation("p", ["0", "1"])],
            allocatable=["0", "1", "2", "3"],
        )
        c = make_collector(four_chip_backend, attr, store)
        c.poll_once()
        snap = store.current()
        topo = ("v4-8", "s0", "host0", "0")
        assert snap.value("tpu_kubelet_allocatable_chips", topo) == 4
        assert snap.value("tpu_kubelet_allocated_chips", topo) == 2

    def test_idle_node_with_inventory_reports_zero_allocated(
        self, store, four_chip_backend
    ):
        attr = FakeAttribution([], allocatable=["0", "1", "2", "3"])
        c = make_collector(four_chip_backend, attr, store)
        c.poll_once()
        snap = store.current()
        topo = ("v4-8", "s0", "host0", "0")
        assert snap.value("tpu_kubelet_allocatable_chips", topo) == 4
        # 0 is real data (alertable), not absence
        assert snap.value("tpu_kubelet_allocated_chips", topo) == 0

    def test_inventory_survives_pod_churn(self, store, four_chip_backend):
        attr = FakeAttribution(
            [simple_allocation("p", ["0"])], allocatable=["0", "1", "2", "3"]
        )
        c = make_collector(four_chip_backend, attr, store)
        c.poll_once()
        attr.set_allocations([])  # pod exits; kubelet inventory unchanged
        c.poll_once()
        snap = store.current()
        topo = ("v4-8", "s0", "host0", "0")
        assert snap.value("tpu_kubelet_allocatable_chips", topo) == 4
        assert snap.value("tpu_kubelet_allocated_chips", topo) == 0

    def test_absent_when_source_cannot_report(self, store, four_chip_backend):
        c = make_collector(four_chip_backend, FakeAttribution(), store)
        c.poll_once()
        snap = store.current()
        assert snap.samples("tpu_kubelet_allocatable_chips") == {}
        assert snap.samples("tpu_kubelet_allocated_chips") == {}


class TestLegacyMetrics:
    def test_disabled_by_default(self, store, four_chip_backend, one_pod_attribution):
        c = make_collector(four_chip_backend, one_pod_attribution, store)
        c.poll_once()
        text = store.current().encode()
        assert b"pod_gpu_memory_usage" not in text

    def test_reference_names_emitted_when_enabled(
        self, store, four_chip_backend, one_pod_attribution
    ):
        c = make_collector(
            four_chip_backend, one_pod_attribution, store, legacy_metrics=True
        )
        c.poll_once()
        snap = store.current()
        # per-pod sum over 4 chips × 4 GiB, pid always ""
        assert snap.value("pod_gpu_memory_usage", ("", "train-job-0")) == 16 * 1024**3
        assert snap.value("docker_gpu_memory_perc_usage", ("", "train-job-0")) == 12.5
        assert b"DEPRECATED" in snap.encode()

    def test_same_name_pods_sum_across_namespaces(self, store):
        backend = FakeBackend(
            chips=2, script=FakeChipScript(hbm_total_bytes=100.0, hbm_used_bytes=10.0)
        )
        attr = FakeAttribution(
            [
                simple_allocation("job", ["0"], namespace="alpha"),
                simple_allocation("job", ["1"], namespace="beta"),
            ]
        )
        c = make_collector(backend, attr, store, legacy_metrics=True)
        c.poll_once()
        assert store.current().value("pod_gpu_memory_usage", ("", "job")) == 20.0


class TestSeriesLifecycle:
    def test_stale_series_gone_after_pod_exit(self, store, four_chip_backend):
        attr = FakeAttribution([simple_allocation("ephemeral", ["0", "1", "2", "3"])])
        c = make_collector(four_chip_backend, attr, store)
        c.poll_once()
        assert (
            store.current().value(
                "tpu_hbm_used_bytes", chip_labels(0, "ephemeral", "default", "main")
            )
            is not None
        )
        attr.set_allocations([])  # pod deleted
        c.poll_once()
        snap = store.current()
        assert (
            snap.value("tpu_hbm_used_bytes", chip_labels(0, "ephemeral", "default", "main"))
            is None
        )
        # chip series still exists, unattributed
        assert snap.value("tpu_hbm_used_bytes", chip_labels(0)) is not None

    def test_reassignment_single_owner_per_chip(self, store, four_chip_backend):
        attr = FakeAttribution([simple_allocation("a", ["0"])])
        c = make_collector(four_chip_backend, attr, store)
        c.poll_once()
        attr.set_allocations([simple_allocation("b", ["0"])])
        c.poll_once()
        snap = store.current()
        assert snap.value("tpu_hbm_used_bytes", chip_labels(0, "b", "default", "main")) is not None
        assert snap.value("tpu_hbm_used_bytes", chip_labels(0, "a", "default", "main")) is None
        # exactly 4 hbm_used series (one per chip)
        assert len(snap.samples("tpu_hbm_used_bytes")) == 4


class TestFaultContainment:
    def test_backend_failure_degrades_not_dies(self, store, four_chip_backend, one_pod_attribution):
        c = make_collector(four_chip_backend, one_pod_attribution, store)
        c.poll_once()
        four_chip_backend.fail_next(1)
        stats = c.poll_once()
        assert not stats.ok
        snap = store.current()
        assert snap.value("tpu_exporter_up") == 0
        assert snap.value("tpu_exporter_poll_errors_total", ("device_read",)) == 1
        # recovery
        stats = c.poll_once()
        assert stats.ok
        assert store.current().value("tpu_exporter_up") == 1

    def test_attribution_failure_uses_last_good_within_staleness(
        self, store, four_chip_backend, one_pod_attribution
    ):
        c = make_collector(
            four_chip_backend, one_pod_attribution, store, attribution_max_stale_s=1e9
        )
        c.poll_once()
        one_pod_attribution.fail_next(1)
        c.poll_once()
        snap = store.current()
        # stale-but-recent attribution still applied
        assert (
            snap.value(
                "tpu_hbm_used_bytes", chip_labels(0, "train-job-0", "ml", "main")
            )
            is not None
        )
        assert snap.value("tpu_exporter_poll_errors_total", ("attribution",)) == 1

    def test_attribution_failure_beyond_staleness_drops_labels(
        self, store, four_chip_backend, one_pod_attribution
    ):
        fake_now = [0.0]
        c = make_collector(
            four_chip_backend,
            one_pod_attribution,
            store,
            attribution_max_stale_s=5.0,
            clock=lambda: fake_now[0],
        )
        c.poll_once()
        fake_now[0] += 10.0
        one_pod_attribution.fail_next(1)
        c.poll_once()
        snap = store.current()
        assert snap.value("tpu_hbm_used_bytes", chip_labels(0)) is not None

    def test_unexpected_exception_contained(self, store):
        class ExplodingBackend(FakeBackend):
            def sample(self):
                raise RuntimeError("not a BackendError")

        c = make_collector(ExplodingBackend(chips=1), FakeAttribution(), store)
        stats = c.poll_once()
        assert not stats.ok
        assert store.current().value("tpu_exporter_up") == 0

    def test_partial_errors_counted(self, store, four_chip_backend):
        four_chip_backend.set_partial_errors(["chip 3 flaky"])
        c = make_collector(four_chip_backend, FakeAttribution(), store)
        stats = c.poll_once()
        assert stats.ok  # partial errors degrade, not fail
        assert (
            store.current().value("tpu_exporter_poll_errors_total", ("device_partial",))
            == 1
        )


class TestIciRates:
    def test_counter_monotonic_and_rate(self, store):
        script = FakeChipScript(ici_link_count=2, ici_bytes_per_step=500.0)
        backend = FakeBackend(chips=1, script=script)
        fake_now = [0.0]

        def clock():
            return fake_now[0]

        c = make_collector(backend, FakeAttribution(), store, clock=clock)
        c.poll_once()
        labels = {**chip_labels(0), "link": "0"}
        snap = store.current()
        assert snap.value("tpu_ici_transferred_bytes_total", labels) == 500.0
        # no rate on first poll (no dt)
        assert snap.value("tpu_ici_link_bandwidth_bytes_per_second", labels) is None
        fake_now[0] += 2.0
        c.poll_once()
        snap = store.current()
        assert snap.value("tpu_ici_transferred_bytes_total", labels) == 1000.0
        assert snap.value("tpu_ici_link_bandwidth_bytes_per_second", labels) == 250.0

    def test_dcn_counter_and_rate(self, store):
        # DCN (cross-slice fabric) rides the same fold semantics as ICI:
        # monotonic counter, rate only from the second sampled poll.
        script = FakeChipScript(
            ici_link_count=1, ici_bytes_per_step=500.0,
            dcn_link_count=2, dcn_bytes_per_step=100.0,
        )
        backend = FakeBackend(chips=1, script=script)
        fake_now = [0.0]
        c = make_collector(backend, FakeAttribution(), store,
                           clock=lambda: fake_now[0])
        c.poll_once()
        labels = {**chip_labels(0), "link": "dcn0"}
        snap = store.current()
        assert snap.value("tpu_dcn_transferred_bytes_total", labels) == 100.0
        assert snap.value("tpu_dcn_link_bandwidth_bytes_per_second", labels) is None
        fake_now[0] += 2.0
        c.poll_once()
        snap = store.current()
        assert snap.value("tpu_dcn_transferred_bytes_total", labels) == 200.0
        assert snap.value("tpu_dcn_link_bandwidth_bytes_per_second", labels) == 50.0
        # ICI and DCN coexist without cross-talk.
        assert snap.value(
            "tpu_ici_transferred_bytes_total", {**chip_labels(0), "link": "0"}
        ) == 1000.0

    def test_no_dcn_series_without_dcn_links(self, store, four_chip_backend):
        c = make_collector(four_chip_backend, FakeAttribution(), store)
        c.poll_once()
        c.poll_once()
        text = store.current().encode().decode()
        assert "tpu_dcn_transferred_bytes_total{" not in text

    def test_dcn_counter_monotonic_across_device_reset(self, store):
        steps = iter([1000.0, 2000.0, 50.0, 150.0])  # reset after poll 2

        class ResettingScript(FakeChipScript):
            def sample(self, info, step, link_cache=None):
                s = super().sample(info, step, link_cache)
                total = next(steps)
                from tpu_pod_exporter.backend import IciLinkSample
                return s._replace(
                    dcn_links=(IciLinkSample("dcn0", total),)
                )

        backend = FakeBackend(chips=1, script=ResettingScript())
        c = make_collector(backend, FakeAttribution(), store)
        labels = {**chip_labels(0), "link": "dcn0"}
        vals = []
        for _ in range(4):
            c.poll_once()
            vals.append(
                store.current().value("tpu_dcn_transferred_bytes_total", labels)
            )
        assert vals == [1000.0, 2000.0, 2000.0, 2100.0]  # holds over the reset

    def test_counter_state_survives_failed_poll(self, store):
        """A transient device-read failure must not wipe ICI counter state —
        otherwise the exported counter regresses to the raw value on
        recovery (spurious rate() spike in Prometheus)."""
        script = FakeChipScript(ici_link_count=1, ici_bytes_per_step=100.0)
        backend = FakeBackend(chips=1, script=script)
        c = make_collector(backend, FakeAttribution(), store)
        labels = {**chip_labels(0), "link": "0"}
        c.poll_once()  # total=100 (step 0 → (0+1)*100)
        c.poll_once()  # total=200
        assert store.current().value("tpu_ici_transferred_bytes_total", labels) == 200.0
        backend.fail_next(1)
        c.poll_once()  # failed poll: no ICI series this snapshot
        assert store.current().value("tpu_ici_transferred_bytes_total", labels) is None
        c.poll_once()  # recovery: counter resumes monotonically, no regression
        assert store.current().value("tpu_ici_transferred_bytes_total", labels) >= 200.0

    def test_rate_survives_pod_relabel(self, store):
        # Chip moves pod-a -> pod-b between polls; counter state is keyed by
        # full label set, so the new series starts fresh but stays monotonic.
        script = FakeChipScript(ici_link_count=1, ici_bytes_per_step=100.0)
        backend = FakeBackend(chips=1, script=script)
        attr = FakeAttribution([simple_allocation("a", ["0"])])
        fake_now = [0.0]
        c = make_collector(backend, attr, store, clock=lambda: fake_now[0])
        c.poll_once()
        fake_now[0] += 1.0
        attr.set_allocations([simple_allocation("b", ["0"])])
        c.poll_once()
        labels_b = {**chip_labels(0, "b", "default", "main"), "link": "0"}
        assert store.current().value("tpu_ici_transferred_bytes_total", labels_b) == 200.0


class TestSelfMetrics:
    def test_poll_phases_and_counts(self, store, four_chip_backend, one_pod_attribution):
        c = make_collector(four_chip_backend, one_pod_attribution, store)
        c.poll_once()
        c.poll_once()
        snap = store.current()
        assert snap.value("tpu_exporter_polls_total") == 2
        assert snap.value("tpu_exporter_series") == snap.series_count
        for phase in ("device_read", "attribution", "join", "publish", "total"):
            assert snap.value("tpu_exporter_poll_duration_seconds", (phase,)) is not None
        info = snap.samples("tpu_exporter_info")
        assert len(info) == 1
        (values,) = info.keys()
        assert values[1] == "fake" and values[2] == "fake"

    def test_zero_devices_smoke(self, store):
        # Baseline config 1: no devices at all, exporter healthy.
        c = make_collector(FakeBackend(chips=0), FakeAttribution(), store)
        stats = c.poll_once()
        assert stats.ok
        snap = store.current()
        assert snap.value("tpu_exporter_up") == 1
        assert snap.samples("tpu_hbm_used_bytes") == {}
        # families still declared for a stable scrape surface
        assert b"# TYPE tpu_hbm_used_bytes gauge" in snap.encode()


class TestTelemetryDepth:
    def test_peak_hbm_and_chip_info(self, store):
        from tpu_pod_exporter.backend import ChipInfo

        infos = [
            ChipInfo(chip_id=0, device_path="/dev/accel0",
                     device_kind="TPU v5p", coords="0,0,0"),
            ChipInfo(chip_id=1, device_path="/dev/accel1",
                     device_kind="TPU v5p", coords="1,0,0"),
        ]
        script = FakeChipScript(
            hbm_total_bytes=100.0, hbm_used_bytes=10.0, hbm_peak_bytes=55.0
        )
        c = make_collector(FakeBackend(chips=infos, script=script),
                           FakeAttribution(), store)
        c.poll_once()
        snap = store.current()
        assert snap.value("tpu_hbm_peak_bytes", chip_labels(0)) == 55.0
        info_labels = dict(chip_labels(1), device_kind="TPU v5p", coords="1,0,0")
        assert snap.value("tpu_chip_info", info_labels) == 1.0

    def test_peak_absent_when_unknown_but_info_always_present(
        self, store, four_chip_backend
    ):
        c = make_collector(four_chip_backend, FakeAttribution(), store)
        c.poll_once()
        text = store.current().encode().decode()
        # Peak family declared (stable surface) but no samples.
        assert "# TYPE tpu_hbm_peak_bytes gauge" in text
        assert "\ntpu_hbm_peak_bytes{" not in text
        # chip_info, by contrast, is the guaranteed per-chip presence
        # series (round 4: tpu_hbm_* became omissible, so the aggregator
        # counts chips from chip_info) — published even with empty
        # kind/coords labels.
        assert text.count("\ntpu_chip_info{") == 4
        assert 'device_kind="",coords=""' in text

    def test_self_usage_metrics(self, store, four_chip_backend):
        import sys

        c = make_collector(four_chip_backend, FakeAttribution(), store)
        c.poll_once()
        snap = store.current()
        cpu1 = snap.value("tpu_exporter_cpu_seconds_total")
        rss = snap.value("tpu_exporter_rss_bytes")
        if sys.platform == "linux":
            # Documented absence behavior applies only off-Linux.
            assert cpu1 is not None and cpu1 > 0
            assert rss is not None and rss > 1024 * 1024  # a real process RSS
        if cpu1 is not None:
            c.poll_once()
            assert store.current().value("tpu_exporter_cpu_seconds_total") >= cpu1

    def test_peak_round_trips_through_recording(self, tmp_path, store):
        from tpu_pod_exporter.backend import ChipInfo
        from tpu_pod_exporter.backend.recorded import RecordedBackend, RecordingBackend

        infos = [ChipInfo(chip_id=0, device_kind="TPU v4", coords="0,1,2")]
        script = FakeChipScript(hbm_total_bytes=10.0, hbm_used_bytes=2.0,
                                hbm_peak_bytes=7.0)
        path = str(tmp_path / "t.jsonl")
        rec = RecordingBackend(FakeBackend(chips=infos, script=script), path)
        rec.sample()
        rec.close()
        replay = RecordedBackend(path)
        chip = replay.sample().chips[0]
        assert chip.hbm_peak_bytes == 7.0
        assert chip.info.device_kind == "TPU v4"
        assert chip.info.coords == "0,1,2"


class TestSideChannelErrorNamespacing:
    def test_provider_source_names_cannot_clobber_phase_counters(self, store):
        """ADVICE r2 #3: side-channel error counters are published with
        b.add (overwrite); a provider returning a source named like a poll
        phase ("attribution") must not replace the phase series."""

        class CollidingAttribution(FakeAttribution):
            def error_counters(self):
                return {"attribution": 99.0}

        backend = FakeBackend(chips=1)
        attr = CollidingAttribution()
        attr.fail_next(1)  # one real attribution-phase error
        c = make_collector(backend, attr, store)
        c.poll_once()
        snap = store.current()
        # The phase counter survives with its own count...
        assert snap.value(
            "tpu_exporter_poll_errors_total", {"source": "attribution"}
        ) == 1.0
        # ...and the provider's counter appears under its namespaced name.
        assert snap.value(
            "tpu_exporter_poll_errors_total", {"source": "attribution.attribution"}
        ) == 99.0


class TestPodRollupHonesty:
    """Code-review r4: pod/legacy rollups must not fold unreadable (None)
    HBM as 0 — same absent-beats-fake-zero rule as the per-chip series."""

    def _none_hbm_backend(self, chips=2):
        from tpu_pod_exporter.backend import ChipInfo, ChipSample, HostSample

        class NoHbmBackend(FakeBackend):
            def sample(self):
                return HostSample(chips=tuple(
                    ChipSample(
                        info=ChipInfo(chip_id=i, device_path=f"/dev/accel{i}",
                                      device_ids=(str(i),)),
                        hbm_used_bytes=None, hbm_total_bytes=None,
                    ) for i in range(chips)
                ))

        return NoHbmBackend(chips=0)

    def test_fully_unreadable_pod_omits_hbm_series_keeps_chip_count(self, store, one_pod_attribution):
        c = make_collector(self._none_hbm_backend(), one_pod_attribution, store)
        c.poll_once()
        text = store.current().encode().decode()
        assert "tpu_pod_chip_count{" in text
        assert "tpu_pod_hbm_used_bytes{" not in text

    def test_fully_unreadable_pod_emits_no_legacy_series(self, store, one_pod_attribution):
        c = make_collector(self._none_hbm_backend(), one_pod_attribution, store,
                           legacy_metrics=True)
        c.poll_once()
        text = store.current().encode().decode()
        assert "pod_gpu_memory_usage{" not in text
        assert "docker_gpu_memory_perc_usage{" not in text

    def test_chip_info_always_published(self, store):
        c = make_collector(self._none_hbm_backend(), FakeAttribution(), store)
        c.poll_once()
        # Even with empty device_kind/coords: chip presence is guaranteed.
        assert store.current().value(
            "tpu_chip_info",
            {**chip_labels(0), "device_kind": "", "coords": ""},
        ) == 1.0

    def _backend_with_totals(self, totals):
        from tpu_pod_exporter.backend import ChipInfo, ChipSample, HostSample

        class TotalsBackend(FakeBackend):
            def sample(self):
                return HostSample(chips=tuple(
                    ChipSample(
                        info=ChipInfo(chip_id=i, device_path=f"/dev/accel{i}",
                                      device_ids=(str(i),)),
                        hbm_used_bytes=4 * 1024**3, hbm_total_bytes=t,
                    ) for i, t in enumerate(totals)
                ))

        return TotalsBackend(chips=0)

    def test_none_total_omits_total_and_percent_keeps_used(self, store):
        # VERDICT r4 weak #1 (collector half): total=None ⇒ no
        # tpu_hbm_total_bytes and no tpu_hbm_used_percent for that chip,
        # while used (which WAS read) still publishes.
        c = make_collector(
            self._backend_with_totals([32 * 1024**3, None]),
            FakeAttribution(), store,
        )
        c.poll_once()
        snap = store.current()
        assert snap.value("tpu_hbm_used_bytes", chip_labels(1)) == 4 * 1024**3
        assert snap.value("tpu_hbm_total_bytes", chip_labels(1)) is None
        assert snap.value("tpu_hbm_used_percent", chip_labels(1)) is None
        # The healthy chip is unaffected.
        assert snap.value("tpu_hbm_used_percent", chip_labels(0)) == 12.5

    def test_zero_total_publishes_total_but_omits_percent(self, store):
        # A genuinely-read 0 total is real data (publish it), but a percent
        # of a zero capacity is undefined — omit, don't publish 0.0.
        c = make_collector(
            self._backend_with_totals([0.0]), FakeAttribution(), store
        )
        c.poll_once()
        snap = store.current()
        assert snap.value("tpu_hbm_total_bytes", chip_labels(0)) == 0.0
        assert snap.value("tpu_hbm_used_percent", chip_labels(0)) is None


class TestOverrunsExported:
    def test_loop_overruns_reach_exposition(self, store):
        c = make_collector(
            FakeBackend(chips=1), FakeAttribution(), store,
            loop_overruns_fn=lambda: 7,
        )
        c.poll_once()
        assert store.current().value("tpu_exporter_poll_overruns_total") == 7.0

    def test_absent_without_a_loop(self, store):
        # One-shot tools (status, hwcheck) have no loop: no overruns series.
        c = make_collector(FakeBackend(chips=1), FakeAttribution(), store)
        c.poll_once()
        text = store.current().encode().decode()
        assert "\ntpu_exporter_poll_overruns_total " not in text
