"""Resource-pressure governor: ladders, shed hooks, admission control,
full-disk boots, and the ENOSPC-mid-append contract (ISSUE 10)."""

from __future__ import annotations

import errno
import http.client
import os
import random
import time

import pytest

from tpu_pod_exporter import persist as persist_mod
from tpu_pod_exporter.history import HistoryStore
from tpu_pod_exporter.metrics import SnapshotBuilder, SnapshotStore
from tpu_pod_exporter.persist import StatePersister, WalBuffer
from tpu_pod_exporter.pressure import (
    PressureGovernor,
    dir_usage_bytes,
    is_disk_full_error,
    pressure_status_summary,
    reclaim_tmp_files,
)
from tpu_pod_exporter.server import MetricsServer
from tpu_pod_exporter.trace import PollTrace, TraceStore


def put_body(store: SnapshotStore, n: int = 2000) -> None:
    b = SnapshotBuilder()
    from tpu_pod_exporter.metrics import schema

    b.declare(schema.TPU_EXPORTER_UP)
    b.add(schema.TPU_EXPORTER_UP, 1.0)
    store.swap(b.build(timestamp=time.time()))


class FakeClock:
    def __init__(self, t: float = 100.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t


# ------------------------------------------------------------ governor core


class TestGovernor:
    def make(self, **kw):
        clock = FakeClock()
        kw.setdefault("check_interval_s", 0.01)
        kw.setdefault("hysteresis_s", 10.0)
        gov = PressureGovernor(clock=clock, wallclock=clock, **kw)
        return gov, clock

    def test_sheds_one_rung_per_tick_in_order(self):
        gov, clock = self.make(memory_budget_bytes=100)
        usage = {"n": 1000}
        gov.register_memory_component("x", lambda: usage["n"])
        order: list[str] = []
        for name in ("a", "b", "c"):
            gov.add_memory_rung(name, lambda n=name: order.append(n),
                                lambda n=name: order.append(f"-{n}"))
        gov.tick()
        assert order == ["a"]
        gov.tick()
        gov.tick()
        gov.tick()  # ladder exhausted: no further sheds
        assert order == ["a", "b", "c"]
        st = gov.stats()["memory"]
        assert st["level"] == 3 and st["sheds"] == 3
        assert st["rung"] == "c"

    def test_recovery_needs_hysteresis_and_steps_rung_by_rung(self):
        gov, clock = self.make(memory_budget_bytes=100, hysteresis_s=5.0)
        usage = {"n": 1000}
        gov.register_memory_component("x", lambda: usage["n"])
        order: list[str] = []
        gov.add_memory_rung("a", lambda: order.append("a"),
                            lambda: order.append("-a"))
        gov.add_memory_rung("b", lambda: order.append("b"),
                            lambda: order.append("-b"))
        gov.tick()
        gov.tick()
        assert order == ["a", "b"]
        usage["n"] = 10  # pressure gone, well under recover_frac
        gov.tick()       # starts the quiet window, no release yet
        assert order == ["a", "b"]
        clock.t += 3.0
        gov.tick()       # still inside hysteresis
        assert order == ["a", "b"]
        clock.t += 3.0
        gov.tick()       # one rung released...
        assert order == ["a", "b", "-b"]
        gov.tick()       # ...and the NEXT needs its own quiet window
        assert order == ["a", "b", "-b"]
        clock.t += 6.0
        gov.tick()
        assert order == ["a", "b", "-b", "-a"]
        st = gov.stats()["memory"]
        assert st["level"] == 0 and st["recovers"] == 2

    def test_usage_above_recover_frac_blocks_recovery(self):
        gov, clock = self.make(memory_budget_bytes=100, hysteresis_s=1.0)
        usage = {"n": 1000}
        gov.register_memory_component("x", lambda: usage["n"])
        released = []
        gov.add_memory_rung("a", lambda: None, lambda: released.append(1))
        gov.tick()
        usage["n"] = 95  # under budget, but above 0.85 * budget
        for _ in range(5):
            clock.t += 5.0
            gov.tick()
        assert not released  # hysteresis band holds the rung

    def test_enospc_report_sheds_without_a_budget(self):
        gov, clock = self.make()  # no budgets at all
        shed = []
        gov.add_disk_rung("a", lambda: shed.append(1), lambda: shed.append(-1))
        assert gov.report_io_error(OSError(errno.ENOSPC, "full"))
        assert not gov.report_io_error(OSError(errno.EIO, "flaky"))
        assert not gov.report_io_error(ValueError("nope"))
        gov.tick()
        assert shed == [1]
        # The fault window expires -> recovery (budget 0 = fault-only).
        clock.t += 120.0
        gov.tick()
        clock.t += 120.0
        gov.tick()
        assert shed == [1, -1]

    def test_broken_rung_does_not_kill_the_governor(self):
        gov, _clock = self.make(memory_budget_bytes=1)
        gov.register_memory_component("x", lambda: 1000)

        def boom() -> None:
            raise RuntimeError("rung exploded")

        gov.add_memory_rung("a", boom, boom)
        gov.tick()  # must not raise
        assert gov.stats()["memory"]["level"] == 1

    def test_emit_matches_stats(self):
        gov, _clock = self.make(memory_budget_bytes=100)
        gov.register_memory_component("x", lambda: 500)
        gov.add_memory_rung("a", lambda: None, lambda: None)
        gov.tick()
        b = SnapshotBuilder()
        gov.emit(b)
        body = b.build(timestamp=time.time()).encode().decode()
        assert 'tpu_exporter_pressure_state{resource="memory"} 1' in body
        assert 'tpu_exporter_pressure_state{resource="disk"} 0' in body
        assert ('tpu_exporter_pressure_transitions_total'
                '{resource="memory",direction="shed"} 1') in body
        assert ('tpu_exporter_pressure_budget_bytes{resource="memory"} 100'
                in body)

    def test_sidecar_roundtrip_and_status_line(self, tmp_path):
        gov = PressureGovernor(memory_budget_bytes=100,
                               sidecar_dir=str(tmp_path))
        gov.register_memory_component("x", lambda: 500)
        gov.add_memory_rung("cache_off", lambda: None, lambda: None)
        gov.tick()
        doc = pressure_status_summary(str(tmp_path))
        assert doc is not None
        assert doc["memory"]["level"] == 1
        assert doc["memory"]["rung"] == "cache_off"
        from tpu_pod_exporter.status import pressure_line

        line = pressure_line(str(tmp_path))
        assert line is not None and "memory rung 1 (cache_off)" in line
        assert pressure_status_summary(str(tmp_path / "nope")) is None


class TestTmpReclaim:
    def test_reclaims_orphans_keeps_fresh(self, tmp_path):
        old = tmp_path / "snapshot.bin.tmp"
        old.write_bytes(b"x" * 10)
        os.utime(old, (time.time() - 3600, time.time() - 3600))
        fresh = tmp_path / "live.tmp"
        fresh.write_bytes(b"y")
        keep = tmp_path / "snapshot.bin"
        keep.write_bytes(b"z")
        n = reclaim_tmp_files([str(tmp_path)], min_age_s=60.0)
        assert n == 1
        assert not old.exists() and fresh.exists() and keep.exists()
        # Boot shape: age 0 reclaims everything .tmp.
        assert reclaim_tmp_files([str(tmp_path)], min_age_s=0.0) == 1
        assert not fresh.exists() and keep.exists()

    def test_missing_dir_is_quiet(self):
        assert reclaim_tmp_files(["/nonexistent/nowhere", ""]) == 0

    def test_dir_usage(self, tmp_path):
        (tmp_path / "a").write_bytes(b"x" * 100)
        (tmp_path / "b").write_bytes(b"y" * 50)
        assert dir_usage_bytes(str(tmp_path)) == 150
        assert dir_usage_bytes(str(tmp_path / "nope")) == 0

    def test_is_disk_full_error(self):
        assert is_disk_full_error(OSError(errno.ENOSPC, "x"))
        assert is_disk_full_error(OSError(errno.EDQUOT, "x"))
        assert not is_disk_full_error(OSError(errno.EIO, "x"))
        assert not is_disk_full_error(RuntimeError("x"))


# -------------------------------------------------------- persist shed hooks


def make_persister(tmp_path, **kw):
    history = HistoryStore(capacity=8, max_series=64, retention_s=0.0,
                           tiers=())
    kw.setdefault("snapshot_interval_s", 0.0)
    kw.setdefault("fsync_interval_s", 0.0)
    p = StatePersister(str(tmp_path), history=history, **kw)
    return p


def snap_with(up: float = 1.0, ts: float = 100.0):
    from tpu_pod_exporter.metrics import schema

    b = SnapshotBuilder()
    b.declare(schema.TPU_EXPORTER_UP)
    b.add(schema.TPU_EXPORTER_UP, up)
    return b.build(timestamp=ts)


class TestPersistShed:
    def test_wal_stride_thins_and_counts_shed(self, tmp_path):
        p = make_persister(tmp_path)
        p.set_wal_stride(4)
        for i in range(8):
            p._write_samples(snap_with(ts=100.0 + i))
        st = p.stats()
        assert st["dropped_by_reason"]["shed"] == 6  # 2 of 8 written
        assert st["wal_stride"] == 4
        p.set_wal_stride(1)
        p._write_samples(snap_with(ts=200.0))
        assert p.stats()["dropped_by_reason"]["shed"] == 6

    def test_wal_off_sheds_everything(self, tmp_path):
        p = make_persister(tmp_path)
        p.set_wal_enabled(False)
        for i in range(3):
            p._write_samples(snap_with(ts=100.0 + i))
        st = p.stats()
        assert st["dropped_by_reason"]["shed"] == 3
        assert st["wal_records"] == 0

    def test_snapshot_factor_stretches_interval(self, tmp_path):
        clock = FakeClock()
        p = make_persister(tmp_path, snapshot_interval_s=10.0, clock=clock,
                           wallclock=clock)
        p._last_rotate = clock.t
        p.set_snapshot_interval_factor(2.0)
        clock.t += 15.0  # past the base interval, inside the doubled one
        p._maybe_rotate()
        assert p.stats()["snapshots"] == 0
        clock.t += 6.0
        p._maybe_rotate()
        assert p.stats()["snapshots"] == 1

    def test_checkpoint_failure_retries_on_short_cadence(self, tmp_path,
                                                         monkeypatch):
        clock = FakeClock()
        p = make_persister(tmp_path, snapshot_interval_s=100.0, clock=clock,
                           wallclock=clock)
        p._last_rotate = clock.t
        calls = {"n": 0}
        real = persist_mod.atomic_write

        def failing(path, data):
            calls["n"] += 1
            if calls["n"] == 1:
                raise OSError(errno.ENOSPC, "disk full")
            real(path, data)

        monkeypatch.setattr(persist_mod, "atomic_write", failing)
        clock.t += 101.0
        p._maybe_rotate()  # fails, counted as disk_full, armed for retry
        st = p.stats()
        assert st["snapshots"] == 0
        assert st["errors_by_reason"]["disk_full"] == 1
        clock.t += 2.0
        p._maybe_rotate()  # inside SNAPSHOT_RETRY_S: no attempt yet
        assert calls["n"] == 1
        clock.t += StatePersister.SNAPSHOT_RETRY_S
        p._maybe_rotate()  # retry succeeds WITHOUT waiting out 100 s
        assert p.stats()["snapshots"] == 1

    def test_enospc_reports_to_pressure_hook(self, tmp_path):
        p = make_persister(tmp_path)
        seen: list[BaseException] = []
        p.set_pressure_hook(lambda e: bool(seen.append(e)) or True)
        p._count_error("boom: %s", "x", exc=OSError(errno.ENOSPC, "full"))
        assert len(seen) == 1
        st = p.stats()
        assert st["errors_by_reason"]["disk_full"] == 1
        assert st["errors_by_reason"]["io"] == 0

    def test_boot_reclaims_orphan_tmp(self, tmp_path):
        orphan = tmp_path / "snapshot.bin.tmp"
        orphan.write_bytes(b"partial checkpoint")
        p = make_persister(tmp_path)
        p.load()
        assert not orphan.exists()


# --------------------------------------------------- ENOSPC mid-append fuzz


class TestWalBufferEnospcFuzz:
    def test_seeded_enospc_mid_append_keeps_the_contract(self, tmp_path,
                                                         monkeypatch):
        """ENOSPC striking MID-append (a torn partial record on disk) must
        seal the segment: every record appended BEFORE the tear stays
        deliverable, every record after lands in a fresh segment, nothing
        acked is ever re-delivered across a reopen — 25 seeded trials."""
        real_append = persist_mod.append_record

        for trial in range(25):
            rng = random.Random(1000 + trial)
            d = tmp_path / f"t{trial}"
            buf = WalBuffer(str(d), fsync=False)
            buf.open()
            n = 20
            fault_at = rng.randrange(2, n - 2)
            cut_header = rng.random() < 0.5

            def torn_append(f, payload, _fa=fault_at, _ch=cut_header):
                idx = int(payload.decode().split(":")[0])
                if idx == _fa:
                    # Write PART of the record, then fail — the torn-tail
                    # shape a real ENOSPC leaves behind.
                    hdr = persist_mod._HDR.pack(
                        len(payload), 0xDEAD)
                    f.write(hdr if _ch else hdr + payload[: len(payload) // 2])
                    raise OSError(errno.ENOSPC, "No space left on device")
                return real_append(f, payload)

            monkeypatch.setattr(persist_mod, "append_record", torn_append)
            dropped = []
            for i in range(n):
                payload = f"{i}:{'x' * rng.randrange(5, 40)}".encode()
                try:
                    buf.append(payload)
                except OSError:
                    dropped.append(i)
            monkeypatch.setattr(persist_mod, "append_record", real_append)
            assert dropped == [fault_at]
            # Every non-dropped record is deliverable, in order.
            delivered = []
            k = rng.randrange(1, n - 2)  # ack a prefix, then "crash"
            for _ in range(k):
                payload = buf.peek()
                assert payload is not None
                delivered.append(int(payload.decode().split(":")[0]))
                buf.ack()
            buf.close()
            buf2 = WalBuffer(str(d), fsync=False)
            info = buf2.open()
            resumed = []
            while True:
                payload = buf2.peek()
                if payload is None:
                    break
                resumed.append(int(payload.decode().split(":")[0]))
                buf2.ack()
            expect = [i for i in range(n) if i != fault_at]
            assert delivered + resumed == expect, (
                f"trial {trial}: {delivered} + {resumed} != {expect} "
                f"(fault at {fault_at}, open info {info})"
            )
            assert not set(delivered) & set(resumed)  # no acked re-send
            buf2.close()


# ------------------------------------------------------- boot on a full disk


BAD_DIR = "/proc/1/nonexistent"


def _bad_dir_is_bad() -> bool:
    try:
        os.makedirs(BAD_DIR, exist_ok=True)
        return False
    except OSError:
        return True


class TestBootOnFullDisk:
    """Every --state-dir / egress-dir consumer must START SERVING with
    persistence shed when the disk refuses everything — never crash-loop
    (the hopeless-dir flavor; the mid-flight ENOSPC flavor is covered by
    the persist/egress error paths above)."""

    pytestmark = pytest.mark.skipif(
        not _bad_dir_is_bad(), reason="no unwritable directory available"
    )

    def test_exporter_app_serves(self):
        from tpu_pod_exporter.app import ExporterApp
        from tpu_pod_exporter.config import ExporterConfig

        cfg = ExporterConfig(
            port=0, host="127.0.0.1", interval_s=0.2, backend="fake",
            fake_chips=2, attribution="none",
            state_dir=os.path.join(BAD_DIR, "state"),
            egress_url="http://127.0.0.1:9/unreachable",
            egress_dir=os.path.join(BAD_DIR, "egress"),
            state_max_disk_mb=1.0,
            log_level="error",
        )
        app = ExporterApp(cfg)
        try:
            app.start()
            conn = http.client.HTTPConnection("127.0.0.1", app.port,
                                              timeout=5)
            conn.request("GET", "/metrics")
            resp = conn.getresponse()
            body = resp.read().decode()
            assert resp.status == 200
            assert "tpu_exporter_up 1" in body
            conn.close()
        finally:
            app.stop()

    def test_persister_load_cold_starts(self):
        p = StatePersister(os.path.join(BAD_DIR, "state"))
        rs = p.load()
        assert not rs.restored and rs.errors
        p.start()  # no thread on a dead dir; on_poll is a no-op
        assert p.on_poll(snap_with()) == 0
        p.close()

    def test_shipper_load_degrades(self):
        from tpu_pod_exporter.egress import RemoteWriteShipper

        sh = RemoteWriteShipper("http://127.0.0.1:9/w",
                                os.path.join(BAD_DIR, "egress"))
        info = sh.load()  # must not raise
        assert info["errors"]
        sh.close()

    def test_flat_aggregator_state_files_tolerate(self):
        from tpu_pod_exporter.persist import BreakerStateFile, ShardMapFile

        bf = BreakerStateFile(os.path.join(BAD_DIR, "breakers.json"))
        assert bf.load() == {}
        bf.save({"t": {"state": "open"}})  # logs, never raises
        sf = ShardMapFile(os.path.join(BAD_DIR, "shardmap.json"))
        assert sf.load() == {}
        sf.save({"shards": 2})

    def test_leaf_and_root_serve_with_dead_state_dirs(self):
        from tpu_pod_exporter.persist import BreakerStateFile, ShardMapFile
        from tpu_pod_exporter.shard import (
            RootAggregator,
            ShardMap,
            default_shards,
        )

        smap = ShardMap(default_shards(2))
        store = SnapshotStore()
        root = RootAggregator(
            {"s0": ("127.0.0.1:9",)},  # unreachable leaf: degrades, fine
            store,
            timeout_s=0.2,
            shard_map=smap,
            shard_map_store=ShardMapFile(
                os.path.join(BAD_DIR, "root-map.json")),
            breaker_store=BreakerStateFile(
                os.path.join(BAD_DIR, "root-breakers.json")),
        )
        root.poll_once()  # must not raise; publishes a (degraded) round
        body = store.current().encode().decode()
        assert "tpu_root_leaf_up" in body
        root.close()


# --------------------------------------------------------- admission control


class TestAdmissionControl:
    def test_connection_cap_rejects_with_429_health_exempt(self):
        store = SnapshotStore()
        put_body(store)
        server = MetricsServer(store, host="127.0.0.1", port=0,
                               max_open_connections=1)
        server.start()
        try:
            c1 = http.client.HTTPConnection("127.0.0.1", server.port,
                                            timeout=5)
            c1.request("GET", "/metrics")
            r1 = c1.getresponse()
            r1.read()
            assert r1.status == 200  # admitted, slot held (keep-alive)

            c2 = http.client.HTTPConnection("127.0.0.1", server.port,
                                            timeout=5)
            c2.request("GET", "/metrics")
            r2 = c2.getresponse()
            body = r2.read()
            assert r2.status == 429
            assert r2.headers["Retry-After"] == "1"
            assert b"connection limit" in body
            c2.close()

            # Probe paths answer even over the cap (kubelet must never be
            # 429'd into restarting the pod by a storm).
            c3 = http.client.HTTPConnection("127.0.0.1", server.port,
                                            timeout=5)
            c3.request("GET", "/healthz")
            r3 = c3.getresponse()
            r3.read()
            assert r3.status == 200
            c3.close()

            assert server.scrape_rejects["connections"] >= 1
            assert server.conn_stats["peak"] == 1

            # Releasing the incumbent frees the slot.
            c1.close()
            deadline = time.monotonic() + 5.0
            ok = False
            while time.monotonic() < deadline:
                c4 = http.client.HTTPConnection("127.0.0.1", server.port,
                                                timeout=5)
                c4.request("GET", "/metrics")
                r4 = c4.getresponse()
                r4.read()
                c4.close()
                if r4.status == 200:
                    ok = True
                    break
                time.sleep(0.05)
            assert ok
        finally:
            server.stop()

    def test_per_client_cap_rejects_and_counts(self):
        store = SnapshotStore()
        put_body(store)
        server = MetricsServer(store, host="127.0.0.1", port=0,
                               max_requests_per_client=2)
        server.start()
        try:
            handler = server._httpd.RequestHandlerClass
            # Saturate the client's budget deterministically (the counter
            # the admission check reads).
            with handler.client_lock:
                handler.client_active["127.0.0.1"] = 2
            conn = http.client.HTTPConnection("127.0.0.1", server.port,
                                              timeout=5)
            conn.request("GET", "/metrics")
            resp = conn.getresponse()
            body = resp.read()
            assert resp.status == 429
            assert b"per-client" in body
            conn.close()
            # Health stays exempt.
            conn = http.client.HTTPConnection("127.0.0.1", server.port,
                                              timeout=5)
            conn.request("GET", "/readyz")
            resp = conn.getresponse()
            resp.read()
            assert resp.status in (200, 503)  # not 429
            conn.close()
            assert server.scrape_rejects["client"] >= 1
            with handler.client_lock:
                handler.client_active.clear()
            conn = http.client.HTTPConnection("127.0.0.1", server.port,
                                              timeout=5)
            conn.request("GET", "/metrics")
            resp = conn.getresponse()
            resp.read()
            assert resp.status == 200
            conn.close()
        finally:
            server.stop()

    def test_caps_default_off(self):
        store = SnapshotStore()
        put_body(store)
        server = MetricsServer(store, host="127.0.0.1", port=0)
        server.start()
        try:
            conns = []
            for _ in range(6):
                c = http.client.HTTPConnection("127.0.0.1", server.port,
                                               timeout=5)
                c.request("GET", "/metrics")
                r = c.getresponse()
                r.read()
                assert r.status == 200
                conns.append(c)
            for c in conns:
                c.close()
            assert server.scrape_rejects["connections"] == 0
            assert server.scrape_rejects["client"] == 0
        finally:
            server.stop()


# ------------------------------------------------------- component shed hooks


class TestHistoryCapacityCut:
    def test_cut_keeps_newest_and_grows_back(self):
        h = HistoryStore(capacity=16, max_series=32, retention_s=0.0,
                         tiers=())
        for i in range(16):
            h.append("tpu_hbm_used_bytes", {"chip_id": "0"}, float(i),
                     t_mono=float(i), t_wall=1000.0 + i)
        h.set_capacity(4)
        rows = h.query_range("tpu_hbm_used_bytes", {},
                             start=0.0, end=2000.0)
        vals = [v for _t, v in rows[0]["values"]]
        assert vals == [12.0, 13.0, 14.0, 15.0]  # newest kept
        assert h.stats()["memory_bytes"] == 1 * 4 * 24
        # Appends keep flowing after the rebuild (layout cache intact).
        h.append("tpu_hbm_used_bytes", {"chip_id": "0"}, 99.0,
                 t_mono=20.0, t_wall=1020.0)
        rows = h.query_range("tpu_hbm_used_bytes", {},
                             start=0.0, end=2000.0)
        vals = [v for _t, v in rows[0]["values"]]
        assert vals == [13.0, 14.0, 15.0, 99.0]
        # Growing back preserves what survived.
        h.set_capacity(16)
        rows = h.query_range("tpu_hbm_used_bytes", {},
                             start=0.0, end=2000.0)
        vals = [v for _t, v in rows[0]["values"]]
        assert vals == [13.0, 14.0, 15.0, 99.0]

    def test_cut_through_append_snapshot_fast_path(self):
        h = HistoryStore(capacity=8, max_series=32, retention_s=0.0,
                         tiers=())
        from tpu_pod_exporter.metrics import schema

        def snap(i: float):
            b = SnapshotBuilder()
            b.declare(schema.TPU_EXPORTER_UP)
            b.add(schema.TPU_EXPORTER_UP, i)
            return b.build(timestamp=1000.0 + i)

        for i in range(6):
            h.append_snapshot(snap(float(i)), now_mono=float(i),
                              now_wall=1000.0 + i)
        h.set_capacity(3)
        for i in range(6, 9):
            h.append_snapshot(snap(float(i)), now_mono=float(i),
                              now_wall=1000.0 + i)
        rows = h.query_range("tpu_exporter_up", {}, start=0.0, end=2000.0)
        vals = [v for _t, v in rows[0]["values"]]
        assert vals == [6.0, 7.0, 8.0]


class TestTraceRingShed:
    def make_trace(self):
        tr = PollTrace("poll", time.monotonic, time.time)
        tr.begin("device_read")
        tr.end("ok")
        return tr

    def test_halving_keeps_newest_and_accounts(self):
        ts = TraceStore(max_traces=8)
        traces = [self.make_trace() for _ in range(8)]
        for tr in traces:
            ts.append(tr)
        before = ts.memory_bytes()
        ts.set_max_traces(4)
        assert ts.max_traces == 4
        assert ts.last(8) == traces[4:]
        assert ts.memory_bytes() == before // 2
        ts.set_max_traces(8)  # grow back: bound restored, evictions stay
        assert len(ts.last(8)) == 4
        ts.append(self.make_trace())
        assert len(ts.last(8)) == 5


class TestFleetCacheBytes:
    def test_bytes_clear_disable(self):
        from tpu_pod_exporter.fleet import _QueryCache

        c = _QueryCache(4)
        env = {"status": "ok", "data": ["x" * 100]}
        c.put(("a",), env)
        assert c.bytes() >= 100
        c.put(("a",), env)  # re-put same key: no double count
        one = c.bytes()
        c.put(("b",), env)
        assert c.bytes() == 2 * one
        for i in range(10):
            c.put((f"k{i}",), env)
        assert len(c) == 4 and c.bytes() == 4 * one  # LRU eviction accounted
        c.set_enabled(False)
        assert c.bytes() == 0 and len(c) == 0
        c.put(("z",), env)  # disabled: no-op
        assert len(c) == 0
        c.set_enabled(True)
        c.put(("z",), env)
        assert len(c) == 1


# ------------------------------------------------------ exporter exposition


class TestExpositionSurface:
    def test_collector_emits_pressure_and_reason_labels(self, tmp_path):
        from tpu_pod_exporter.attribution.fake import FakeAttribution
        from tpu_pod_exporter.backend.fake import FakeBackend
        from tpu_pod_exporter.collector import Collector

        persister = StatePersister(str(tmp_path))
        gov = PressureGovernor(disk_budget_bytes=1 << 20)
        gov.add_disk_path(str(tmp_path))
        store = SnapshotStore()
        collector = Collector(
            FakeBackend(chips=2), FakeAttribution(), store,
            persister=persister, governor=gov,
        )
        collector.poll_once()
        body = store.current().encode().decode()
        assert 'tpu_exporter_pressure_state{resource="disk"} 0' in body
        assert 'tpu_exporter_pressure_budget_bytes{resource="disk"}' in body
        assert ('tpu_exporter_persist_dropped_total{reason="queue"} 0'
                in body)
        assert ('tpu_exporter_persist_dropped_total{reason="disk_full"} 0'
                in body)
        assert ('tpu_exporter_persist_errors_total{reason="disk_full"} 0'
                in body)
        collector.close()


# ------------------------------------------------------------- scenario DSL


class TestScenarioDsl:
    def test_new_kinds_parse(self):
        from tpu_pod_exporter.scenario import parse_event, parse_scenario

        ev = parse_event("disk_full()@3+4")
        assert ev.kind == "disk_full" and ev.duration == 4
        ev = parse_event("mem_pressure()@2")
        assert ev.kind == "mem_pressure" and ev.duration == 1
        ev = parse_event("scrape_storm(120)@3+2")
        assert ev.kind == "scrape_storm" and ev.count == 120
        ev = parse_event("clock_step(-45)@2")
        assert ev.kind == "clock_step" and ev.step_s == -45.0
        ev = parse_event("clock_step(+3600)@1")
        assert ev.step_s == 3600.0
        evs = parse_scenario("clock_step(-45)@2; disk_full()@3+4")
        assert [e.kind for e in evs] == ["clock_step", "disk_full"]

    def test_new_kind_errors_are_actionable(self):
        from tpu_pod_exporter.scenario import parse_event

        with pytest.raises(ValueError, match="takes no arguments"):
            parse_event("disk_full(3)@1")
        with pytest.raises(ValueError, match="takes no arguments"):
            parse_event("mem_pressure(x)@1")
        with pytest.raises(ValueError, match="connection count"):
            parse_event("scrape_storm(zero)@1")
        with pytest.raises(ValueError, match="must be >= 1"):
            parse_event("scrape_storm(0)@1")
        with pytest.raises(ValueError, match="signed seconds"):
            parse_event("clock_step(fast)@1")
        with pytest.raises(ValueError, match="injects nothing"):
            parse_event("clock_step(0)@1")
        with pytest.raises(ValueError, match="instantaneous"):
            parse_event("clock_step(-45)@1+3")

    def test_named_pressure_scenarios_registered(self):
        from tpu_pod_exporter.scenario import SCENARIOS

        for name in ("disk_full", "mem_pressure", "scrape_storm"):
            assert name in SCENARIOS
            SCENARIOS[name].events()  # timelines parse


# ---------------------------------------------------------- chaos injectors


class TestHostChaos:
    def test_clock_stepper(self):
        c = FakeClock(1000.0)
        from tpu_pod_exporter.chaos import ClockStepper

        stepped = ClockStepper(real=c)
        assert stepped() == 1000.0
        stepped.step(-45.0)
        assert stepped() == 955.0
        stepped.step(+100.0)
        assert stepped() == 1055.0
        assert stepped.steps == [-45.0, 100.0]

    def test_memory_hog(self):
        from tpu_pod_exporter.chaos import MemoryHog

        hog = MemoryHog()
        hog.hold(3 << 20)
        assert hog.held_bytes() == 3 << 20
        hog.release()
        assert hog.held_bytes() == 0

    def test_scrape_storm_against_real_server(self):
        store = SnapshotStore()
        put_body(store)
        server = MetricsServer(store, host="127.0.0.1", port=0,
                               max_open_connections=2)
        server.start()
        from tpu_pod_exporter.chaos import ScrapeStorm

        storm = ScrapeStorm("127.0.0.1", server.port, conns=6,
                            pause_s=0.01, reject_pause_s=0.05)
        try:
            storm.start()
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                st = storm.stats()
                if st["served"] > 0 and st["rejected"] > 0:
                    break
                time.sleep(0.05)
            st = storm.stats()
            assert st["served"] > 0
            assert st["rejected"] > 0
            assert server.conn_stats["peak"] <= 2
        finally:
            storm.stop()
            server.stop()


# ----------------------------------------------------------- app-level wiring


class TestAppWiring:
    def test_governor_built_from_flags_and_debug_vars(self, tmp_path):
        from tpu_pod_exporter.app import ExporterApp
        from tpu_pod_exporter.config import ExporterConfig

        cfg = ExporterConfig(
            port=0, host="127.0.0.1", interval_s=0.2, backend="fake",
            fake_chips=2, attribution="none",
            state_dir=str(tmp_path),
            state_max_disk_mb=64.0, memory_budget_mb=64.0,
            log_level="error",
        )
        app = ExporterApp(cfg)
        try:
            assert app.governor is not None
            rungs = app.governor.stats()["disk"]["rungs"]
            assert rungs == ["wal_coarse", "checkpoint_halved", "wal_off"]
            mem_rungs = app.governor.stats()["memory"]["rungs"]
            assert mem_rungs == ["trace_halved", "history_cut"]
            dv = app._debug_vars()
            assert "pressure" in dv
            assert "memory_components" in dv["pressure"]
            assert dv["connections"]["open"] >= 0
        finally:
            app.collector.close()

    def test_no_budgets_no_state_no_governor(self):
        from tpu_pod_exporter.app import ExporterApp
        from tpu_pod_exporter.config import ExporterConfig

        cfg = ExporterConfig(
            port=0, host="127.0.0.1", backend="fake", fake_chips=1,
            attribution="none", log_level="error",
        )
        app = ExporterApp(cfg)
        try:
            assert app.governor is None
        finally:
            app.collector.close()


# ------------------------------------------------------- shard byte estimate


class TestStaleViewBytes:
    def test_estimate_and_shed(self):
        from tpu_pod_exporter.shard import LeafView, RootAggregator

        store = SnapshotStore()
        root = RootAggregator({"s0": ("leaf:a",)}, store, timeout_s=0.1)
        assert root.stale_view_bytes() == 0
        view = LeafView(leaf="leaf:a", round_ts=1.0,
                        target_up={"t1": 1.0, "t2": 0.0})
        root._last_views["leaf:a"] = (view, 1.0)
        est = root.stale_view_bytes()
        assert est == 3 * 160  # 1 base + 2 target_up entries
        assert root.debug_vars()["stale_view_bytes"] == est
        assert root.shed_stale_views() == 1
        assert root.stale_view_bytes() == 0
        root.close()
