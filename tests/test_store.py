"""Fleet TSDB-lite (ISSUE 11): durable, queryable fleet history at the root.

Covers the tier generalization (disk-backed TierRing push/replay/
accumulator-restore), recording-rule parsing + evaluation, the FleetStore
append/query/persistence contract, the seeded torn-segment fuzz (boot
always succeeds, restored buckets are a clean prefix, no duplicate bucket
on replay), the store_thin pressure rung, the source-aware query plane,
the cross-tier ``source`` envelope contract (node == leaf == root shapes),
root wiring + exposition, the status --tree store footer, and the
store_continuity scenario drill with its store-off negative control.
"""

import json
import os
import random
import urllib.error
import urllib.request

import pytest

from tpu_pod_exporter.history import HistoryStore, TierRing, tier_items
from tpu_pod_exporter.metrics import SnapshotBuilder, SnapshotStore, schema
from tpu_pod_exporter.store import (
    DEFAULT_STORE_TIERS,
    FleetStore,
    StoreQueryPlane,
    evaluate_rule,
    parse_rules,
    series_key,
    store_status_summary,
)

BASE_WALL = 1_700_000_000.0


@pytest.fixture
def quiet_logs():
    """Silence the stack's WARNING chatter for the e2e runs (the
    test_scenario.py fixture, local twin)."""
    import logging

    loggers = [logging.getLogger(f"tpu_pod_exporter.{n}")
               for n in ("shard", "aggregate", "fleet", "store",
                         "pressure", "chaos", "server")]
    old = [lg.level for lg in loggers]
    for lg in loggers:
        lg.setLevel(logging.ERROR)
    yield
    for lg, lv in zip(loggers, old):
        lg.setLevel(lv)


def get_json(url):
    try:
        resp = urllib.request.urlopen(url, timeout=5)
        return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def fleet_snapshot(r, n_targets=4, n_slices=2, wall=BASE_WALL):
    """One root-shaped published snapshot: per-target up + slice rollups."""
    b = SnapshotBuilder()
    b.declare(schema.TPU_AGG_TARGET_UP)
    b.declare(schema.TPU_SLICE_HBM_USED_BYTES)
    b.declare(schema.TPU_SLICE_CHIP_COUNT)
    for i in range(n_targets):
        b.add(schema.TPU_AGG_TARGET_UP,
              0.0 if (i + r) % 19 == 0 else 1.0, (f"t{i}",))
    for sl in range(n_slices):
        b.add(schema.TPU_SLICE_HBM_USED_BYTES,
              float(1000 * (sl + 1) + r), (f"slice-{sl}", "v5p", "tpu"))
        b.add(schema.TPU_SLICE_CHIP_COUNT, 8.0, (f"slice-{sl}", "v5p", "tpu"))
    return b.build(timestamp=wall)


def make_store(tmp_path, tiers="10:20,60:40", rules_text="", **kw):
    rules = parse_rules(rules_text) if rules_text else ()
    st = FleetStore(str(tmp_path / "store"), tiers=tiers, rules=rules, **kw)
    st.open()
    return st


def feed_rounds(store, n, dt=10.0, start_wall=BASE_WALL, **snap_kw):
    wall = start_wall
    for r in range(n):
        wall += dt
        store.append_snapshot(fleet_snapshot(r, wall=wall, **snap_kw),
                              now_wall=wall)
    return wall


# ------------------------------------------------------ tier generalization


class TestTierGeneralization:
    def bucket(self, bid, step=10.0, v=1.0, cnt=2.0):
        t0 = bid * step + 1.0
        return (t0, t0 + 5, t0, t0 + 5, v, v + 1, v * cnt, cnt, v, v + 1,
                0.5)

    def test_push_keeps_order_and_wraps(self):
        r = TierRing(10.0, 3)
        for bid in range(5):
            r.push(self.bucket(bid))
        ids = [int(b[2] // 10.0) for b in tier_items(r.copy())]
        assert ids == [2, 3, 4]  # newest kept, oldest evicted

    def test_push_same_bucket_replaces(self):
        r = TierRing(10.0, 4)
        r.push(self.bucket(7, v=1.0))
        r.push(self.bucket(7, v=9.0))  # re-finalization record supersedes
        items = tier_items(r.copy())
        assert len(items) == 1
        assert items[0][4] == 9.0

    def test_pop_to_accumulator_merges_same_bucket(self):
        r = TierRing(10.0, 4)
        r.push(self.bucket(3, v=5.0, cnt=2.0))
        r.pop_to_accumulator()
        assert r.n == 0
        assert r.bucket == 3
        # A live sample in the SAME wall bucket merges exactly.
        r.add(36.0, 36.0, 7.0, 2.0)
        ob = r.open_bucket()
        assert ob is not None
        assert ob[7] == 3.0        # cnt resumed: 2 restored + 1 live
        assert ob[5] == 7.0        # max updated
        assert ob[8] == 5.0        # first preserved from the restore

    def test_open_bucket_none_when_empty(self):
        assert TierRing(10.0, 4).open_bucket() is None


# --------------------------------------------------------- recording rules


class TestRules:
    def test_parse_happy_path(self):
        rules = parse_rules(
            "# comment\n"
            "\n"
            "fleet:hbm:by_slice = sum(tpu_slice_hbm_used_bytes) "
            "by (slice_name)\n"
            'up:count = count(tpu_aggregator_target_up{target="t1"})\n'
            "duty:avg = avg(tpu_slice_tensorcore_duty_cycle_avg_percent)\n"
        )
        assert [r.name for r in rules] == [
            "fleet:hbm:by_slice", "up:count", "duty:avg"]
        assert rules[0].by == ("slice_name",)
        assert rules[1].match == (("target", "t1"),)
        assert rules[2].by == ()

    @pytest.mark.parametrize("line,fragment", [
        ("bogus", "want name = agg"),
        ("x = frobnicate(tpu_slice_chip_count)", "unknown aggregation"),
        ("x = sum(no_such_metric)", "unknown metric"),
        ("x = sum(tpu_slice_chip_count) by (nope)", "not a label"),
        ('x = sum(tpu_slice_chip_count{nope="v"})', "not a label"),
        ("tpu_slice_chip_count = sum(tpu_slice_chip_count)", "shadows"),
        ("x = sum(tpu_slice_chip_count)\nx = sum(tpu_slice_chip_count)",
         "duplicate rule name"),
    ])
    def test_parse_errors_are_actionable(self, line, fragment):
        with pytest.raises(ValueError, match=fragment):
            parse_rules(line)

    def test_evaluate_sum_by_and_match(self):
        snap = fleet_snapshot(0, n_targets=6, n_slices=3)
        (rule,) = parse_rules(
            "s = sum(tpu_slice_hbm_used_bytes) by (slice_name)")
        out = dict((tuple(sorted(lbl.items())), v)
                   for lbl, v in evaluate_rule(rule, snap))
        assert out[(("slice_name", "slice-1"),)] == 2000.0
        (cnt,) = parse_rules("c = count(tpu_aggregator_target_up)")
        assert evaluate_rule(cnt, snap)[0][1] == 6.0
        (m,) = parse_rules(
            'm = max(tpu_slice_hbm_used_bytes{accelerator="v5p"})')
        assert evaluate_rule(m, snap)[0][1] == 3000.0

    def test_evaluate_absent_family_is_empty(self):
        (rule,) = parse_rules("d = sum(tpu_slice_dcn_bytes_per_second)")
        assert evaluate_rule(rule, fleet_snapshot(0)) == []


# --------------------------------------------------- append/query contract


class TestStoreAppendQuery:
    def test_rows_carry_source_tier_staleness(self, tmp_path):
        st = make_store(tmp_path)
        wall = feed_rounds(st, 12)
        rows = st.query_range(schema.TPU_SLICE_HBM_USED_BYTES.name,
                              {"slice_name": "slice-1"},
                              start=wall - 100, end=wall, step=0.0)
        assert len(rows) == 1
        row = rows[0]
        assert row["source"] == "store"
        assert row["tier"] == 10.0
        assert row["last_sample_wall_ts"] == wall
        assert len(row["values"]) >= 10
        st.close()

    def test_grid_and_agg(self, tmp_path):
        st = make_store(tmp_path)
        wall = feed_rounds(st, 12)
        rows = st.query_range(schema.TPU_SLICE_HBM_USED_BYTES.name,
                              {"slice_name": "slice-0"},
                              start=wall - 60, end=wall, step=10.0,
                              agg="min")
        assert rows and len(rows[0]["values"]) == 7
        # min over one-sample buckets == the sample
        assert rows[0]["values"][-1][1] == 1000.0 + 11

    def test_step_escalates_to_coarse_tier(self, tmp_path):
        st = make_store(tmp_path)
        wall = feed_rounds(st, 40)  # finest (cap 20) wrapped
        rows = st.query_range(schema.TPU_SLICE_CHIP_COUNT.name,
                              {"slice_name": "slice-0"},
                              start=wall - 390, end=wall, step=0.0)
        assert rows[0]["tier"] == 60.0  # escalated for coverage
        rows = st.query_range(schema.TPU_SLICE_CHIP_COUNT.name,
                              {"slice_name": "slice-0"},
                              start=wall - 100, end=wall, step=0.0)
        assert rows[0]["tier"] == 10.0

    def test_window_stats_and_counter_rate(self, tmp_path):
        st = make_store(tmp_path)
        wall = BASE_WALL
        for r in range(20):
            wall += 10.0
            st.append_samples(
                [("my_bytes_total", {"link": "0"}, 100.0 * r)],
                now_wall=wall)
        rows = st.window_stats("my_bytes_total", {"link": "0"},
                               window_s=150.0, now_wall=wall)
        assert rows[0]["source"] == "store"
        assert rows[0]["stats"]["rate"] == pytest.approx(10.0)
        st.close()

    def test_rule_series_stored(self, tmp_path):
        st = make_store(
            tmp_path,
            rules_text="fleet:hbm = sum(tpu_slice_hbm_used_bytes) "
                       "by (slice_name)")
        wall = feed_rounds(st, 6)
        rows = st.query_range("fleet:hbm", {"slice_name": "slice-0"},
                              start=wall - 100, end=wall, step=0.0)
        assert rows and rows[0]["values"][-1][1] == 1000.0 + 5
        assert st.stats()["rules"] == 1
        st.close()

    def test_series_list(self, tmp_path):
        st = make_store(tmp_path)
        feed_rounds(st, 3, n_targets=2, n_slices=1)
        names = {s["metric"] for s in st.series_list()}
        assert schema.TPU_AGG_TARGET_UP.name in names
        assert all(s["source"] == "store" for s in st.series_list())


# ----------------------------------------------------- persistence/replay


class TestPersistence:
    def test_restart_replays_and_continues(self, tmp_path):
        st = make_store(tmp_path)
        wall = feed_rounds(st, 15)
        before = st.query_range(schema.TPU_SLICE_HBM_USED_BYTES.name,
                                {"slice_name": "slice-0"},
                                start=0, end=wall, step=0.0)[0]["values"]
        st.close()
        st2 = make_store(tmp_path)
        after = st2.query_range(schema.TPU_SLICE_HBM_USED_BYTES.name,
                                {"slice_name": "slice-0"},
                                start=0, end=wall, step=0.0)[0]["values"]
        # Everything finalized before the restart answers after it.
        assert after == before
        # And live appends continue the same series with NO duplicate
        # bucket even when the first post-restart sample lands in the
        # same wall bucket the pre-restart accumulator owned.
        st2.append_snapshot(fleet_snapshot(15, wall=wall + 1.0),
                            now_wall=wall + 1.0)
        st2.append_snapshot(fleet_snapshot(16, wall=wall + 11.0),
                            now_wall=wall + 11.0)
        vals = st2.query_range(schema.TPU_SLICE_HBM_USED_BYTES.name,
                               {"slice_name": "slice-0"},
                               start=wall - 80, end=wall + 12,
                               step=0.0)[0]["values"]
        ids = [int(t // 10.0) for t, _v in vals]
        assert len(ids) == len(set(ids)), f"duplicate bucket: {ids}"
        st2.close()

    def test_same_bucket_merge_is_exact(self, tmp_path):
        st = make_store(tmp_path, tiers="100:10")
        st.append_samples([("g", {}, 1.0)], now_wall=BASE_WALL + 110.0)
        st.append_samples([("g", {}, 5.0)], now_wall=BASE_WALL + 120.0)
        st.close()
        st2 = make_store(tmp_path, tiers="100:10")
        st2.append_samples([("g", {}, 9.0)], now_wall=BASE_WALL + 130.0)
        rows = st2.window_stats("g", window_s=500.0,
                                now_wall=BASE_WALL + 130.0)
        s = rows[0]["stats"]
        assert s["samples"] == 3       # restored 2 + live 1, ONE bucket
        assert s["min"] == 1.0 and s["max"] == 9.0 and s["first"] == 1.0
        st2.close()

    def test_counter_rate_survives_restart(self, tmp_path):
        st = make_store(tmp_path, tiers="10:40")
        wall = BASE_WALL
        for r in range(8):
            wall += 10.0
            st.append_samples([("c_total", {}, 50.0 * r)], now_wall=wall)
        st.close()
        st2 = make_store(tmp_path, tiers="10:40")
        for r in range(8, 12):
            wall += 10.0
            st2.append_samples([("c_total", {}, 50.0 * r)], now_wall=wall)
        rows = st2.window_stats("c_total", window_s=110.0, now_wall=wall)
        # The boundary delta across the restart contributes: pv was
        # restored from the replayed accumulator, not re-learned as NaN.
        assert rows[0]["stats"]["rate"] == pytest.approx(5.0)
        st2.close()

    def test_backward_clock_step_keeps_buckets_monotone(self, tmp_path):
        """Regression (review finding): the PR-10 clock fence, applied to
        the store — a backward NTP step must not open an OLDER bucket id
        (non-monotone buckets would break align_grid's forward walk and
        replay's replace-newest dedup)."""
        st = make_store(tmp_path, tiers="10:40")
        wall = BASE_WALL
        for r in range(6):
            wall += 10.0
            st.append_samples([("g", {}, float(r))], now_wall=wall)
        # 45 s backward step: samples keep folding at the fenced wall.
        for r in range(6, 9):
            st.append_samples([("g", {}, float(r))], now_wall=wall - 45.0)
        # Clock catches back up and passes the fence.
        st.append_samples([("g", {}, 9.0)], now_wall=wall + 20.0)
        rows = st.query_range("g", start=0, end=wall + 30, step=0.0)
        ts = [t for t, _v in rows[0]["values"]]
        assert ts == sorted(ts)
        ids = [int(t // 10.0) for t in ts]
        assert len(ids) == len(set(ids))
        st.close()

    def test_last_append_stamp_is_durability_not_ingestion(self, tmp_path):
        """Regression (review finding): the published last-append
        timestamp must stop advancing while the WAL refuses writes —
        it is the AppendFailing alert's age arm."""
        st = make_store(tmp_path, tiers="10:20")
        wall = feed_rounds(st, 5, n_targets=1, n_slices=1)
        durable = st.stats()["last_append_wall"]
        assert durable > 0

        def refuse(payload):
            raise OSError(28, "No space left on device")

        for buf in st._buffers:
            buf.append = refuse
        wall = feed_rounds(st, 5, start_wall=wall, n_targets=1, n_slices=1)
        stats = st.stats()
        assert stats["append_failures"] > 0
        assert stats["last_append_wall"] == durable  # aged, not refreshed
        for buf in st._buffers:
            del buf.append  # restore the real method (disk "recovers")
        st.close()
        assert st.stats()["last_append_wall"] >= durable

    def test_key_discipline_matches_snapshot_path(self):
        labels = {"target": "t1"}
        assert series_key(schema.TPU_AGG_TARGET_UP.name, labels) == (
            schema.TPU_AGG_TARGET_UP.name, ("t1",))
        # Rule names fall back to sorted-items keys.
        assert series_key("my:rule", {"b": "2", "a": "1"}) == (
            "my:rule", (("a", "1"), ("b", "2")))


# -------------------------------------------- torn-segment fuzz (satellite)


class TestTornSegmentFuzz:
    def _written_ids(self, wall0, rounds, step=10.0):
        return [int((wall0 + (r + 1) * 10.0) // step) for r in range(rounds)]

    def _restored_ids(self, store, step=10.0):
        key = series_key(schema.TPU_SLICE_HBM_USED_BYTES.name,
                         {"slice_name": "slice-0", "accelerator": "v5p", "family": "tpu"})
        s = store._series.get(key)
        if s is None:
            return []
        return [int(b[2] // step) for b in tier_items(s.tiers[0].copy())]

    def _segments(self, tmp_path):
        tier_dir = tmp_path / "store" / "tier-10"
        return sorted(p for p in tier_dir.iterdir()
                      if p.name.startswith("seg-"))

    def test_tail_truncation_keeps_clean_prefix(self, tmp_path):
        rng = random.Random(1234)
        for trial in range(8):
            root = tmp_path / f"t{trial}"
            root.mkdir()
            st = make_store(root, tiers="10:64,60:32")
            feed_rounds(st, 30, n_targets=2, n_slices=1)
            st.close()
            seg = self._segments(root)[-1]
            size = seg.stat().st_size
            os.truncate(seg, rng.randrange(8, size))
            st2 = make_store(root, tiers="10:64,60:32")  # must not raise
            ids = self._restored_ids(st2)
            written = self._written_ids(BASE_WALL, 30)
            # Clean prefix: some leading run of the written buckets,
            # nothing invented, nothing reordered, nothing duplicated.
            assert ids == written[:len(ids)]
            st2.close()

    def test_scramble_never_breaks_boot_or_duplicates(self, tmp_path):
        rng = random.Random(99)
        for trial in range(8):
            root = tmp_path / f"t{trial}"
            root.mkdir()
            st = make_store(root, tiers="10:64,60:32",
                            segment_max_bytes=2048)
            feed_rounds(st, 30, n_targets=2, n_slices=1)
            st.close()
            segs = self._segments(root)
            victim = segs[rng.randrange(len(segs))]
            data = bytearray(victim.read_bytes())
            if len(data) > 16:
                off = rng.randrange(8, len(data))
                data[off] = (data[off] + 1 + rng.randrange(255)) % 256
                victim.write_bytes(bytes(data))
            st2 = make_store(root, tiers="10:64,60:32",
                             segment_max_bytes=2048)  # must not raise
            ids = self._restored_ids(st2)
            written = self._written_ids(BASE_WALL, 30)
            assert len(ids) == len(set(ids)), "duplicate bucket on replay"
            # Restored buckets are a subsequence of what was written — a
            # mid-segment tear loses that segment's tail, never invents
            # or reorders data.
            it = iter(written)
            assert all(any(w == b for w in it) for b in ids)
            st2.close()


# ------------------------------------------------- thin rung + retention


class TestThinAndRetention:
    def test_thin_drops_finest_keeps_coarse(self, tmp_path):
        st = make_store(tmp_path)
        wall = feed_rounds(st, 30)
        st.set_thin(True)
        stats = st.stats()
        assert stats["thinned"] is True
        assert stats["tiers"][0]["enabled"] is False
        assert stats["tiers"][0]["buckets"] == 0
        # The tier's WAL records shed on the APPENDER's next pass (one
        # cursor-mover per buffer — set_thin may run on the governor
        # thread), so the counter lands after one more round.
        feed_rounds(st, 1, start_wall=wall)
        assert st.stats()["dropped"]["shed"] > 0
        assert st._buffers[0].pending() == 0
        # Queries keep answering from the coarse tier.
        rows = st.query_range(schema.TPU_SLICE_HBM_USED_BYTES.name,
                              {"slice_name": "slice-0"},
                              start=wall - 200, end=wall, step=0.0)
        assert rows and rows[0]["tier"] == 60.0
        # Memory accounting stays HONEST while thinned: the rings are
        # preallocated and set_thin only resets counters (it frees disk,
        # not memory) — reporting less would feed the memory ladder
        # phantom headroom.
        thin_mem = st.memory_bytes()
        st.set_thin(False)
        assert st.memory_bytes() == thin_mem
        # The re-enabled tier refills from live rounds.
        feed_rounds(st, 3, start_wall=wall)
        assert st.stats()["tiers"][0]["buckets"] > 0
        st.close()

    def test_release_does_not_mask_coarse_coverage(self, tmp_path):
        """Regression (review finding): a just-released finest tier
        refills from EMPTY — it must not claim infinite coverage via the
        oldest_wall() not-wrapped convention and silently answer minutes
        where the coarse tier still holds the long span."""
        st = make_store(tmp_path)
        wall = feed_rounds(st, 30)
        st.set_thin(True)
        st.set_thin(False)
        # A few refill rounds: finest now holds ONLY the newest samples.
        wall = feed_rounds(st, 3, start_wall=wall)
        rows = st.query_range(schema.TPU_SLICE_HBM_USED_BYTES.name,
                              {"slice_name": "slice-0"},
                              start=wall - 300, end=wall, step=0.0)
        assert rows and rows[0]["tier"] == 60.0  # coarse serves the span
        assert len(rows[0]["values"]) >= 5
        # A window INSIDE the refilled coverage stays on the finest tier.
        rows = st.query_range(schema.TPU_SLICE_HBM_USED_BYTES.name,
                              {"slice_name": "slice-0"},
                              start=wall - 15, end=wall, step=0.0)
        assert rows and rows[0]["tier"] == 10.0
        st.close()

    def test_single_tier_store_refuses_thin(self, tmp_path):
        st = make_store(tmp_path, tiers="10:20")
        feed_rounds(st, 5)
        st.set_thin(True)
        assert st.stats()["thinned"] is False
        st.close()

    def test_retention_trims_wal_to_ring_span(self, tmp_path):
        st = make_store(tmp_path, tiers="10:8")
        feed_rounds(st, 60)
        # Records per tier stay near ring capacity (cap + slack), so disk
        # is bounded by the tier's own span, not by uptime.
        assert st._buffers[0].pending() <= 8 + 16
        assert st.stats()["dropped"]["retention"] > 0
        st.close()

    def test_governor_rung_sheds_and_recovers(self, tmp_path):
        from tpu_pod_exporter.pressure import (
            PressureGovernor,
            register_store_rungs,
        )

        st = make_store(tmp_path)
        gov = PressureGovernor(hysteresis_s=0.0)
        register_store_rungs(gov, st)
        wall = feed_rounds(st, 30)
        usage = sum(
            os.path.getsize(os.path.join(d, f))
            for d in st.disk_paths() if os.path.isdir(d)
            for f in os.listdir(d)
            if os.path.isfile(os.path.join(d, f))
        )
        gov.set_disk_budget_bytes(max(usage // 2, 1024))
        gov.tick()
        assert st.stats()["thinned"] is True
        gov.set_disk_budget_bytes(10 * usage)
        gov.tick()  # first quiet tick arms the recovery window…
        gov.tick()  # …second releases the rung (hysteresis 0)
        assert st.stats()["thinned"] is False
        _ = wall
        st.close()


# --------------------------------------------------- source-aware plane


class FakeLivePlane:
    def __init__(self, rows):
        self.rows = rows
        self.closed = False

    def _env(self, route, data):
        return {"status": "ok", "partial": False, "route": route,
                "source": "live", "data": data, "targets": {},
                "took_s": 0.001}

    def series(self):
        return self._env("series", [
            {"metric": r["metric"], "labels": r["labels"]}
            for r in self.rows
        ])

    def query_range(self, metric, match=None, start=None, end=None,
                    step=0.0, agg="last"):
        rows = [r for r in self.rows if r["metric"] == metric]
        return self._env("query_range",
                         {"resultType": "matrix", "result": rows})

    def window_stats(self, metric, match=None, window_s=60.0):
        rows = [r for r in self.rows if r["metric"] == metric]
        return self._env("window_stats", {"result": rows})

    def close(self):
        self.closed = True


class TestStoreQueryPlane:
    HBM = schema.TPU_SLICE_HBM_USED_BYTES.name

    def make(self, tmp_path, live_rows=None):
        st = make_store(
            tmp_path,
            rules_text="fleet:hbm = sum(" + self.HBM + ") by (slice_name)")
        wall = feed_rounds(st, 8)
        live = FakeLivePlane(live_rows if live_rows is not None else [{
            "metric": self.HBM,
            "labels": {"slice_name": "slice-0", "accelerator": "v5p", "family": "tpu"},
            "values": [[wall, 1.0]],
        }])
        return StoreQueryPlane(live, st), st, wall

    def test_merged_fills_missing_series(self, tmp_path):
        plane, st, wall = self.make(tmp_path)
        env = plane.query_range(self.HBM, start=wall - 100, end=wall,
                                step=0.0)
        rows = env["data"]["result"]
        srcs = {r["labels"].get("slice_name"): r["source"] for r in rows}
        assert srcs["slice-0"] == "live"    # live coverage wins
        assert srcs["slice-1"] == "store"   # store fills the hole
        assert env["source"] == "merged"
        assert env["store"]["filled_series"] == 1

    def test_merged_without_fills_stays_live(self, tmp_path):
        plane, st, wall = self.make(tmp_path)
        env = plane.query_range("nothing_stored", start=wall - 50,
                                end=wall, step=0.0)
        assert env["source"] == "live"
        assert env["store"]["filled_series"] == 0

    def test_store_only(self, tmp_path):
        plane, st, wall = self.make(tmp_path)
        env = plane.query_range(self.HBM, start=wall - 100, end=wall,
                                step=0.0, source="store")
        assert env["source"] == "store"
        assert env["partial"] is False
        assert all(r["source"] == "store" for r in env["data"]["result"])
        # Rule series answer store-only by construction.
        renv = plane.query_range("fleet:hbm", start=wall - 100, end=wall,
                                 step=0.0, source="store")
        assert renv["data"]["result"]

    def test_live_only_tags_rows(self, tmp_path):
        plane, st, wall = self.make(tmp_path)
        env = plane.query_range(self.HBM, start=wall - 100, end=wall,
                                step=0.0, source="live")
        assert env["source"] == "live"
        assert all(r["source"] == "live" for r in env["data"]["result"])
        assert "store" not in env

    def test_bad_source_raises(self, tmp_path):
        plane, st, wall = self.make(tmp_path)
        with pytest.raises(ValueError, match="source must be one of"):
            plane.query_range(self.HBM, source="bogus")

    def test_no_live_plane_serves_store(self, tmp_path):
        st = make_store(tmp_path)
        wall = feed_rounds(st, 5)
        plane = StoreQueryPlane(None, st)
        env = plane.query_range(self.HBM, start=wall - 100, end=wall)
        assert env["source"] == "store"
        with pytest.raises(ValueError, match="no live query plane"):
            plane.query_range(self.HBM, source="live")

    def test_window_stats_and_series_merge(self, tmp_path):
        plane, st, wall = self.make(tmp_path)
        env = plane.window_stats(self.HBM, window_s=100.0)
        assert env["source"] in ("merged", "live")
        senv = plane.series()
        names = {r["metric"] for r in senv["data"]}
        assert schema.TPU_AGG_TARGET_UP.name in names  # store fill

    def test_cached_live_envelope_never_mutated(self, tmp_path):
        plane, st, wall = self.make(tmp_path)
        live_rows = plane._live.rows
        plane.query_range(self.HBM, start=wall - 100, end=wall, step=0.0)
        assert "source" not in live_rows[0]  # rows tagged on COPIES


# -------------------------------- cross-tier source contract (satellite 6)


class TestSourceContract:
    """The envelope-shape contract: every tier's /api/v1/query_range
    answers carry ``source``, with the same key and the same value
    domain — node (live), leaf fan-out (live), store-backed root
    (live|store|merged) — so parsers cannot drift between tiers."""

    def _serve(self, **kw):
        from tpu_pod_exporter.server import MetricsServer

        server = MetricsServer(SnapshotStore(), host="127.0.0.1", port=0,
                               **kw)
        server.start()
        return server, f"http://127.0.0.1:{server.port}"

    def test_node_tier_carries_live_source(self):
        import time as _time

        h = HistoryStore(capacity=16, max_series=16, retention_s=0.0)
        now = _time.time()
        mono = _time.monotonic()
        for i in range(5):
            h.append("tpu_hbm_used_bytes", {"chip_id": "0"}, float(i),
                     t_mono=mono - 10 + i, t_wall=now - 10 + i)
        server, base = self._serve(history=h)
        try:
            status, doc = get_json(
                base + "/api/v1/query_range?metric=tpu_hbm_used_bytes"
                       f"&start={now - 60:.3f}&end={now:.3f}")
            assert status == 200
            assert doc["source"] == "live"
            # ALL THREE node routes carry the key (drift guard).
            status, doc = get_json(base + "/api/v1/series")
            assert status == 200 and doc["source"] == "live"
            status, doc = get_json(
                base + "/api/v1/window_stats?metric=tpu_hbm_used_bytes"
                       "&window=600")
            assert status == 200 and doc["source"] == "live"
            # A node has no store: ?source= must 400, not be ignored.
            status, doc = get_json(
                base + "/api/v1/query_range?metric=tpu_hbm_used_bytes"
                       "&source=store")
            assert status == 400
            assert "store-backed" in doc["error"]
        finally:
            server.stop()

    def test_store_backed_root_over_http(self, tmp_path):
        st = make_store(tmp_path)
        wall = feed_rounds(st, 8)
        plane = StoreQueryPlane(None, st)
        server, base = self._serve(fleet=plane)
        try:
            metric = schema.TPU_SLICE_HBM_USED_BYTES.name
            status, doc = get_json(
                base + f"/api/v1/query_range?metric={metric}"
                       f"&start={wall - 100:.3f}&end={wall:.3f}")
            assert status == 200
            assert doc["source"] == "store"
            status, doc = get_json(
                base + f"/api/v1/query_range?metric={metric}"
                       f"&start={wall - 100:.3f}&end={wall:.3f}"
                       "&source=store")
            assert status == 200
            assert all(r["source"] == "store"
                       for r in doc["data"]["result"])
            status, doc = get_json(
                base + f"/api/v1/query_range?metric={metric}&source=nope")
            assert status == 400
            assert "source must be one of" in doc["error"]
        finally:
            server.stop()
            st.close()

    def test_all_tiers_same_key_same_domain(self, tmp_path):
        """One assertion over every tier's envelope: the drift guard."""
        import time as _time

        from tpu_pod_exporter.fleet import FleetQueryPlane

        envelopes = []
        # Node tier.
        h = HistoryStore(capacity=16, max_series=16, retention_s=0.0)
        now = _time.time()
        h.append("tpu_hbm_used_bytes", {}, 1.0, t_mono=0.0, t_wall=now)
        server, base = self._serve(history=h)
        try:
            _st, doc = get_json(
                base + "/api/v1/query_range?metric=tpu_hbm_used_bytes"
                       f"&start={now - 60:.3f}&end={now + 1:.3f}")
            envelopes.append(doc)
        finally:
            server.stop()
        # Leaf fan-out tier (fetch injected — no sockets needed).
        plane = FleetQueryPlane(
            ["n0:1"], timeout_s=1.0,
            fetch=lambda url, t: {"status": "ok", "data": {
                "resultType": "matrix",
                "result": [{"metric": "m", "labels": {},
                            "values": [[now, 1.0]]}]}},
        )
        envelopes.append(plane.query_range("m", start=now - 60, end=now))
        plane.close()
        # Store-backed root tier.
        st = make_store(tmp_path)
        wall = feed_rounds(st, 4)
        sp = StoreQueryPlane(None, st)
        envelopes.append(sp.query_range(
            schema.TPU_SLICE_HBM_USED_BYTES.name,
            start=wall - 100, end=wall))
        st.close()
        for env in envelopes:
            assert env.get("source") in ("live", "store", "merged"), env


# ----------------------------------------------------------- root wiring


class TestRootWiring:
    def test_root_appends_and_emits(self, tmp_path, quiet_logs):
        from tpu_pod_exporter.loadgen.fleet import _ShardSim

        holder = {}

        def factory():
            s = FleetStore(str(tmp_path / "store"), tiers="0.5:64,5:64")
            s.open()
            holder["store"] = s
            return s

        sim = _ShardSim(4, 1, False, 1, str(tmp_path), timeout_s=5.0,
                        store_factory=factory)
        try:
            for _ in range(3):
                sim.run_round()
            st = holder["store"]
            assert st.stats()["samples_appended"] > 0
            body = sim.root_body()
            assert "tpu_root_store_series" in body
            assert "tpu_root_store_span_seconds" in body
            assert 'tpu_root_store_dropped_records_total{reason="shed"}' \
                in body
            assert sim.root.debug_vars()["store"]["series"] > 0
            # Store rows answer for per-target series the fleet owns.
            rows = st.query_range(schema.TPU_AGG_TARGET_UP.name)
            assert len(rows) == 4
        finally:
            sim.close()


# ------------------------------------------------- status --tree footer


class TestStatusFooter:
    def test_store_line_renders(self, tmp_path):
        from tpu_pod_exporter.status import store_line

        st = make_store(tmp_path)
        feed_rounds(st, 10)
        st.write_sidecar()
        st.close()
        doc = store_status_summary(str(tmp_path / "store"))
        assert doc is not None
        line = store_line(doc)
        assert line.startswith("store: span ")
        assert "rules 0" in line
        assert "series" in line

    def test_render_tree_appends_footer(self, tmp_path):
        from tpu_pod_exporter.status import render_tree

        doc = {"shards": {}, "fleet": {"targets": 0, "targets_up": 0,
                                       "chips": 0.0},
               "store": {"span_s": 3600.0, "disk_bytes": 1024,
                         "disk_budget_bytes": 2048, "rules": 2,
                         "rules_evaluated_total": 10, "series": 5,
                         "last_append_wall": 0, "thinned": True}}
        out = render_tree(doc)
        assert "store: span 1.0h" in out
        assert "THINNED" in out

    def test_missing_sidecar_is_none(self, tmp_path):
        assert store_status_summary(str(tmp_path)) is None


# ---------------------------------------------- scenario drill (e2e)


class TestScenarioDrill:
    def test_dsl_parses_root_restart(self):
        from tpu_pod_exporter.scenario import SCENARIOS, parse_scenario

        (ev,) = parse_scenario("root_restart()@4+2")
        assert ev.kind == "root_restart"
        assert ev.duration == 2
        with pytest.raises(ValueError, match="takes no arguments"):
            parse_scenario("root_restart(now)@4")
        scn = SCENARIOS["store_continuity"]
        assert scn.uses_store and not scn.uses_egress
        assert scn.events()  # the committed timeline parses

    def test_store_continuity_end_to_end_and_negative_control(
            self, tmp_path, quiet_logs):
        from tpu_pod_exporter.loadgen.scenario import run_scenarios

        summary = run_scenarios(["store_continuity"], 8, 1, 1,
                                str(tmp_path / "on"), seed=7, store=True)
        assert summary["ok"], summary["scenarios"]["store_continuity"]
        # Negative control: the SAME invariant must fail without a store.
        summary = run_scenarios(["store_continuity"], 8, 1, 1,
                                str(tmp_path / "off"), seed=7, store=False)
        assert not summary["ok"]
        problems = summary["scenarios"]["store_continuity"]["problems"]
        assert any("store OFF" in p and "gap" in p for p in problems), \
            problems


# ------------------------------------------------------------- demo smoke


class TestDemos:
    def test_retention_demo_small(self, tmp_path, capsys):
        from tpu_pod_exporter.store import run_retention_demo

        rc = run_retention_demo(str(tmp_path / "ret"), targets=30,
                                days=0.5, verbose=False)
        out = capsys.readouterr().out
        assert rc == 0, out
