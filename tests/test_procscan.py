"""Procfs process↔chip attribution (SURVEY.md §2.6 inversion).

The reference harvests *container-namespace* PIDs via ``kubectl exec … ps``
and joins them against NVML *host* PIDs (broken by construction). Here the
scan reads ``/proc/<pid>/fd`` host-side over a synthetic proc tree — the
symlink targets never need to exist, so these tests run with zero devices.
"""

import os

import pytest

from tpu_pod_exporter.attribution.fake import FakeAttribution, simple_allocation
from tpu_pod_exporter.backend.fake import FakeBackend, FakeChipScript
from tpu_pod_exporter.collector import Collector
from tpu_pod_exporter.metrics import SnapshotStore
from tpu_pod_exporter.procscan import DeviceHolder, ProcScanner, parse_cgroup_identity
from tpu_pod_exporter.topology import HostTopology

UID = "3a61f333-1234-5678-9abc-def012345678"
CID = "0123456789abcdef0123456789abcdef0123456789abcdef0123456789abcdef"

CGROUP_V2 = (
    "0::/kubepods.slice/kubepods-burstable.slice/"
    f"kubepods-burstable-pod{UID.replace('-', '_')}.slice/"
    f"cri-containerd-{CID}.scope\n"
)
CGROUP_V1 = (
    "12:memory:/kubepods/burstable/pod" + UID + "/" + CID + "\n"
    "11:cpu,cpuacct:/kubepods/burstable/pod" + UID + "/" + CID + "\n"
)
CGROUP_DOCKER = (
    "0::/kubepods.slice/kubepods-pod" + UID.replace("-", "_") + ".slice/"
    "docker-" + CID + ".scope\n"
)
CGROUP_NON_POD = "0::/user.slice/user-0.slice/session-1.scope\n"


def add_proc(root, pid, fds, comm="train_worker", cgroup=CGROUP_V2):
    d = root / str(pid)
    (d / "fd").mkdir(parents=True)
    for i, target in enumerate(fds):
        os.symlink(target, d / "fd" / str(3 + i))
    (d / "comm").write_text(comm + "\n")
    (d / "cgroup").write_text(cgroup)


class TestParseCgroupIdentity:
    def test_v2_systemd(self):
        assert parse_cgroup_identity(CGROUP_V2) == (UID, CID)

    def test_v1_cgroupfs(self):
        assert parse_cgroup_identity(CGROUP_V1) == (UID, CID)

    def test_docker_scope(self):
        assert parse_cgroup_identity(CGROUP_DOCKER) == (UID, CID)

    def test_non_pod_process(self):
        assert parse_cgroup_identity(CGROUP_NON_POD) == ("", "")

    def test_empty(self):
        assert parse_cgroup_identity("") == ("", "")

    def test_pod_without_container_component(self):
        text = "0::/kubepods.slice/kubepods-pod" + UID.replace("-", "_") + ".slice\n"
        assert parse_cgroup_identity(text) == (UID, "")


class TestFullScan:
    def test_finds_holders_with_identity(self, tmp_path):
        add_proc(tmp_path, 100, ["/dev/accel0", "/dev/accel1"])
        add_proc(tmp_path, 200, ["/dev/null", "/tmp/log"])  # not a holder
        (tmp_path / "self").mkdir()  # non-numeric entries are skipped
        s = ProcScanner(proc_root=str(tmp_path))
        holders = s.scan()
        assert holders == (
            DeviceHolder(100, "train_worker", "/dev/accel0", UID, CID),
            DeviceHolder(100, "train_worker", "/dev/accel1", UID, CID),
        )

    def test_duplicate_fds_to_one_device_dedupe(self, tmp_path):
        add_proc(tmp_path, 50, ["/dev/accel2", "/dev/accel2", "/dev/accel2"])
        holders = ProcScanner(proc_root=str(tmp_path)).scan()
        assert [h.device_path for h in holders] == ["/dev/accel2"]

    def test_deleted_device_node_still_joins(self, tmp_path):
        # Runtime restart recreated /dev/accel0 while pid 70 holds the old
        # inode: readlink reports "… (deleted)". The wedged holder must still
        # attribute to the chip's canonical path.
        add_proc(tmp_path, 70, ["/dev/accel0 (deleted)"])
        holders = ProcScanner(proc_root=str(tmp_path)).scan()
        assert [h.device_path for h in holders] == ["/dev/accel0"]

    def test_vfio_paths_match(self, tmp_path):
        add_proc(tmp_path, 60, ["/dev/vfio/17"], cgroup=CGROUP_NON_POD)
        holders = ProcScanner(proc_root=str(tmp_path)).scan()
        assert holders == (DeviceHolder(60, "train_worker", "/dev/vfio/17"),)

    def test_vfio_container_node_excluded(self, tmp_path):
        # /dev/vfio/vfio is the shared container node every vfio-using
        # process opens (including non-TPU passthrough users) — it must
        # not become a holder, while /dev/vfio/<group> still does.
        add_proc(tmp_path, 61, ["/dev/vfio/vfio"], cgroup=CGROUP_NON_POD)
        add_proc(tmp_path, 62, ["/dev/vfio/vfio", "/dev/vfio/9"],
                 cgroup=CGROUP_NON_POD)
        holders = ProcScanner(proc_root=str(tmp_path)).scan()
        assert [(h.pid, h.device_path) for h in holders] == [(62, "/dev/vfio/9")]

    def test_unreadable_fd_table_skips_process(self, tmp_path):
        d = tmp_path / "300"
        d.mkdir()
        (d / "fd").write_text("not a dir")  # listdir → NotADirectoryError
        add_proc(tmp_path, 301, ["/dev/accel0"])
        holders = ProcScanner(proc_root=str(tmp_path)).scan()
        assert [h.pid for h in holders] == [301]

    def test_missing_proc_root_raises(self, tmp_path):
        # A whole-scan failure must surface (collector error budget +
        # staleness fallback), not masquerade as an empty holder set.
        from tpu_pod_exporter.procscan import ProcScanError

        s = ProcScanner(proc_root=str(tmp_path / "nope"))
        with pytest.raises(ProcScanError):
            s.scan()

    def test_proc_root_failure_preserves_cache_state(self, tmp_path):
        import shutil

        from tpu_pod_exporter.procscan import ProcScanError

        add_proc(tmp_path, 100, ["/dev/accel0"])
        s = ProcScanner(proc_root=str(tmp_path), full_scan_every=2)
        assert len(s.scan()) == 1
        moved = str(tmp_path) + ".moved"
        shutil.move(str(tmp_path), moved)
        # Verify window exhausts (cached pid unreadable → escalate to full
        # scan → ProcScanError), state untouched.
        with pytest.raises(ProcScanError):
            for _ in range(4):
                s.scan()
        shutil.move(moved, str(tmp_path))
        assert [h.pid for h in s.scan()] == [100]

    def test_sorted_by_pid(self, tmp_path):
        add_proc(tmp_path, 900, ["/dev/accel1"])
        add_proc(tmp_path, 80, ["/dev/accel0"])
        holders = ProcScanner(proc_root=str(tmp_path)).scan()
        assert [h.pid for h in holders] == [80, 900]


class TestIncrementalScan:
    def test_new_holder_appears_after_full_scan_interval(self, tmp_path):
        add_proc(tmp_path, 100, ["/dev/accel0"])
        s = ProcScanner(proc_root=str(tmp_path), full_scan_every=3)
        assert len(s.scan()) == 1  # full scan #1
        add_proc(tmp_path, 101, ["/dev/accel1"])
        # Verify-only window: cached set unchanged, new pid not yet visible.
        assert len(s.scan()) == 1
        assert len(s.scan()) == 1
        assert len(s.scan()) == 1  # 3rd verify exhausts the window
        assert len(s.scan()) == 2  # next full scan picks up pid 101
        assert s.full_scans == 2

    def test_departed_holder_triggers_immediate_rescan(self, tmp_path):
        import shutil

        add_proc(tmp_path, 100, ["/dev/accel0"])
        add_proc(tmp_path, 101, ["/dev/accel1"])
        s = ProcScanner(proc_root=str(tmp_path), full_scan_every=1000)
        assert len(s.scan()) == 2
        shutil.rmtree(tmp_path / "100")  # chip 0 freed
        holders = s.scan()  # verify notices, falls through to full scan
        assert [h.pid for h in holders] == [101]
        assert s.full_scans == 2

    def test_empty_holder_set_is_also_cached(self, tmp_path):
        # Idle node (chips present, nothing holding them): the verify window
        # must apply to the empty result too, not degenerate into a full
        # /proc walk every poll.
        tmp_path.mkdir(exist_ok=True)
        s = ProcScanner(proc_root=str(tmp_path), full_scan_every=4)
        for _ in range(9):
            assert s.scan() == ()
        assert s.full_scans == 2  # polls 1 and 6, not all 9

    def test_cached_path_costs_only_holder_reads(self, tmp_path):
        add_proc(tmp_path, 100, ["/dev/accel0"])
        s = ProcScanner(proc_root=str(tmp_path), full_scan_every=5)
        s.scan()
        s.scan()
        s.scan()
        assert s.full_scans == 1
        assert s.verify_scans == 2


def make_collector(store, scanner, legacy=False, chips=2):
    backend = FakeBackend(
        chips=chips,
        script=FakeChipScript(hbm_total_bytes=100.0, hbm_used_bytes=25.0),
    )
    attr = FakeAttribution(
        [simple_allocation("train-0", ["0", "1"], namespace="ml")]
    )
    topo = HostTopology(accelerator="v4-8", slice_name="s0", host="h0", worker_id="0")
    return Collector(
        backend, attr, store, topology=topo,
        process_scanner=scanner, legacy_metrics=legacy,
    )


def process_labels(chip_id, pid, comm="train_worker", pod_uid=UID,
                   pod="train-0", namespace="ml", container="main"):
    return {
        "chip_id": str(chip_id),
        "device_path": f"/dev/accel{chip_id}",
        "accelerator": "v4-8",
        "slice_name": "s0",
        "host": "h0",
        "worker_id": "0",
        "pod": pod,
        "namespace": namespace,
        "container": container,
        "pid": str(pid),
        "comm": comm,
        "pod_uid": pod_uid,
    }


class TestCollectorIntegration:
    def test_chip_process_info_series(self, tmp_path):
        add_proc(tmp_path, 4242, ["/dev/accel0"])
        store = SnapshotStore()
        c = make_collector(store, ProcScanner(proc_root=str(tmp_path)))
        c.poll_once()
        snap = store.current()
        assert snap.value("tpu_chip_process_info", process_labels(0, 4242)) == 1.0
        # Chip 1 has no holder — no series for it.
        assert snap.value("tpu_chip_process_info", process_labels(1, 4242)) is None
        assert c.last_stats.process_scan_s >= 0.0

    def test_multiple_holders_one_chip(self, tmp_path):
        add_proc(tmp_path, 10, ["/dev/accel0"])
        add_proc(tmp_path, 11, ["/dev/accel0"])
        store = SnapshotStore()
        c = make_collector(store, ProcScanner(proc_root=str(tmp_path)))
        c.poll_once()
        snap = store.current()
        assert snap.value("tpu_chip_process_info", process_labels(0, 10)) == 1.0
        assert snap.value("tpu_chip_process_info", process_labels(0, 11)) == 1.0

    def test_legacy_pid_label_uses_primary_holder(self, tmp_path):
        add_proc(tmp_path, 500, ["/dev/accel0", "/dev/accel1"])
        store = SnapshotStore()
        c = make_collector(store, ProcScanner(proc_root=str(tmp_path)), legacy=True)
        c.poll_once()
        snap = store.current()
        # Both chips held by pid 500: one legacy series {pid="500", pod}.
        assert snap.value(
            "pod_gpu_memory_usage", {"pid": "500", "pod": "train-0"}
        ) == 50.0
        assert snap.value(
            "docker_gpu_memory_perc_usage", {"pid": "500", "pod": "train-0"}
        ) == 25.0

    def test_legacy_pid_empty_without_holders(self, tmp_path):
        store = SnapshotStore()
        c = make_collector(store, ProcScanner(proc_root=str(tmp_path)), legacy=True)
        c.poll_once()
        snap = store.current()
        assert snap.value(
            "pod_gpu_memory_usage", {"pid": "", "pod": "train-0"}
        ) == 50.0

    def test_transient_scan_failure_keeps_last_holders(self, tmp_path):
        # One failed scan must not blink tpu_chip_process_info out (nor flip
        # the legacy pid label): the last good holder set is reused within
        # the bounded-staleness window.
        add_proc(tmp_path, 4242, ["/dev/accel0"])
        real = ProcScanner(proc_root=str(tmp_path))

        class Flaky:
            fail = False

            def scan(self):
                if self.fail:
                    raise RuntimeError("transient")
                return real.scan()

        flaky = Flaky()
        store = SnapshotStore()
        c = make_collector(store, flaky)
        c.poll_once()
        flaky.fail = True
        stats = c.poll_once()
        assert "process_scan" in stats.errors
        snap = store.current()
        assert snap.value("tpu_chip_process_info", process_labels(0, 4242)) == 1.0

    def test_scanner_failure_is_contained(self):
        class BoomScanner:
            def scan(self):
                raise RuntimeError("boom")

        store = SnapshotStore()
        c = make_collector(store, BoomScanner())
        stats = c.poll_once()
        assert stats.ok  # device read fine; scan failure degrades only
        assert "process_scan" in stats.errors
        snap = store.current()
        assert snap.value(
            "tpu_exporter_poll_errors_total", {"source": "process_scan"}
        ) == 1.0
        # Chip metrics unaffected.
        assert snap.value("tpu_exporter_up") == 1.0

    def test_phase_timing_published(self, tmp_path):
        store = SnapshotStore()
        c = make_collector(store, ProcScanner(proc_root=str(tmp_path)))
        c.poll_once()
        snap = store.current()
        assert (
            snap.value("tpu_exporter_poll_duration_seconds", {"phase": "process_scan"})
            is not None
        )

    def test_no_scanner_means_no_family(self):
        store = SnapshotStore()
        c = make_collector(store, None)
        c.poll_once()
        text = store.current().encode().decode()
        assert "tpu_chip_process_info" not in text


class TestNativeParity:
    def _tree(self, tmp_path):
        add_proc(tmp_path, 100, ["/dev/accel0", "/dev/accel0", "/dev/accel1"])
        add_proc(tmp_path, 205, ["/dev/accel2 (deleted)"], comm="wedged",
                 cgroup=CGROUP_V1)
        add_proc(tmp_path, 30, ["/dev/vfio/7"], cgroup=CGROUP_NON_POD)
        add_proc(tmp_path, 40, ["/dev/null"])  # not a holder
        (tmp_path / "not-a-pid").mkdir()

    def test_native_and_python_full_scans_agree(self, tmp_path):
        from tpu_pod_exporter import nativelib

        self._tree(tmp_path)
        s = ProcScanner(proc_root=str(tmp_path))
        if nativelib.load() is None:
            pytest.skip("native lib unavailable")
        native_found = s._native_full_scan()
        assert native_found is not None
        python_found = s._python_full_scan()
        assert native_found == python_found
        assert sorted(native_found) == [30, 100, 205]

    def test_python_fallback_when_native_unavailable(self, tmp_path, monkeypatch):
        from tpu_pod_exporter import nativelib

        self._tree(tmp_path)
        monkeypatch.setattr(nativelib, "load", lambda: None)
        holders = ProcScanner(proc_root=str(tmp_path)).scan()
        assert sorted({h.pid for h in holders}) == [30, 100, 205]
        assert [h.device_path for h in holders if h.pid == 205] == ["/dev/accel2"]

    def test_weird_comm_parity(self, tmp_path):
        # prctl lets a process set comm to nearly anything; both scanners
        # must sanitize identically or the verify cache thrashes.
        from tpu_pod_exporter import nativelib

        add_proc(tmp_path, 90, ["/dev/accel0"], comm="a\rb")
        add_proc(tmp_path, 91, ["/dev/accel1"], comm="\tworker ")
        add_proc(tmp_path, 92, ["/dev/accel2"], comm="odd\tname")
        s = ProcScanner(proc_root=str(tmp_path))
        python_found = s._python_full_scan()
        if nativelib.load() is not None:
            native_found = s._native_full_scan()
            assert native_found == python_found
        comms = {pid: hs[0].comm for pid, hs in python_found.items()}
        assert comms == {90: "a\rb", 91: "worker", 92: "odd?name"}

    def test_native_overflow_falls_back_to_python(self, tmp_path):
        # >16 distinct matching devices in one process: native must refuse
        # (-1) rather than truncate, and scan() must still return the truth.
        from tpu_pod_exporter import nativelib

        add_proc(tmp_path, 95, [f"/dev/accel{i}" for i in range(20)])
        s = ProcScanner(proc_root=str(tmp_path))
        if nativelib.load() is not None:
            assert s._native_full_scan() is None
        holders = s.scan()
        assert len(holders) == 20
