"""Exposition parser + slice aggregator (SURVEY.md §2.8, baseline config 4).

Rollups are fed from real per-host Collector output (encode → parse → fold),
so the aggregator is tested against the exact bytes exporters serve.
"""

import math
import sys
import urllib.request
from pathlib import Path

import pytest

from tpu_pod_exporter.aggregate import SliceAggregator
from tpu_pod_exporter.app import ExporterApp
from tpu_pod_exporter.attribution.fake import FakeAttribution, simple_allocation
from tpu_pod_exporter.backend.fake import FakeBackend, FakeChipScript
from tpu_pod_exporter.collector import Collector
from tpu_pod_exporter.config import ExporterConfig
from tpu_pod_exporter.metrics import SnapshotStore
from tpu_pod_exporter.metrics.parse import ParseError, parse_exposition, parse_families
from tpu_pod_exporter.server import MetricsServer
from tpu_pod_exporter.topology import HostTopology

GIB = 1024**3


class TestParser:
    def test_bare_sample(self):
        (s,) = parse_exposition("tpu_exporter_up 1\n")
        assert s == ("tpu_exporter_up", {}, 1.0)

    def test_labels(self):
        (s,) = parse_exposition('m{a="x",b="y"} 2.5\n')
        assert s.labels == {"a": "x", "b": "y"}
        assert s.value == 2.5

    def test_escapes_roundtrip(self):
        (s,) = parse_exposition('m{a="q\\"uo\\\\te\\nnl"} 1\n')
        assert s.labels == {"a": 'q"uo\\te\nnl'}

    def test_timestamp_dropped(self):
        (s,) = parse_exposition("m 3 1700000000000\n")
        assert s.value == 3.0

    def test_nan_and_inf(self):
        samples = list(parse_exposition("a NaN\nb +Inf\nc -Inf\n"))
        assert math.isnan(samples[0].value)
        assert samples[1].value == math.inf
        assert samples[2].value == -math.inf

    def test_comments_and_blanks_skipped(self):
        text = "# HELP m help\n# TYPE m gauge\n\nm 1\n# EOF\n"
        assert len(list(parse_exposition(text))) == 1

    @pytest.mark.parametrize(
        "bad",
        ['m{a=x} 1', 'm{a="x} 1', "m{=} 1", "m", 'm{a="x"} notanumber', "{} 1"],
    )
    def test_malformed_raises(self, bad):
        with pytest.raises(ParseError):
            list(parse_exposition(bad + "\n"))

    def test_roundtrip_with_own_renderer(self):
        """encode() output must parse back to identical values."""
        backend = FakeBackend(
            chips=2, script=FakeChipScript(hbm_total_bytes=8.0, hbm_used_bytes=2.0)
        )
        store = SnapshotStore()
        Collector(backend, FakeAttribution(), store).poll_once()
        fams = parse_families(store.current().encode().decode())
        assert len(fams["tpu_hbm_used_bytes"]) == 2
        for s in fams["tpu_hbm_used_bytes"]:
            assert s.value == 2.0


def make_host_text(worker_id: int, pod="llm-train-0", chips=4, used_gib=1.0):
    """One v5p host's real exposition bytes."""
    backend = FakeBackend(
        chips=chips,
        script=FakeChipScript(
            hbm_total_bytes=96 * GIB,
            hbm_used_bytes=used_gib * GIB,
            duty_cycle_percent=60.0 + worker_id,
            ici_link_count=6,
            ici_bytes_per_step=1_000_000.0,
        ),
    )
    attr = FakeAttribution(
        [simple_allocation(pod, [str(i) for i in range(chips)], namespace="ml")]
    )
    topo = HostTopology(
        accelerator="v5p-64", slice_name="slice-a",
        host=f"host-{worker_id}", worker_id=str(worker_id),
    )
    store = SnapshotStore()
    c = Collector(backend, attr, store, topology=topo)
    c.poll_once()
    c.poll_once()  # second poll so ICI rates have a dt window
    return store.current().encode().decode()


class StaticFetch:
    """Injectable fetch: target -> canned text, or raise."""

    def __init__(self, pages: dict[str, str], down: set[str] = frozenset()):
        self.pages = pages
        self.down = set(down)

    def __call__(self, target: str, timeout_s: float) -> str:
        if target in self.down:
            raise ConnectionError(f"{target} unreachable")
        return self.pages[target]


class TestSliceAggregator:
    def setup_method(self):
        self.pages = {f"h{w}:8000": make_host_text(w) for w in range(2)}
        self.store = SnapshotStore()

    def agg(self, down=frozenset()):
        return SliceAggregator(
            tuple(self.pages), self.store,
            fetch=StaticFetch(self.pages, down=down),
        )

    def test_slice_rollups(self):
        self.agg().poll_once()
        snap = self.store.current()
        key = {"slice_name": "slice-a", "accelerator": "v5p-64", "family": "tpu"}
        assert snap.value("tpu_slice_chip_count", key) == 8.0
        assert snap.value("tpu_slice_hosts_reporting", key) == 2.0
        assert snap.value("tpu_slice_hbm_used_bytes", key) == 8 * GIB
        assert snap.value("tpu_slice_hbm_total_bytes", key) == 8 * 96 * GIB
        assert snap.value("tpu_slice_hbm_used_percent", key) == pytest.approx(
            100.0 * 8 / (8 * 96)
        )
        # hosts 0 and 1 run at 60/61% duty → mean 60.5 over 8 chips.
        assert snap.value(
            "tpu_slice_tensorcore_duty_cycle_avg_percent", key
        ) == pytest.approx(60.5)
        assert snap.value("tpu_slice_ici_bytes_per_second", key) >= 0.0

    def test_workload_rollups(self):
        self.agg().poll_once()
        snap = self.store.current()
        key = {"pod": "llm-train-0", "namespace": "ml", "slice_name": "slice-a"}
        assert snap.value("tpu_workload_chip_count", key) == 8.0
        assert snap.value("tpu_workload_hosts", key) == 2.0
        assert snap.value("tpu_workload_hbm_used_bytes", key) == 8 * GIB

    def test_down_target_drops_out_and_recovers(self):
        fetch = StaticFetch(self.pages, down={"h1:8000"})
        a = SliceAggregator(tuple(self.pages), self.store, fetch=fetch)
        a.poll_once()
        snap = self.store.current()
        key = {"slice_name": "slice-a", "accelerator": "v5p-64", "family": "tpu"}
        assert snap.value("tpu_aggregator_target_up", {"target": "h1:8000"}) == 0.0
        assert snap.value("tpu_aggregator_target_up", {"target": "h0:8000"}) == 1.0
        assert snap.value("tpu_slice_chip_count", key) == 4.0
        assert snap.value("tpu_slice_hosts_reporting", key) == 1.0
        assert snap.value(
            "tpu_aggregator_scrape_errors_total", {"target": "h1:8000"}
        ) == 1.0
        fetch.down.clear()
        a.poll_once()
        snap = self.store.current()
        assert snap.value("tpu_aggregator_target_up", {"target": "h1:8000"}) == 1.0
        assert snap.value("tpu_slice_chip_count", key) == 8.0
        # Error counter is cumulative, not reset by recovery.
        assert snap.value(
            "tpu_aggregator_scrape_errors_total", {"target": "h1:8000"}
        ) == 1.0

    def test_garbage_in_consumed_family_counts_as_down_without_partial_sums(self):
        self.pages["h1:8000"] = (
            self.pages["h1:8000"] + 'tpu_hbm_used_bytes{oops} not-a-number\n'
        )
        self.agg().poll_once()
        snap = self.store.current()
        assert snap.value("tpu_aggregator_target_up", {"target": "h1:8000"}) == 0.0
        # h1 contributed nothing despite its valid prefix.
        assert snap.value(
            "tpu_slice_chip_count",
            {"slice_name": "slice-a", "accelerator": "v5p-64", "family": "tpu"},
        ) == 4.0

    def test_garbage_outside_consumed_families_is_tolerated(self):
        # The pre-parse name filter (CONSUMED_NAMES) means junk in families
        # the aggregator never folds cannot corrupt sums — so the host
        # stays up and its rollups intact (deliberate trade vs the test
        # above; see parse_exposition's `names` docstring).
        self.pages["h1:8000"] = (
            self.pages["h1:8000"] + 'some_other_metric{oops} not-a-number\n'
        )
        self.agg().poll_once()
        snap = self.store.current()
        assert snap.value("tpu_aggregator_target_up", {"target": "h1:8000"}) == 1.0
        assert snap.value(
            "tpu_slice_chip_count",
            {"slice_name": "slice-a", "accelerator": "v5p-64", "family": "tpu"},
        ) == 8.0

    def test_missing_host_label_not_counted_as_a_host(self):
        # An exporter that omits the host label must not collapse into a
        # phantom host "" in hosts_reporting; its chips still count.
        # (tpu_chip_info is the per-chip presence series chips are counted
        # from — round 4, when tpu_hbm_* became omissible.)
        nohost = (
            'tpu_chip_info{chip_id="0",slice_name="slice-a",'
            'accelerator="v5p-64"} 1\n'
            'tpu_hbm_used_bytes{chip_id="0",slice_name="slice-a",'
            'accelerator="v5p-64"} 1\n'
        )
        pages = {"h0:8000": make_host_text(0), "bare:8000": nohost}
        store = SnapshotStore()
        SliceAggregator(
            tuple(pages), store, fetch=StaticFetch(pages)
        ).poll_once()
        snap = store.current()
        key = {"slice_name": "slice-a", "accelerator": "v5p-64", "family": "tpu"}
        assert snap.value("tpu_slice_hosts_reporting", key) == 1.0
        assert snap.value("tpu_slice_chip_count", key) == 5.0

    def test_unallocated_chips_do_not_create_workloads(self):
        store = SnapshotStore()
        Collector(FakeBackend(chips=2), FakeAttribution(), store).poll_once()
        text = store.current().encode().decode()
        agg_store = SnapshotStore()
        SliceAggregator(
            ("h:1",), agg_store, fetch=StaticFetch({"h:1": text})
        ).poll_once()
        snap = agg_store.current()
        assert parse_families(snap.encode().decode()).get("tpu_workload_chip_count") in (None, [])
        # Chip-level slice rollups still exist (empty slice/accelerator labels).
        assert snap.value(
            "tpu_slice_chip_count", {"slice_name": "", "accelerator": "", "family": "tpu"}
        ) == 2.0

    def test_empty_targets_rejected(self):
        with pytest.raises(ValueError):
            SliceAggregator((), SnapshotStore())


class TestAggregatorOverHTTP:
    def test_end_to_end(self):
        """Real exporter → real scrape → aggregator's own /metrics."""
        backend = FakeBackend(
            chips=4,
            script=FakeChipScript(hbm_total_bytes=96 * GIB, hbm_used_bytes=GIB),
        )
        attr = FakeAttribution(
            [simple_allocation("job-0", ["0", "1", "2", "3"], namespace="ml")]
        )
        cfg = ExporterConfig(
            port=0, host="127.0.0.1", interval_s=0.05,
            accelerator="v5e-16", slice_name="s-e2e", node_name="n0", worker_id="0",
        )
        app = ExporterApp(cfg, backend=backend, attribution=attr)
        app.start()
        agg_store = SnapshotStore()
        server = None
        try:
            agg = SliceAggregator(
                (f"127.0.0.1:{app.port}",), agg_store, timeout_s=5.0
            )
            agg.poll_once()
            server = MetricsServer(agg_store, host="127.0.0.1", port=0)
            server.start()
            with urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/metrics", timeout=5
            ) as resp:
                body = resp.read().decode()
            fams = parse_families(body)
            (chip_count,) = fams["tpu_slice_chip_count"]
            assert chip_count.labels == {
                "slice_name": "s-e2e", "accelerator": "v5e-16",
                "family": "tpu",
            }
            assert chip_count.value == 4.0
            (up,) = fams["tpu_aggregator_target_up"]
            assert up.value == 1.0
        finally:
            if server is not None:
                server.stop()
            app.stop()


class TestMultiSlice:
    def test_two_slices_roll_up_independently(self):
        """One aggregator scraping hosts of two different slices keeps their
        rollups apart (slice identity comes from series labels, not config)."""
        pages = {}
        for sl, workers in (("slice-a", 2), ("slice-b", 1)):
            for w in range(workers):
                backend = FakeBackend(
                    chips=4,
                    script=FakeChipScript(hbm_total_bytes=10.0, hbm_used_bytes=1.0),
                )
                attr = FakeAttribution(
                    [simple_allocation(f"job-{sl}", ["0", "1", "2", "3"], namespace="ml")]
                )
                topo = HostTopology(
                    accelerator="v5p-64", slice_name=sl,
                    host=f"{sl}-h{w}", worker_id=str(w),
                )
                store = SnapshotStore()
                Collector(backend, attr, store, topology=topo).poll_once()
                pages[f"{sl}-h{w}:8000"] = store.current().encode().decode()
        agg_store = SnapshotStore()
        SliceAggregator(
            tuple(pages), agg_store, fetch=StaticFetch(pages)
        ).poll_once()
        snap = agg_store.current()
        a = {"slice_name": "slice-a", "accelerator": "v5p-64", "family": "tpu"}
        b_ = {"slice_name": "slice-b", "accelerator": "v5p-64", "family": "tpu"}
        assert snap.value("tpu_slice_chip_count", a) == 8.0
        assert snap.value("tpu_slice_chip_count", b_) == 4.0
        assert snap.value("tpu_slice_hosts_reporting", a) == 2.0
        assert snap.value("tpu_slice_hosts_reporting", b_) == 1.0
        assert snap.value(
            "tpu_workload_chip_count",
            {"pod": "job-slice-a", "namespace": "ml", "slice_name": "slice-a"},
        ) == 8.0


class TestDefaultFetch:
    def test_gzip_negotiated_and_decompressed(self):
        """default_fetch must transparently handle the exporter's gzip path
        (and servers that ignore Accept-Encoding)."""
        from tpu_pod_exporter.aggregate import default_fetch

        backend = FakeBackend(
            chips=2, script=FakeChipScript(hbm_total_bytes=8.0, hbm_used_bytes=2.0)
        )
        store = SnapshotStore()
        Collector(backend, FakeAttribution(), store).poll_once()
        server = MetricsServer(store, host="127.0.0.1", port=0)
        server.start()
        try:
            text = default_fetch(f"127.0.0.1:{server.port}", timeout_s=5.0)
            fams = parse_families(text)
            assert len(fams["tpu_hbm_used_bytes"]) == 2
        finally:
            server.stop()


class TestParserRobustness:
    def test_unterminated_value_raises_fast(self):
        # The naive value regex backtracked exponentially here; must raise
        # ParseError in well under a second, not hang the aggregation round.
        import time

        bad = 'm{a="' + "x" * 60 + '} 1\n'
        t0 = time.perf_counter()
        with pytest.raises(ParseError):
            list(parse_exposition(bad))
        assert time.perf_counter() - t0 < 1.0

    def test_oversized_label_block_parses_but_is_not_cached(self):
        from tpu_pod_exporter.metrics import parse as parse_mod

        big = 'm{a="' + "y" * 5000 + '"} 1\n'
        (s,) = parse_exposition(big)
        assert len(s.labels["a"]) == 5000
        assert ('a="' + "y" * 5000 + '"') not in parse_mod._BLOCK_CACHE

    def test_parse_exposition_callers_own_labels(self):
        # The ownership copy lives at the parse_exposition boundary (the
        # block cache itself hands out SHARED dicts — layout entries and
        # every line with the same block reuse one object): a caller
        # mutating its ParsedSample.labels must not corrupt later parses.
        text = 'm{a="x"} 1\n'
        (s1,) = parse_exposition(text)
        s1.labels["mutated"] = "yes"
        (s2,) = parse_exposition(text)
        assert s2.labels == {"a": "x"}

    def test_separator_leniency_grandfathered(self):
        # The historical per-character parser accepted any run of ", " as a
        # pair separator; the regex parser must keep that grammar.
        for text in (
            'm{a="x" b="y"} 1\n',     # space-separated
            'm{a="x",,b="y"} 1\n',    # doubled comma
            'm{a="x", b="y",} 1\n',   # trailing comma
            'm{a="x"b="y"} 1\n',      # no separator at all
        ):
            (s,) = parse_exposition(text)
            assert s.labels == {"a": "x", "b": "y"}, text

    def test_round_duration_self_metric(self):
        # uses the TestSliceAggregator-style setup inline: one good target
        pages = {"h0:8000": make_host_text(0)}
        store = SnapshotStore()
        SliceAggregator(tuple(pages), store, fetch=StaticFetch(pages)).poll_once()
        dur = store.current().value("tpu_aggregator_round_duration_seconds", {})
        assert dur is not None and 0.0 <= dur < 60.0


class TestParseCacheConcurrency:
    def test_concurrent_parsers_keep_accounting_consistent(self, monkeypatch):
        """ADVICE r2 #4: the block cache is shared across threads; clears
        racing inserts must not let the byte accounting drift from actual
        residency (a drift would quietly disable or unbound the budget)."""
        import threading

        from tpu_pod_exporter.metrics import parse as parse_mod

        monkeypatch.setattr(parse_mod, "_BLOCK_CACHE", {})
        parse_mod._block_cache_bytes = 0
        # Budget small enough that every thread forces clears continuously.
        monkeypatch.setattr(parse_mod, "_BLOCK_CACHE_MAX_BYTES", 4000)

        def worker(tid):
            for i in range(300):
                text = f'm{{t="{tid}",i="{i}"}} 1\n'
                list(parse_mod.parse_exposition(text))

        threads = [threading.Thread(target=worker, args=(t,)) for t in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        with parse_mod._block_cache_lock:
            actual = sum(
                parse_mod._entry_cost(k) for k in parse_mod._BLOCK_CACHE
            )
            assert parse_mod._block_cache_bytes == actual
        parse_mod._BLOCK_CACHE.clear()
        parse_mod._block_cache_bytes = 0


class TestParseNameFilter:
    def test_filter_skips_unlisted_names(self):
        text = 'a{x="1"} 1\nb{x="2"} 2\nc 3\n'
        names = [s.name for s in parse_exposition(text, names=frozenset({"b", "c"}))]
        assert names == ["b", "c"]

    def test_filter_skips_malformed_unlisted_lines(self):
        # The filter runs before value parsing: garbage in an unconsumed
        # family must not kill the round (documented trade-off).
        text = 'junk{x="1"} not-a-number\nb 2\n'
        (s,) = parse_exposition(text, names=frozenset({"b"}))
        assert s.value == 2.0

    def test_consumed_names_stays_in_sync_with_consume(self):
        """CONSUMED_NAMES is a pre-parse filter: a name folded by _consume
        but missing from the set would be silently dropped from rollups.
        Lock the two together."""
        import inspect
        import re

        from tpu_pod_exporter import aggregate as agg_mod

        src = inspect.getsource(SliceAggregator._consume)
        referenced = set(re.findall(r'"((?:tpu|gpu)_[a-z_]+)"', src))
        assert referenced == set(agg_mod.CONSUMED_NAMES)


class TestUnreadableHbmHostsStillCounted:
    def test_host_with_no_hbm_series_keeps_chip_count_and_reporting(self):
        """Code-review r4: a healthy host on an HBM-less backend (tunnel)
        publishes no tpu_hbm_* series; it must still contribute chips and
        hosts_reporting via tpu_chip_info."""
        from tpu_pod_exporter.backend import ChipInfo, ChipSample, HostSample
        from tpu_pod_exporter.backend.fake import FakeBackend
        from tpu_pod_exporter.collector import Collector

        class NoHbmBackend(FakeBackend):
            def sample(self):
                chips = tuple(
                    ChipSample(
                        info=ChipInfo(
                            chip_id=i, device_path=f"/dev/accel{i}",
                            device_ids=(str(i),),
                        ),
                        hbm_used_bytes=None,
                        hbm_total_bytes=None,
                    )
                    for i in range(4)
                )
                return HostSample(chips=chips,
                                  partial_errors=("hbm unreadable",) * 4)

        store = SnapshotStore()
        topo = HostTopology(
            accelerator="v5p-64", slice_name="slice-a",
            host="host-0", worker_id="0",
        )
        c = Collector(NoHbmBackend(chips=0), FakeAttribution(), store, topology=topo)
        c.poll_once()
        text = store.current().encode().decode()
        assert "tpu_hbm_used_bytes{" not in text  # honesty preserved

        agg_store = SnapshotStore()
        agg = SliceAggregator(
            ("h0:8000",), agg_store, fetch=StaticFetch({"h0:8000": text})
        )
        agg.poll_once()
        agg.close()
        snap = agg_store.current()
        key = {"slice_name": "slice-a", "accelerator": "v5p-64", "family": "tpu"}
        assert snap.value("tpu_slice_chip_count", key) == 4.0
        assert snap.value("tpu_slice_hosts_reporting", key) == 1.0
        # ...but the slice HBM rollups stay ABSENT (not fake zeros): no
        # chip reported a readable HBM value this round.
        assert snap.value("tpu_slice_hbm_used_bytes", key) is None
        assert snap.value("tpu_slice_hbm_total_bytes", key) is None
        assert snap.value("tpu_slice_hbm_used_percent", key) is None


class TestAggregateHonesty:
    """Advisor r4: the absent-beats-fake-zero rule applies to every rollup
    tier — workload HBM, slice percent on mismatched coverage — and mixed
    fleets undercounting presence must be loud."""

    KEY = {"slice_name": "slice-a", "accelerator": "v5p-64", "family": "tpu"}

    def _aggregate(self, text):
        store = SnapshotStore()
        agg = SliceAggregator(
            ("h0:8000",), store, fetch=StaticFetch({"h0:8000": text})
        )
        agg.poll_once()
        agg.close()
        return store.current()

    def test_workload_without_hbm_omits_workload_hbm_series(self):
        # A workload whose pods emitted chip_count but no pod_hbm series
        # (all chips HBM-unreadable) must not publish a fake-0 workload HBM.
        text = (
            'tpu_chip_info{chip_id="0",host="host-0",slice_name="slice-a",'
            'accelerator="v5p-64"} 1\n'
            'tpu_pod_chip_count{pod="train",namespace="ml",'
            'slice_name="slice-a",host="host-0"} 2\n'
        )
        snap = self._aggregate(text)
        wkey = {"pod": "train", "namespace": "ml", "slice_name": "slice-a"}
        assert snap.value("tpu_workload_chip_count", wkey) == 2.0
        assert snap.value("tpu_workload_hbm_used_bytes", wkey) is None

    def test_slice_percent_omitted_when_used_total_coverage_differs(self):
        # Two chips report used, only one reports total (runtime serving
        # bytes_in_use but no bytes_limit on chip 1): a percent over
        # mismatched chip sets would mislead (could read >100%) — omit it.
        rows = []
        for i in range(2):
            rows.append(
                f'tpu_chip_info{{chip_id="{i}",host="host-0",'
                f'slice_name="slice-a",accelerator="v5p-64"}} 1'
            )
            rows.append(
                f'tpu_hbm_used_bytes{{chip_id="{i}",host="host-0",'
                f'slice_name="slice-a",accelerator="v5p-64"}} {GIB}'
            )
        rows.append(
            'tpu_hbm_total_bytes{chip_id="0",host="host-0",'
            'slice_name="slice-a",accelerator="v5p-64"} ' + str(GIB * 2)
        )
        snap = self._aggregate("\n".join(rows) + "\n")
        assert snap.value("tpu_slice_hbm_used_bytes", self.KEY) == 2 * GIB
        assert snap.value("tpu_slice_hbm_total_bytes", self.KEY) == 2 * GIB
        assert snap.value("tpu_slice_hbm_used_percent", self.KEY) is None

    def test_percent_present_when_coverage_matches(self):
        snap = self._aggregate(make_host_text(0))
        assert snap.value("tpu_slice_hbm_used_percent", self.KEY) is not None

    def test_slice_percent_omitted_on_disjoint_equal_count_coverage(self):
        # Code-review r5: equal COUNTS over disjoint chip sets (chip 0
        # used-only + chip 1 total-only) must not publish used_A/total_B.
        text = (
            'tpu_hbm_used_bytes{chip_id="0",host="host-0",'
            'slice_name="slice-a",accelerator="v5p-64"} ' + str(3 * GIB) + "\n"
            'tpu_hbm_total_bytes{chip_id="1",host="host-0",'
            'slice_name="slice-a",accelerator="v5p-64"} ' + str(GIB) + "\n"
        )
        snap = self._aggregate(text)
        # used/total sums still publish (each was read somewhere)...
        assert snap.value("tpu_slice_hbm_used_bytes", self.KEY) == 3 * GIB
        assert snap.value("tpu_slice_hbm_total_bytes", self.KEY) == GIB
        # ...but a percent over different chips (here it would read 300%)
        # is omitted.
        assert snap.value("tpu_slice_hbm_used_percent", self.KEY) is None

    def test_slice_percent_omitted_on_zero_total(self):
        # Same rule as the per-chip series: percent of a zero capacity is
        # undefined — 0.0 would read as "idle".
        text = (
            'tpu_hbm_used_bytes{chip_id="0",host="host-0",'
            'slice_name="slice-a",accelerator="v5p-64"} ' + str(GIB) + "\n"
            'tpu_hbm_total_bytes{chip_id="0",host="host-0",'
            'slice_name="slice-a",accelerator="v5p-64"} 0\n'
        )
        snap = self._aggregate(text)
        assert snap.value("tpu_slice_hbm_total_bytes", self.KEY) == 0.0
        assert snap.value("tpu_slice_hbm_used_percent", self.KEY) is None

    def test_orphan_warning_fires_for_total_only_host(self, caplog):
        # Code-review r5: an old exporter contributing only TOTAL rows
        # (its used was unreadable) must still trip the mixed-fleet warning.
        import logging

        text = (
            'tpu_hbm_total_bytes{chip_id="0",host="old-host",'
            'slice_name="slice-a",accelerator="v5p-64"} 1\n'
        )
        with caplog.at_level(logging.WARNING, "tpu_pod_exporter.aggregate"):
            self._aggregate(text)
        assert any("old-host" in r.message for r in caplog.records)

    def test_slice_ici_omitted_when_no_chip_reported_ici(self):
        # Code-review r5: a fleet on a runtime without ICI counters must
        # not publish tpu_slice_ici_bytes_per_second 0.0 ("idle" != "unmeasured").
        text = (
            'tpu_chip_info{chip_id="0",host="host-0",slice_name="slice-a",'
            'accelerator="v5p-64"} 1\n'
        )
        snap = self._aggregate(text)
        assert snap.value("tpu_slice_chip_count", self.KEY) == 1.0
        assert snap.value("tpu_slice_ici_bytes_per_second", self.KEY) is None

    def test_orphan_hbm_host_warns_once(self, caplog):
        # A host contributing HBM sums but zero chip_info rows (exporter
        # older than the unconditional-chip_info change) must log loudly:
        # its chips/hosts_reporting silently undercount otherwise.
        import logging

        text = (
            'tpu_hbm_used_bytes{chip_id="0",host="old-host",'
            'slice_name="slice-a",accelerator="v5p-64"} 1\n'
        )
        with caplog.at_level(logging.WARNING, "tpu_pod_exporter.aggregate"):
            self._aggregate(text)
        assert any(
            "old-host" in r.message and "chip_info" in r.message
            for r in caplog.records
        )


class TestAggregatorDebugVars:
    def test_layout_sizes_and_targets(self):
        pages = {"h0:8000": make_host_text(0)}
        store = SnapshotStore()
        agg = SliceAggregator(
            ("h0:8000", "down:8000"), store,
            fetch=StaticFetch(pages, down={"down:8000"}),
        )
        agg.poll_once()
        agg.close()
        dv = agg.debug_vars()
        assert dv["targets"] == ["h0:8000", "down:8000"]
        assert dv["layout_entries"]["h0:8000"] > 100  # parsed a real body
        assert dv["layout_entries"]["down:8000"] == 0  # never reachable
        assert dv["layout_oversize"] == {"h0:8000": False, "down:8000": False}

    def test_aggregator_publishes_loop_overruns(self):
        # Same contract as tpu_exporter_poll_overruns_total: the one
        # signal that says --interval-s is too tight for the round cost.
        pages = {"h0:8000": make_host_text(0)}
        store = SnapshotStore()
        agg = SliceAggregator(
            ("h0:8000",), store, fetch=StaticFetch(pages),
            loop_overruns_fn=lambda: 3,
        )
        agg.poll_once()
        agg.close()
        assert store.current().value(
            "tpu_aggregator_poll_overruns_total", {}
        ) == 3.0
        # And absent (not zero-faked... zero IS the honest value here, but
        # the series must not exist at all when no loop is attached).
        store2 = SnapshotStore()
        agg2 = SliceAggregator(
            ("h0:8000",), store2, fetch=StaticFetch(pages)
        )
        agg2.poll_once()
        agg2.close()
        assert store2.current().value(
            "tpu_aggregator_poll_overruns_total", {}
        ) is None

    def test_aggregator_publishes_own_cpu_and_rss(self):
        # Same auditability contract as the exporter's self-metrics: the
        # aggregator's slice-scale cost budget (BASELINE.md) must be
        # checkable from its exposition alone.
        pages = {"h0:8000": make_host_text(0)}
        store = SnapshotStore()
        agg = SliceAggregator(("h0:8000",), store, fetch=StaticFetch(pages))
        agg.poll_once()
        agg.close()
        snap = store.current()
        cpu = snap.value("tpu_aggregator_cpu_seconds_total", {})
        rss = snap.value("tpu_aggregator_rss_bytes", {})
        if sys.platform == "linux":  # absent-off-POSIX/Linux is the contract
            assert cpu is not None and cpu > 0  # this test itself burned CPU
            assert rss is not None and rss > 10 * 1024 * 1024  # a real RSS

    def test_rollups_exact_while_target_crosses_cap(self):
        # Integration churn for the oversize state machine: one target's
        # body grows past the layout-cache cap mid-run (chip hotplug /
        # label explosion) then shrinks back. Every round's rollups must
        # be exact — the cached, uncached, and re-cached parse paths all
        # feed the same fold — and debug vars must track the transitions.
        small = make_host_text(0, chips=2)
        big = make_host_text(0, chips=8)
        pages = {"h0:8000": small}
        store = SnapshotStore()
        agg = SliceAggregator(("h0:8000",), store, fetch=StaticFetch(pages))
        key = {"slice_name": "slice-a", "accelerator": "v5p-64", "family": "tpu"}
        try:
            agg.poll_once()
            assert store.current().value("tpu_slice_chip_count", key) == 2.0
            (layout,) = agg._parse_layouts.values()
            assert layout.entries and not layout.oversize_logged
            # Shrink the cap under the CURRENT body so the next round is
            # oversize without needing a 32k-line fixture.
            layout.max_entries = small.count("\n") // 2
            pages["h0:8000"] = big
            agg.poll_once()
            assert store.current().value("tpu_slice_chip_count", key) == 8.0
            assert layout.oversize_logged and layout.entries == []
            agg.poll_once()  # steady-state oversize round
            assert store.current().value("tpu_slice_chip_count", key) == 8.0
            layout.max_entries = 32768
            pages["h0:8000"] = small
            agg.poll_once()  # shrink-back: re-enters the cache
            assert store.current().value("tpu_slice_chip_count", key) == 2.0
            assert layout.entries and not layout.oversize_logged
            agg.poll_once()  # warm round on the re-cached layout
            assert store.current().value("tpu_slice_chip_count", key) == 2.0
        finally:
            agg.close()

    def test_oversize_target_distinguishable_from_down(self):
        # layout_entries=0 is ambiguous (down vs deliberately uncached);
        # layout_oversize disambiguates so an operator doesn't misdiagnose
        # a healthy oversize target as down (code-review r5).
        pages = {"h0:8000": make_host_text(0)}
        store = SnapshotStore()
        agg = SliceAggregator(
            ("h0:8000",), store, fetch=StaticFetch(pages),
        )
        for layout in agg._parse_layouts.values():
            layout.max_entries = 10  # force the oversize path
        agg.poll_once()
        agg.close()
        dv = agg.debug_vars()
        assert dv["layout_entries"]["h0:8000"] == 0
        assert dv["layout_oversize"]["h0:8000"] is True


class TestRealHardwareExposition:
    """tests/fixtures/real-metrics-r5.txt is a VERBATIM /metrics body served
    by this exporter running `--backend jax` against the tunneled TPU v5
    lite chip (round 5, 05:33Z window) — the one place a real-hardware
    exposition exercises the parse + aggregation pipeline in CI. Its
    load-bearing properties: chip_info presence WITHOUT any tpu_hbm_*
    series (memory_stats is None through the tunnel — absent beats
    fake-zero on the wire), histogram families, and self-metrics."""

    FIXTURE = (
        Path(__file__).resolve().parent / "fixtures" / "real-metrics-r5.txt"
    )

    def test_parses_and_folds_through_aggregator(self):
        body = self.FIXTURE.read_text()
        assert 'device_kind="TPU v5 lite"' in body
        # HELP/TYPE headers are rendered for declared families, but not a
        # single HBM SAMPLE is on the real wire (absent beats fake-zero).
        assert "\ntpu_hbm_used_bytes{" not in body
        assert "\ntpu_hbm_total_bytes{" not in body
        store = SnapshotStore()
        agg = SliceAggregator(
            ("real:8000",), store, fetch=StaticFetch({"real:8000": body})
        )
        agg.poll_once()
        agg.close()
        snap = store.current()
        key = {"slice_name": "", "accelerator": "v5e", "family": "tpu"}
        assert snap.value("tpu_slice_chip_count", key) == 1.0
        assert snap.value("tpu_slice_hosts_reporting", key) == 1.0
        # No HBM samples on the wire -> no slice HBM rollups fabricated.
        assert snap.value("tpu_slice_hbm_used_bytes", key) is None
        assert snap.value("tpu_slice_hbm_used_percent", key) is None

    def test_real_aggregate_output_fixture_is_honest(self):
        """tests/fixtures/real-aggregate-r5.txt: the AGGREGATOR's own
        /metrics, captured while it scraped the exporter on the real
        tunneled chip — the full pipeline (silicon → exporter →
        aggregator) as served. The rollups must show the chip present and
        the target up, with NO slice-HBM series fabricated from a chip
        whose HBM was unreadable."""
        body = (
            Path(__file__).resolve().parent
            / "fixtures" / "real-aggregate-r5.txt"
        ).read_text()
        fams = {
            name: dict((tuple(sorted(s.labels.items())), s.value) for s in ss)
            for name, ss in parse_families(body).items()
        }
        key = tuple(sorted({"slice_name": "", "accelerator": "v5e"}.items()))
        assert fams["tpu_slice_chip_count"][key] == 1.0
        assert fams["tpu_slice_hosts_reporting"][key] == 1.0
        assert all(v == 1.0 for v in fams["tpu_aggregator_target_up"].values())
        assert "tpu_slice_hbm_used_bytes" not in fams
        assert "tpu_slice_hbm_used_percent" not in fams

    def test_layout_parser_roundtrips_the_real_body(self):
        from tpu_pod_exporter.metrics.parse import (
            LayoutCache,
            parse_exposition,
            parse_exposition_layout,
        )

        body = self.FIXTURE.read_text()
        names = frozenset({"tpu_chip_info", "tpu_exporter_up"})
        layout = LayoutCache()
        cold = parse_exposition_layout(body, names, layout)
        warm = parse_exposition_layout(body, names, layout)
        assert [tuple(s) for s in cold] == [tuple(s) for s in warm]
        assert [tuple(s) for s in cold] == [
            tuple(s) for s in parse_exposition(body, names)
        ]
        assert len(cold) == 2  # one chip_info + up


class TestAggregatorHistograms:
    def test_round_and_scrape_histograms_exposed_and_om_valid(self):
        from prometheus_client.openmetrics.parser import (
            text_string_to_metric_families as om_parse,
        )

        pages = {"h0:8000": make_host_text(0)}
        store = SnapshotStore()
        agg = SliceAggregator(
            tuple(pages), store, fetch=StaticFetch(pages)
        )
        agg.poll_once()
        agg.poll_once()
        agg.poll_once()
        agg.close()
        om = store.current().encode_openmetrics().decode()
        fams = {f.name: f for f in om_parse(om)}
        scr = fams["tpu_aggregator_target_scrape_seconds"]
        assert scr.type == "histogram"
        count = next(
            s.value for s in scr.samples if s.name.endswith("_count")
        )
        assert count == 3.0  # one target x three rounds
        rnd = fams["tpu_aggregator_round_seconds"]
        assert rnd.type == "histogram"
        # Round durations observe after the swap: snapshot 3 carries 2.
        rcount = next(
            s.value for s in rnd.samples if s.name.endswith("_count")
        )
        assert rcount == 2.0

    def test_failed_scrapes_excluded_from_scrape_histogram(self):
        # A down target's timeout duration must not pollute the pooled
        # latency distribution (it would pin p99 at the top bucket).
        pages = {"up:8000": make_host_text(0), "down:8000": ""}
        store = SnapshotStore()
        agg = SliceAggregator(
            tuple(pages), store,
            fetch=StaticFetch(pages, down={"down:8000"}),
        )
        agg.poll_once()
        agg.poll_once()
        agg.close()
        text = store.current().encode().decode()
        (count_line,) = [
            l for l in text.splitlines()
            if l.startswith("tpu_aggregator_target_scrape_seconds_count")
        ]
        assert float(count_line.split()[-1]) == 2.0  # up target only, 2 rounds


class TestMultisliceRollups:
    """Cross-slice (multi-slice group) rollups joined via tpu_host_info
    (BASELINE config 5: 2x v5p-128 over DCN)."""

    def _host_text(self, slice_name, worker, group="ms-group-a", nslices="2"):
        backend = FakeBackend(
            chips=2,
            script=FakeChipScript(
                hbm_total_bytes=8 * GIB, hbm_used_bytes=GIB,
                ici_link_count=2, ici_bytes_per_step=1000.0,
                dcn_link_count=1, dcn_bytes_per_step=500.0,
            ),
        )
        topo = HostTopology(
            accelerator="v5p-128", slice_name=slice_name,
            host=f"{slice_name}-h{worker}", worker_id=str(worker),
            multislice_group=group, num_slices=nslices,
        )
        store = SnapshotStore()
        c = Collector(backend, FakeAttribution(), store, topology=topo)
        c.poll_once()
        c.poll_once()  # second poll so ICI/DCN rates exist
        return store.current().encode().decode()

    def _aggregate(self, pages):
        store = SnapshotStore()
        agg = SliceAggregator(tuple(pages), store, fetch=StaticFetch(pages))
        agg.poll_once()
        agg.close()
        return store.current()

    def test_two_slices_roll_up_into_their_group(self):
        pages = {
            f"{s}h{w}:8000": self._host_text(s, w)
            for s in ("s0", "s1") for w in (0, 1)
        }
        snap = self._aggregate(pages)
        g = {"multislice_group": "ms-group-a"}
        assert snap.value("tpu_multislice_slices_reporting", g) == 2.0
        assert snap.value("tpu_multislice_expected_slices", g) == 2.0
        assert snap.value("tpu_multislice_hosts_reporting", g) == 4.0
        assert snap.value("tpu_multislice_chip_count", g) == 8.0
        assert snap.value("tpu_multislice_hbm_used_bytes", g) == 8 * GIB
        assert snap.value("tpu_multislice_ici_bytes_per_second", g) > 0
        assert snap.value("tpu_multislice_dcn_bytes_per_second", g) > 0
        # The per-slice DCN rollup exists alongside the group one.
        skey = {"slice_name": "s0", "accelerator": "v5p-128", "family": "tpu"}
        assert snap.value("tpu_slice_dcn_bytes_per_second", skey) > 0

    def test_missing_slice_shows_in_reporting_vs_expected(self):
        # Only slice s0 scrapes; expected_slices (from MEGASCALE_NUM_SLICES)
        # stays 2 — the alertable gap for a slice that fell out.
        pages = {f"s0h{w}:8000": self._host_text("s0", w) for w in (0, 1)}
        snap = self._aggregate(pages)
        g = {"multislice_group": "ms-group-a"}
        assert snap.value("tpu_multislice_slices_reporting", g) == 1.0
        assert snap.value("tpu_multislice_expected_slices", g) == 2.0

    def test_two_groups_stay_separate(self):
        pages = {
            "a0:8000": self._host_text("s0", 0, group="group-a", nslices="1"),
            "b0:8000": self._host_text("s1", 0, group="group-b", nslices="1"),
        }
        snap = self._aggregate(pages)
        assert snap.value(
            "tpu_multislice_chip_count", {"multislice_group": "group-a"}
        ) == 2.0
        assert snap.value(
            "tpu_multislice_chip_count", {"multislice_group": "group-b"}
        ) == 2.0

    def test_single_slice_without_group_emits_no_group_series(self):
        pages = {"h0:8000": make_host_text(0)}  # no multislice membership
        snap = self._aggregate(pages)
        text = snap.encode().decode()
        assert "tpu_multislice_chip_count{" not in text
        assert "tpu_multislice_slices_reporting{" not in text

    def test_dcn_omitted_when_no_chip_reports_it(self):
        # make_host_text chips have ICI but no DCN links: slice DCN and
        # group DCN must be ABSENT, not 0.0.
        pages = {
            "h0:8000": self._host_text("s0", 0),
        }
        # Re-render without DCN by using the plain host text:
        pages["h1:8000"] = make_host_text(1)
        snap = self._aggregate(pages)
        assert snap.value(
            "tpu_slice_dcn_bytes_per_second",
            {"slice_name": "slice-a", "accelerator": "v5p-64", "family": "tpu"},
        ) is None


class TestAggregatorCli:
    def test_cli_end_to_end_with_sigterm_drain(self):
        """python -m tpu_pod_exporter.aggregate against a live exporter:
        serves rollups over HTTP, drains cleanly on SIGTERM (the deploy
        manifest's termination path)."""
        import signal
        import socket
        import subprocess
        import sys
        import time

        from tpu_pod_exporter.app import ExporterApp

        app = ExporterApp(
            ExporterConfig(
                port=0, host="127.0.0.1", interval_s=0.2,
                backend="fake", fake_chips=2, attribution="none",
                accelerator="v4-8", slice_name="sa", node_name="n0",
            )
        )
        app.start()
        # Grab a free port for the aggregator (bind+close; tiny race is
        # acceptable in CI, and EADDRINUSE would fail loudly anyway).
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        agg_port = s.getsockname()[1]
        s.close()
        import tempfile

        logf = tempfile.NamedTemporaryFile(
            mode="w+", suffix=".log", delete=False
        )
        proc = subprocess.Popen(
            [sys.executable, "-m", "tpu_pod_exporter.aggregate",
             "--targets", f"127.0.0.1:{app.port}",
             "--host", "127.0.0.1", "--port", str(agg_port),
             "--interval-s", "0.2", "--log-format", "json"],
            stderr=logf,
        )
        try:
            deadline = time.monotonic() + 20
            body = ""
            while time.monotonic() < deadline:
                try:
                    with urllib.request.urlopen(
                        f"http://127.0.0.1:{agg_port}/metrics", timeout=2
                    ) as r:
                        body = r.read().decode()
                    if "tpu_slice_chip_count" in body:
                        break
                except OSError:
                    pass
                time.sleep(0.2)
            assert 'tpu_slice_chip_count{slice_name="sa",accelerator="v4-8",family="tpu"} 2' in body
            assert "tpu_aggregator_target_up" in body
            proc.send_signal(signal.SIGTERM)
            assert proc.wait(timeout=15) == 0  # clean drain
            # --log-format json end to end: every emitted CLI log line is
            # a Cloud-Logging-shaped object (severity + message).
            import json as json_mod
            import os

            logf.flush()
            lines = [
                ln for ln in open(logf.name).read().splitlines() if ln.strip()
            ]
            assert lines, "aggregator emitted no log lines"
            for ln in lines:
                obj = json_mod.loads(ln)
                assert "severity" in obj and "message" in obj, ln
            os.unlink(logf.name)
        finally:
            if proc.poll() is None:
                proc.kill()
            app.stop()


class TestLabelStringMemo:
    """Label strings are deduplicated through a bounded memo (NOT
    sys.intern, whose table never releases — a slow leak under pod-name
    churn). Dedup must be observable, the bound enforced by wholesale
    clear, and degenerate strings excluded."""

    def test_identical_values_share_one_string_across_blocks(self):
        from tpu_pod_exporter.metrics.parse import parse_families

        # The two blocks must DIFFER (chip 7 vs 8): byte-identical blocks
        # already share strings via the block cache's shallow copy, which
        # would pass even with the memo reverted (code-review r5). Only
        # the memo can dedup the repeated pod value across distinct blocks.
        body = 'm{pod="train-0",chip="7"} 1\nm2{pod="train-0",chip="8"} 2\n'
        fams = parse_families(body)
        (s1,), (s2,) = fams["m"], fams["m2"]
        assert s1.labels["pod"] is s2.labels["pod"]  # same object via memo
        assert s1.labels["chip"] == "7" and s2.labels["chip"] == "8"

    def test_memo_bounded_and_skips_oversize(self):
        from tpu_pod_exporter.metrics import parse as parse_mod

        parse_mod._STR_MEMO.clear()
        huge = "x" * (parse_mod._STR_MEMO_MAX_LEN + 1)
        assert parse_mod._memo_str(huge) == huge
        assert huge not in parse_mod._STR_MEMO  # degenerate value excluded
        for i in range(parse_mod._STR_MEMO_MAX + 10):
            parse_mod._memo_str(f"v{i}")
        assert len(parse_mod._STR_MEMO) <= parse_mod._STR_MEMO_MAX


class TestLayoutParser:
    """parse_exposition_layout: value-only re-parse between churn events
    (VERDICT r4 #6 — the parse-side twin of the exporter's PrefixCache)."""

    NAMES = frozenset({"m", "tpu_x"})

    def _both(self, texts):
        """Parse a sequence of bodies through one LayoutCache; assert each
        round equals the reference parser's output."""
        from tpu_pod_exporter.metrics.parse import (
            LayoutCache,
            parse_exposition_layout,
        )

        layout = LayoutCache()
        for text in texts:
            got = parse_exposition_layout(text, self.NAMES, layout)
            want = [
                (s.name, s.labels, s.value)
                for s in parse_exposition(text, names=self.NAMES)
            ]
            assert got == want, text
        return layout

    def test_steady_state_values_change(self):
        t1 = 'm{a="1"} 5\nother{a="1"} 1\nm{a="2"} 6\ntpu_x 7\n'
        t2 = 'm{a="1"} 50\nother{a="1"} 2\nm{a="2"} 60\ntpu_x 70\n'
        layout = self._both([t1, t2, t2, t1])
        # Labels dicts are REUSED across rounds (that's the point).
        from tpu_pod_exporter.metrics.parse import parse_exposition_layout

        r1 = parse_exposition_layout(t1, self.NAMES, layout)
        r2 = parse_exposition_layout(t2, self.NAMES, layout)
        assert r1[0][1] is r2[0][1]

    def test_churn_falls_back_then_recovers(self):
        t1 = 'm{a="1"} 5\nm{a="2"} 6\n'
        t2 = 'm{a="1"} 5\nm{a="3"} 6\nm{a="2"} 7\n'  # inserted series
        t3 = 'm{a="3"} 1\n'                          # shrunk body
        self._both([t1, t2, t2, t3, t1])

    def test_comments_and_skipped_lines(self):
        t = (
            "# HELP m help\n# TYPE m gauge\n"
            'm{a="1"} 1\n'
            'skipped_metric{a="1"} 2\n'
            "skipped_bare 3\n\n"
        )
        self._both([t, t])

    def test_prefix_boundary_no_false_positive(self):
        # "m" cached as a bare-name prefix must not claim "m2 1" (a
        # DIFFERENT metric whose name merely extends the prefix).
        t1 = "m 1\n"
        t2 = "m2 1\n"
        from tpu_pod_exporter.metrics.parse import (
            LayoutCache,
            parse_exposition_layout,
        )

        layout = LayoutCache()
        assert parse_exposition_layout(t1, self.NAMES, layout) == [("m", {}, 1.0)]
        assert parse_exposition_layout(t2, self.NAMES, layout) == []

    def test_timestamps_dropped_on_hit_path(self):
        t1 = 'm{a="1"} 5 1700000000\n'
        t2 = 'm{a="1"} 6 1700000001\n'
        self._both([t1, t2])

    def test_parse_error_leaves_cache_untouched(self):
        from tpu_pod_exporter.metrics.parse import (
            LayoutCache,
            ParseError,
            parse_exposition_layout,
        )

        good = 'm{a="1"} 5\n'
        layout = LayoutCache()
        parse_exposition_layout(good, self.NAMES, layout)
        entries_before = layout.entries
        with pytest.raises(ParseError):
            parse_exposition_layout('m{a="1"} not-a-number\n', self.NAMES, layout)
        assert layout.entries is entries_before  # untouched
        # And the good body still parses via the cache afterwards.
        assert parse_exposition_layout(good, self.NAMES, layout) == [
            ("m", {"a": "1"}, 5.0)
        ]

    def test_bad_value_on_cached_prefix_still_raises(self):
        # A cached prefix whose VALUE goes malformed must raise like the
        # reference parser, not silently skip.
        from tpu_pod_exporter.metrics.parse import (
            LayoutCache,
            ParseError,
            parse_exposition_layout,
        )

        layout = LayoutCache()
        parse_exposition_layout('m{a="1"} 5\n', self.NAMES, layout)
        with pytest.raises(ParseError):
            parse_exposition_layout('m{a="1"} zzz\n', self.NAMES, layout)

    def test_escaped_labels_roundtrip(self):
        t = 'm{a="q\\"uote",b="back\\\\slash\\n"} 5\n'
        self._both([t, t])

    def test_oversized_body_never_cached_but_parses_correctly(
        self, caplog, monkeypatch
    ):
        import logging

        from tpu_pod_exporter.metrics import parse as parse_mod
        from tpu_pod_exporter.metrics.parse import (
            LayoutCache,
            parse_exposition_layout,
        )
        from tpu_pod_exporter.utils import RateLimitedLogger

        # Fresh unthrottled limiter: the module-global one may have been
        # consumed by an earlier test in this session.
        monkeypatch.setattr(
            parse_mod, "_rlog",
            RateLimitedLogger(parse_mod.log, min_interval_s=0.0),
        )
        layout = LayoutCache(max_entries=3)
        text = "m 1\nm 2\nm 3\nm 4\n"  # 5 entries incl. trailing blank
        with caplog.at_level(logging.WARNING, "tpu_pod_exporter.metrics.parse"):
            r1 = parse_exposition_layout(text, self.NAMES, layout)
            r2 = parse_exposition_layout(text, self.NAMES, layout)
        assert r1 == r2 == [("m", {}, float(i)) for i in (1, 2, 3, 4)]
        assert layout.entries == []  # never cached
        assert sum("layout cache cap" in r.message for r in caplog.records) == 1

    def test_oversize_transition_drops_native_buffers(self):
        # A target whose body GROWS past the cap must release the native
        # ctypes buffers built while it was small — they hold a body's
        # worth of encoded prefixes (code-review r5).
        from tpu_pod_exporter.metrics.parse import (
            LayoutCache,
            parse_exposition_layout,
        )

        layout = LayoutCache(max_entries=4)
        parse_exposition_layout("m 1\nm 2\n", self.NAMES, layout)
        # Simulate the native arrays having been built for the small body.
        layout.native_built_for = layout.entries
        layout.native_keybytes = [b"m"]
        big = "m 1\nm 2\nm 3\nm 4\nm 5\n"
        parse_exposition_layout(big, self.NAMES, layout)
        assert layout.entries == []
        assert layout.native_built_for is None
        assert layout.native_keybytes is None

    def test_oversize_flag_clears_on_shrink_back_and_rewarns(
        self, caplog, monkeypatch
    ):
        # oversize_logged tracks the CURRENT condition: a body that shrinks
        # back under the cap re-enters the cache and clears the flag, and a
        # later genuine re-oversize warns again (code-review r5: a sticky
        # flag misreported recovered targets as still slow).
        import logging

        from tpu_pod_exporter.metrics import parse as parse_mod
        from tpu_pod_exporter.metrics.parse import (
            LayoutCache,
            parse_exposition_layout,
        )
        from tpu_pod_exporter.utils import RateLimitedLogger

        # Unthrottled limiter so both warnings emit deterministically.
        monkeypatch.setattr(
            parse_mod, "_rlog",
            RateLimitedLogger(parse_mod.log, min_interval_s=0.0),
        )
        layout = LayoutCache(max_entries=4)
        big = "m 1\nm 2\nm 3\nm 4\nm 5\n"
        small = "m 1\nm 2\n"
        with caplog.at_level(logging.WARNING, "tpu_pod_exporter.metrics.parse"):
            parse_exposition_layout(big, self.NAMES, layout)
            assert layout.oversize_logged
            parse_exposition_layout(small, self.NAMES, layout)
            assert not layout.oversize_logged
            assert layout.entries  # re-cached
            parse_exposition_layout(big, self.NAMES, layout)
            assert layout.oversize_logged
        assert sum("layout cache cap" in r.message for r in caplog.records) == 2

    def test_oversize_flap_warnings_rate_limited(self, caplog, monkeypatch):
        # A body flapping across the cap boundary every round must not warn
        # every other round (~1800 lines/hour at 1 s polls — code-review
        # r5): the module-global RateLimitedLogger admits one line per
        # window across all targets.
        import logging

        from tpu_pod_exporter.metrics import parse as parse_mod
        from tpu_pod_exporter.metrics.parse import (
            LayoutCache,
            parse_exposition_layout,
        )
        from tpu_pod_exporter.utils import RateLimitedLogger

        monkeypatch.setattr(
            parse_mod, "_rlog",
            RateLimitedLogger(parse_mod.log, min_interval_s=60.0, clock=lambda: 0.0),
        )
        layout = LayoutCache(max_entries=4)
        big = "m 1\nm 2\nm 3\nm 4\nm 5\n"
        small = "m 1\nm 2\n"
        with caplog.at_level(logging.WARNING, "tpu_pod_exporter.metrics.parse"):
            for _ in range(10):  # 10 full flap cycles
                parse_exposition_layout(big, self.NAMES, layout)
                parse_exposition_layout(small, self.NAMES, layout)
        assert sum("layout cache cap" in r.message for r in caplog.records) == 1

    def test_torn_undercap_scrape_does_not_clear_oversize_flag(self):
        # A target in the oversize state returns one truncated under-cap
        # body with a malformed line: the ParseError round must leave ALL
        # cache state untouched — flag included — or debug_vars briefly
        # reports layout_entries=0 with layout_oversize=False, the exact
        # "looks down" misdiagnosis the flag prevents (code-review r5).
        from tpu_pod_exporter.metrics.parse import (
            LayoutCache,
            ParseError,
            parse_exposition_layout,
        )

        layout = LayoutCache(max_entries=4)
        parse_exposition_layout("m 1\nm 2\nm 3\nm 4\nm 5\n", self.NAMES, layout)
        assert layout.oversize_logged and layout.entries == []
        with pytest.raises(ParseError):
            parse_exposition_layout("m 1\nm zzz\n", self.NAMES, layout)
        assert layout.oversize_logged  # condition never actually cleared
        assert layout.entries == []
        # A clean under-cap round IS recovery: flag clears, body re-caches.
        parse_exposition_layout("m 1\nm 2\n", self.NAMES, layout)
        assert not layout.oversize_logged and layout.entries

    def test_oversize_parse_error_leaves_warm_cache_intact(self):
        # Contract: "On ParseError the cache is left untouched." A warm
        # small-body layout followed by an oversize body with a malformed
        # line must keep the warm layout so the target's recovery round
        # gets the value-only hit path, not a cold parse (code-review r5).
        from tpu_pod_exporter.metrics.parse import (
            LayoutCache,
            ParseError,
            parse_exposition_layout,
        )

        layout = LayoutCache(max_entries=4)
        parse_exposition_layout("m 1\nm 2\n", self.NAMES, layout)
        warm = layout.entries
        assert warm
        bad_big = "m 1\nm 2\nm 3\nm zzz\nm 5\n"
        with pytest.raises(ParseError):
            parse_exposition_layout(bad_big, self.NAMES, layout)
        assert layout.entries is warm  # untouched
        assert not layout.oversize_logged  # warning deferred to a good round
        # Recovery with the original small body: still a cache hit.
        r = parse_exposition_layout("m 7\nm 8\n", self.NAMES, layout)
        assert r == [("m", {}, 7.0), ("m", {}, 8.0)]
        assert layout.entries is warm

    def test_brace_corrupted_tail_on_warm_prefix_still_raises(self):
        # Code-review r5 repro: two lines joined by a lost newline. The
        # reference parser's rfind('}') picks the LATER brace and raises
        # on the malformed block; a warm prefix hit must not silently
        # accept the first sample and drop the second.
        from tpu_pod_exporter.metrics.parse import (
            LayoutCache,
            ParseError,
            parse_exposition_layout,
        )

        layout = LayoutCache()
        parse_exposition_layout('m{a="1"} 5\nm{a="2"} 6\n', self.NAMES, layout)
        with pytest.raises(ParseError):
            parse_exposition_layout(
                'm{a="1"} 5 m{a="2"} 6\n', self.NAMES, layout
            )


class TestRoundRecordReplay:
    """RoundRecorder/ReplayFetch — the aggregator twin of the exporter's
    record/replay backend (SURVEY §5 checkpoint/resume): capture a live
    incident's fetched bodies, replay them deterministically offline."""

    def _roll(self, tmp_path, rounds):
        """Record `rounds` (list of {target: body-or-None}) and return the
        recording path."""
        from tpu_pod_exporter.aggregate import RoundRecorder

        path = str(tmp_path / "incident.jsonl")
        rec = RoundRecorder(path, wallclock=lambda: 123.0)
        for bodies in rounds:
            rec.record([(t, b, 0.01) for t, b in bodies.items()])
        rec.close()
        return path

    def test_replay_reproduces_rollups_and_outage(self, tmp_path):
        from tpu_pod_exporter.aggregate import ReplayFetch, SliceAggregator

        b0 = make_host_text(0)
        b1 = make_host_text(1)
        path = self._roll(tmp_path, [
            {"h0:8000": b0, "h1:8000": b1},
            {"h0:8000": b0, "h1:8000": None},   # h1 down in round 2
        ])
        fetch = ReplayFetch(path, loop=False)
        assert fetch.targets == ("h0:8000", "h1:8000")
        store = SnapshotStore()
        agg = SliceAggregator(fetch.targets, store, fetch=fetch)
        try:
            key = {"slice_name": "slice-a", "accelerator": "v5p-64", "family": "tpu"}
            agg.poll_once()
            snap = store.current()
            assert snap.value("tpu_slice_hosts_reporting", key) == 2.0
            assert snap.value(
                "tpu_aggregator_target_up", {"target": "h1:8000"}
            ) == 1.0
            agg.poll_once()  # the outage round replays as an outage
            snap = store.current()
            assert snap.value("tpu_slice_hosts_reporting", key) == 1.0
            assert snap.value(
                "tpu_aggregator_target_up", {"target": "h1:8000"}
            ) == 0.0
        finally:
            agg.close()

    def test_replay_loops_by_default_and_exhausts_without(self, tmp_path):
        import pytest as _pytest

        from tpu_pod_exporter.aggregate import ReplayFetch

        path = self._roll(tmp_path, [{"h0:8000": "m 1\n"}])
        looped = ReplayFetch(path)
        for _ in range(3):  # 1-round recording served 3 times
            assert looped("h0:8000", 1.0) == "m 1\n"
        strict = ReplayFetch(path, loop=False)
        assert strict("h0:8000", 1.0) == "m 1\n"
        with _pytest.raises(ConnectionError, match="exhausted"):
            strict("h0:8000", 1.0)

    def test_corrupt_recording_names_path_and_line(self, tmp_path):
        import pytest as _pytest

        from tpu_pod_exporter.aggregate import ReplayFetch

        p = tmp_path / "bad.jsonl"
        p.write_text('{"t": 1}\n')  # no "bodies"
        with _pytest.raises(ValueError, match="bad.jsonl:1"):
            ReplayFetch(str(p))
        p.write_text("")
        with _pytest.raises(ValueError, match="no rounds"):
            ReplayFetch(str(p))

    def test_record_during_live_rounds_then_replay_matches(self, tmp_path):
        """End-to-end symmetry: rollups from a live (StaticFetch) run and
        from replaying its recording are numerically identical."""
        from tpu_pod_exporter.aggregate import (
            ReplayFetch,
            RoundRecorder,
            SliceAggregator,
        )

        pages = {"h0:8000": make_host_text(0), "h1:8000": make_host_text(1)}
        path = str(tmp_path / "cap.jsonl")
        store_live = SnapshotStore()
        agg = SliceAggregator(
            tuple(pages), store_live, fetch=StaticFetch(pages),
            recorder=RoundRecorder(path),
        )
        agg.poll_once()
        agg.close()
        store_replay = SnapshotStore()
        agg2 = SliceAggregator(
            tuple(pages), store_replay, fetch=ReplayFetch(path)
        )
        agg2.poll_once()
        agg2.close()
        key = {"slice_name": "slice-a", "accelerator": "v5p-64", "family": "tpu"}
        for name in ("tpu_slice_chip_count", "tpu_slice_hbm_used_bytes",
                     "tpu_slice_hosts_reporting"):
            assert (
                store_live.current().value(name, key)
                == store_replay.current().value(name, key)
            ), name


class TestTargetCircuitBreaker:
    """ISSUE 2: a persistently-down target is quarantined with backoff
    instead of costing a full timeout_s in the scrape pool every round, and
    its history fallback is not probed while quarantined."""

    def _agg(self, fetch, history_fetch=None, **kw):
        kw.setdefault("breaker_failures", 2)
        kw.setdefault("breaker_backoff_s", 5.0)
        kw.setdefault("breaker_backoff_max_s", 20.0)
        store = SnapshotStore()
        agg = SliceAggregator(
            ("h0:8000",), store, fetch=fetch,
            history_fetch=history_fetch or (lambda url, t: (_ for _ in ()).throw(ConnectionError("no hist"))),
            history_fallback_window_s=15.0 if history_fetch is not None else 0.0,
            **kw,
        )
        # Deterministic breaker clock, jitter factor pinned to 1.
        clock = [0.0]
        br = agg._breakers["h0:8000"]
        br._clock = lambda: clock[0]
        br._rng = type("R", (), {"random": staticmethod(lambda: 0.5)})()
        return agg, store, clock, br

    def test_quarantine_skips_scrapes_and_errors(self):
        calls = []

        def fetch(target, timeout_s):
            calls.append(target)
            raise ConnectionError("down")

        agg, store, clock, br = self._agg(fetch)
        try:
            for _ in range(2):  # threshold reached -> breaker opens
                agg.poll_once()
                clock[0] += 1.0
            assert br.state == "open"
            fetches_at_open = len(calls)
            for _ in range(3):  # quarantined rounds: no fetch at all
                agg.poll_once()
                clock[0] += 1.0
            assert len(calls) == fetches_at_open
            snap = store.current()
            # target reports down + quarantined, but the error counter only
            # counts ATTEMPTED scrapes (2), not skipped rounds.
            assert snap.value("tpu_aggregator_target_up", ("h0:8000",)) == 0.0
            assert snap.value(
                "tpu_aggregator_target_breaker_state", ("h0:8000",)
            ) == 1.0
            assert snap.value(
                "tpu_aggregator_scrape_errors_total", ("h0:8000",)
            ) == 2.0
        finally:
            agg.close()

    def test_probe_after_backoff_and_recovery_closes(self):
        down = {"v": True}
        calls = []

        def fetch(target, timeout_s):
            calls.append(target)
            if down["v"]:
                raise ConnectionError("down")
            return make_host_text(0)

        agg, store, clock, br = self._agg(fetch)
        try:
            for _ in range(2):
                agg.poll_once()
            assert br.state == "open"
            agg.poll_once()  # still inside backoff: skipped
            assert len(calls) == 2
            clock[0] += 5.0  # backoff (base 5, jitter pinned 1.0) elapsed
            down["v"] = False
            agg.poll_once()  # half-open probe succeeds
            assert len(calls) == 3
            assert br.state == "closed"
            snap = store.current()
            assert snap.value("tpu_aggregator_target_up", ("h0:8000",)) == 1.0
            assert snap.value(
                "tpu_aggregator_target_breaker_state", ("h0:8000",)
            ) == 0.0
        finally:
            agg.close()

    def test_history_fallback_not_probed_while_quarantined(self):
        hist_calls = []

        def history_fetch(url, timeout_s):
            hist_calls.append(url)
            raise ConnectionError("hist down too")

        def fetch(target, timeout_s):
            raise ConnectionError("down")

        agg, store, clock, br = self._agg(fetch, history_fetch=history_fetch)
        try:
            for _ in range(2):
                agg.poll_once()
            # Both attempted rounds probed history once (bail-fast rule).
            assert len(hist_calls) == 2
            for _ in range(4):  # quarantined rounds: history NOT probed
                agg.poll_once()
            assert len(hist_calls) == 2
        finally:
            agg.close()

    def test_breaker_disabled_scrapes_every_round(self):
        calls = []

        def fetch(target, timeout_s):
            calls.append(target)
            raise ConnectionError("down")

        store = SnapshotStore()
        agg = SliceAggregator(("h0:8000",), store, fetch=fetch,
                              breaker_failures=0)
        try:
            for _ in range(5):
                agg.poll_once()
            assert len(calls) == 5  # pre-breaker behaviour
            assert store.current().value(
                "tpu_aggregator_target_breaker_state", ("h0:8000",)
            ) is None  # no breaker, no series
        finally:
            agg.close()

    def test_recovery_logs_warning(self, caplog):
        import logging as _logging

        down = {"v": True}

        def fetch(target, timeout_s):
            if down["v"]:
                raise ConnectionError("down")
            return make_host_text(0)

        agg, store, clock, br = self._agg(fetch)
        try:
            with caplog.at_level(_logging.WARNING,
                                 logger="tpu_pod_exporter.aggregate"):
                agg.poll_once()
                down["v"] = False
                agg.poll_once()
            assert any(
                "healthy again after 1 failed scrape(s)" in r.getMessage()
                for r in caplog.records
            )
        finally:
            agg.close()
