"""Loadgen + sharding tests on the virtual CPU mesh (SURVEY.md §4; the
multi-chip path must compile and run with zero TPU hardware)."""

import numpy as np
import pytest

from tests.conftest import require_jax
from tpu_pod_exporter.loadgen.parallel import PARALLEL_PROGRAMS


@pytest.fixture(autouse=True)
def _needs_jax():
    require_jax()


@pytest.fixture(scope="module")
def cpu_devices():
    require_jax()
    import jax

    devs = jax.devices("cpu")
    if len(devs) < 8:
        pytest.skip("need 8 virtual CPU devices (conftest sets XLA_FLAGS)")
    return devs


class TestWorkload:
    # flagship compile+run is covered by selftest.CHECKS["flagship"]
    # via tests/test_parallel.py (single source with the driver gate).

    def test_forward_is_deterministic(self):
        from tpu_pod_exporter.loadgen.workload import flagship

        fn, (params, x) = flagship(width=64, depth=2, batch=8)
        a = np.asarray(fn(params, x)).astype(np.float32)
        b = np.asarray(fn(params, x)).astype(np.float32)
        np.testing.assert_array_equal(a, b)

    def test_burn_step(self):
        from tpu_pod_exporter.loadgen.workload import burn_step, init_params

        import jax.numpy as jnp

        params = init_params(width=64, depth=2)
        x = jnp.ones((8, 64), jnp.bfloat16)
        out = burn_step(params, x, iters=3)
        assert out.shape == (8, 64)

    def test_hbm_fill_allocates(self):
        from tpu_pod_exporter.loadgen.workload import hbm_fill

        arr = hbm_fill(1 << 20)
        assert arr.nbytes >= (1 << 20) // 2 * 2


class TestSharded:
    def test_mesh_factorization(self, cpu_devices):
        from tpu_pod_exporter.loadgen.sharded import make_mesh

        mesh = make_mesh(8)
        assert mesh.devices.size == 8
        assert mesh.axis_names == ("data", "model")
        # most-square: 4x2
        assert mesh.devices.shape == (4, 2)

    def test_explicit_dp_tp(self, cpu_devices):
        from tpu_pod_exporter.loadgen.sharded import make_mesh

        assert make_mesh(8, dp=8, tp=1).devices.shape == (8, 1)
        assert make_mesh(8, dp=2, tp=4).devices.shape == (2, 4)
        with pytest.raises(ValueError):
            make_mesh(8, dp=3, tp=2)

    # sharded-step descent is covered by selftest.CHECKS["sharded_descends"]
    # via tests/test_parallel.py (single source with the driver gate).

    def test_param_and_batch_shardings_applied(self, cpu_devices):
        from tpu_pod_exporter.loadgen.sharded import make_mesh, sharded_train_step

        mesh = make_mesh(8)
        step, params, (x, y) = sharded_train_step(mesh, width=64, depth=2, batch=16)
        # weights split over 'model' (2 shards), batch over 'data' (4 shards)
        assert len(params["layers"].sharding.device_set) == 8
        new_params, _ = step(params, x, y)
        assert new_params["layers"].sharding.is_equivalent_to(
            params["layers"].sharding, ndim=new_params["layers"].ndim
        )


class TestGraftEntry:
    def test_entry_compiles(self):
        import sys
        from pathlib import Path

        sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
        import __graft_entry__ as ge

        fn, args = ge.entry()
        out = fn(*args)
        assert np.asarray(out).shape == (32, 128)

    # dryrun_multichip is covered by tests/test_selftest.py — it now runs
    # in a sanitized child process (see tpu_pod_exporter.jaxenv), so the
    # in-process cpu_devices fixture is no longer the right harness.


class TestParallelProgramBuilder:
    """build_parallel_program packages each strategy for CLI looping: one
    step runs, the feedback threads outputs into the next step's inputs
    (the anti-elision data dependency), and values stay finite over a few
    iterations."""

    @pytest.mark.parametrize("name", PARALLEL_PROGRAMS)
    def test_builds_and_loops_finite(self, name):
        require_jax()
        import jax
        import jax.numpy as jnp

        from tpu_pod_exporter.loadgen.parallel import build_parallel_program

        step, inputs, feed = build_parallel_program(name, 8)
        first_inputs = inputs
        for _ in range(3):
            out = step(*inputs)
            inputs = feed(inputs, out)
        leaf = out[0] if isinstance(out, tuple) else out
        assert bool(jnp.all(jnp.isfinite(leaf))), name
        # Feedback really threads outputs into inputs (the anti-elision
        # data dependency): at least one input tensor must have changed.
        assert any(
            not jnp.array_equal(a, b)
            for a, b in zip(first_inputs, inputs)
        ), name
        jax.block_until_ready(leaf)

    def test_multislice_feedback_loop_stays_finite_long(self):
        # The looped w <- step(w) feedback is gradient descent; at lr=0.1
        # it DIVERGED to NaN around step ~94 (caught live, not by the
        # 3-iteration smoke above). 150 iterations covers that horizon.
        require_jax()
        import jax.numpy as jnp

        from tpu_pod_exporter.loadgen.parallel import build_parallel_program

        step, inputs, feed = build_parallel_program("multislice", 8)
        for i in range(150):
            out = step(*inputs)
            inputs = feed(inputs, out)
            if i % 25 == 0:
                assert bool(jnp.isfinite(out[1])), f"loss NaN at step {i}"
        assert bool(jnp.all(jnp.isfinite(out[0])))

    def test_unknown_program_rejected(self):
        require_jax()
        import pytest as _pytest

        from tpu_pod_exporter.loadgen.parallel import build_parallel_program

        with _pytest.raises(ValueError, match="unknown program"):
            build_parallel_program("nope", 8)

    def test_multislice_needs_even_devices(self):
        require_jax()
        import pytest as _pytest

        from tpu_pod_exporter.loadgen.parallel import build_parallel_program

        with _pytest.raises(ValueError, match="even"):
            build_parallel_program("multislice", 3)
