"""Attribution tests: pure conversion, real gRPC over unix sockets, checkpoint
fallback, fault paths (SURVEY.md §4.2, §4.5)."""

import json
import os
import threading
from concurrent import futures

import grpc
import pytest

from tpu_pod_exporter.attribution import (
    AttributionError,
    AttributionSnapshot,
    DeviceAllocation,
    TPU_RESOURCE_NAME,
)
from tpu_pod_exporter.attribution.checkpoint import (
    CheckpointAttribution,
    parse_checkpoint,
)
from tpu_pod_exporter.attribution.podresources import (
    LIST_METHOD,
    PodResourcesAttribution,
    snapshot_from_response,
)
from tpu_pod_exporter.attribution.proto import podresources_pb2 as pb


def make_response(pods):
    """pods: [(name, ns, [(container, resource, [ids])])]"""
    resp = pb.ListPodResourcesResponse()
    for name, ns, containers in pods:
        p = resp.pod_resources.add()
        p.name, p.namespace = name, ns
        for cname, resource, ids in containers:
            c = p.containers.add()
            c.name = cname
            if ids is not None:
                d = c.devices.add()
                d.resource_name = resource
                d.device_ids.extend(ids)
    return resp


class TestSnapshotFromResponse:
    def test_basic(self):
        resp = make_response(
            [("train-0", "ml", [("main", TPU_RESOURCE_NAME, ["0", "1"])])]
        )
        snap = snapshot_from_response(resp)
        assert snap.allocations == (
            DeviceAllocation("train-0", "ml", "main", ("0", "1"), TPU_RESOURCE_NAME),
        )
        assert snap.by_device_id() == {
            "0": snap.allocations[0],
            "1": snap.allocations[0],
        }

    def test_non_tpu_resources_pass_through_but_join_filters(self):
        resp = make_response(
            [("pod", "ns", [("c", "nvidia.com/gpu", ["GPU-abc"])])]
        )
        snap = snapshot_from_response(resp)
        assert len(snap.allocations) == 1
        assert snap.by_device_id(TPU_RESOURCE_NAME) == {}

    def test_resource_prefix_filter(self):
        resp = make_response(
            [
                ("pod", "ns", [("c", "nvidia.com/gpu", ["x"])]),
                ("pod2", "ns", [("c", TPU_RESOURCE_NAME, ["0"])]),
            ]
        )
        snap = snapshot_from_response(resp, resource_prefixes=("google.com/",))
        assert len(snap.allocations) == 1
        assert snap.allocations[0].pod == "pod2"

    def test_deviceless_containers_skipped(self):
        resp = make_response([("pod", "ns", [("c", TPU_RESOURCE_NAME, None)])])
        assert snapshot_from_response(resp).allocations == ()

    def test_duplicate_device_id_first_claim_wins(self):
        snap = AttributionSnapshot(
            (
                DeviceAllocation("a", "ns", "c", ("0",)),
                DeviceAllocation("b", "ns", "c", ("0",)),
            )
        )
        assert snap.by_device_id()["0"].pod == "a"


class _FakeLister:
    """Scripted PodResourcesLister served over a real unix socket."""

    def __init__(self, response, allocatable_ids=None, allocatable_unimplemented=False):
        self.response = response
        self.allocatable_ids = allocatable_ids
        self.allocatable_unimplemented = allocatable_unimplemented
        self.calls = 0
        self.allocatable_calls = 0

    def __call__(self, request, context):
        self.calls += 1
        return self.response

    def get_allocatable(self, request, context):
        self.allocatable_calls += 1
        if self.allocatable_unimplemented:
            context.abort(grpc.StatusCode.UNIMPLEMENTED, "old kubelet")
        resp = pb.AllocatableResourcesResponse()
        if self.allocatable_ids is not None:
            d = resp.devices.add()
            d.resource_name = TPU_RESOURCE_NAME
            d.device_ids.extend(self.allocatable_ids)
        return resp


def serve_lister(socket_path, lister):
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=2))
    handler = grpc.method_handlers_generic_handler(
        "v1.PodResourcesLister",
        {
            "List": grpc.unary_unary_rpc_method_handler(
                lister,
                request_deserializer=pb.ListPodResourcesRequest.FromString,
                response_serializer=pb.ListPodResourcesResponse.SerializeToString,
            ),
            "GetAllocatableResources": grpc.unary_unary_rpc_method_handler(
                lister.get_allocatable,
                request_deserializer=pb.AllocatableResourcesRequest.FromString,
                response_serializer=pb.AllocatableResourcesResponse.SerializeToString,
            ),
        },
    )
    server.add_generic_rpc_handlers((handler,))
    server.add_insecure_port(f"unix://{socket_path}")
    server.start()
    return server


class TestPodResourcesGrpc:
    def test_end_to_end_over_unix_socket(self, tmp_path):
        sock = str(tmp_path / "kubelet.sock")
        lister = _FakeLister(
            make_response(
                [("train-0", "ml", [("main", TPU_RESOURCE_NAME, ["0", "1", "2", "3"])])]
            )
        )
        server = serve_lister(sock, lister)
        try:
            provider = PodResourcesAttribution(socket_path=sock)
            snap = provider.snapshot()
            assert snap.allocations[0].pod == "train-0"
            assert snap.allocations[0].device_ids == ("0", "1", "2", "3")
            # channel reused across polls
            provider.snapshot()
            assert lister.calls == 2
            provider.close()
        finally:
            server.stop(0)

    def test_allocatable_inventory_reported(self, tmp_path):
        sock = str(tmp_path / "kubelet.sock")
        lister = _FakeLister(
            make_response([("p", "ns", [("c", TPU_RESOURCE_NAME, ["0"])])]),
            allocatable_ids=["0", "1", "2", "3"],
        )
        server = serve_lister(sock, lister)
        try:
            provider = PodResourcesAttribution(socket_path=sock)
            snap = provider.snapshot()
            assert snap.allocatable_device_ids == ("0", "1", "2", "3")
            provider.close()
        finally:
            server.stop(0)

    def test_allocatable_unimplemented_probed_once(self, tmp_path):
        sock = str(tmp_path / "kubelet.sock")
        lister = _FakeLister(
            make_response([("p", "ns", [("c", TPU_RESOURCE_NAME, ["0"])])]),
            allocatable_unimplemented=True,
        )
        server = serve_lister(sock, lister)
        try:
            provider = PodResourcesAttribution(socket_path=sock)
            assert provider.snapshot().allocatable_device_ids is None
            assert provider.snapshot().allocatable_device_ids is None
            assert lister.allocatable_calls == 1  # not re-probed
            provider.close()
        finally:
            server.stop(0)

    def test_missing_socket_raises_attribution_error(self, tmp_path):
        provider = PodResourcesAttribution(
            socket_path=str(tmp_path / "nope.sock"), timeout_s=0.2
        )
        with pytest.raises(AttributionError):
            provider.snapshot()
        provider.close()

    def test_kubelet_restart_reconnects(self, tmp_path):
        sock = str(tmp_path / "kubelet.sock")
        lister = _FakeLister(make_response([("p", "ns", [("c", TPU_RESOURCE_NAME, ["0"])])]))
        server = serve_lister(sock, lister)
        provider = PodResourcesAttribution(socket_path=sock, timeout_s=0.5)
        assert provider.snapshot().allocations[0].pod == "p"
        server.stop(0)
        if os.path.exists(sock):  # grpc may remove the socket file on stop
            os.unlink(sock)
        with pytest.raises(AttributionError):
            provider.snapshot()
        # kubelet comes back on the same path
        lister2 = _FakeLister(make_response([("q", "ns", [("c", TPU_RESOURCE_NAME, ["0"])])]))
        server2 = serve_lister(sock, lister2)
        try:
            assert provider.snapshot().allocations[0].pod == "q"
        finally:
            provider.close()
            server2.stop(0)


CHECKPOINT_V2 = {
    "Data": {
        "PodDeviceEntries": [
            {
                "PodUID": "uid-123",
                "ContainerName": "main",
                "ResourceName": TPU_RESOURCE_NAME,
                "DeviceIDs": {"-1": ["0", "1"]},
            }
        ],
        "RegisteredDevices": {TPU_RESOURCE_NAME: ["0", "1", "2", "3"]},
    },
    "Checksum": 12345,
}


class TestCheckpoint:
    def test_parse_v2_shape(self):
        snap = parse_checkpoint(json.dumps(CHECKPOINT_V2))
        assert snap.allocations == (
            DeviceAllocation("uid:uid-123", "", "main", ("0", "1"), TPU_RESOURCE_NAME),
        )

    def test_parse_legacy_flat_shape(self):
        doc = {
            "Data": {
                "PodDeviceEntries": [
                    {
                        "PodUID": "u",
                        "ContainerName": "c",
                        "ResourceName": TPU_RESOURCE_NAME,
                        "DeviceIDs": ["3"],
                    }
                ]
            }
        }
        snap = parse_checkpoint(json.dumps(doc))
        assert snap.allocations[0].device_ids == ("3",)

    def test_uid_hint_map(self):
        snap = parse_checkpoint(
            json.dumps(CHECKPOINT_V2), uid_to_pod={"uid-123": ("train-0", "ml")}
        )
        assert snap.allocations[0].pod == "train-0"
        assert snap.allocations[0].namespace == "ml"

    def test_bad_json_raises(self):
        with pytest.raises(AttributionError):
            parse_checkpoint("{not json")

    def test_empty_and_malformed_entries_skipped(self):
        doc = {"Data": {"PodDeviceEntries": [None, {}, {"PodUID": "u", "DeviceIDs": {}}]}}
        assert parse_checkpoint(json.dumps(doc)).allocations == ()

    def test_provider_reads_file(self, tmp_path):
        path = tmp_path / "kubelet_internal_checkpoint"
        path.write_text(json.dumps(CHECKPOINT_V2))
        provider = CheckpointAttribution(path=str(path))
        assert provider.snapshot().allocations[0].device_ids == ("0", "1")

    def test_provider_missing_file_raises(self, tmp_path):
        provider = CheckpointAttribution(path=str(tmp_path / "missing"))
        with pytest.raises(AttributionError):
            provider.snapshot()


class TestUidMap:
    """UID→(name, namespace) resolution for the checkpoint fallback
    (VERDICT r1 missing #3: no more pod="uid:…" when a source is wired)."""

    def test_static_file_shapes(self, tmp_path):
        from tpu_pod_exporter.attribution.uidmap import StaticUidMap

        p = tmp_path / "uids.json"
        p.write_text(json.dumps({
            "uid-123": {"name": "train-0", "namespace": "ml"},
            "uid-456": ["eval-1", "research"],
        }))
        m = StaticUidMap(str(p)).mapping()
        assert m["uid-123"] == ("train-0", "ml")
        assert m["uid-456"] == ("eval-1", "research")

    def test_static_file_reloads_on_mtime_change(self, tmp_path):
        import os

        from tpu_pod_exporter.attribution.uidmap import StaticUidMap

        p = tmp_path / "uids.json"
        p.write_text(json.dumps({"u": ["a", "ns"]}))
        src = StaticUidMap(str(p))
        assert src.mapping()["u"] == ("a", "ns")
        p.write_text(json.dumps({"u": ["b", "ns"]}))
        os.utime(p, (1, 2))  # force a distinct mtime
        assert src.mapping()["u"] == ("b", "ns")

    def test_static_file_bad_shape_raises(self, tmp_path):
        from tpu_pod_exporter.attribution.uidmap import StaticUidMap, UidMapError

        p = tmp_path / "uids.json"
        p.write_text(json.dumps({"u": "just-a-string"}))
        with pytest.raises(UidMapError):
            StaticUidMap(str(p)).mapping()

    def test_kubelet_pods_parse_and_ttl(self):
        from tpu_pod_exporter.attribution.uidmap import KubeletPodsUidMap

        pods = {"items": [
            {"metadata": {"uid": "u1", "name": "p1", "namespace": "ns1"}},
            {"metadata": {"name": "no-uid-skipped"}},
        ]}
        calls = []
        clock = [0.0]

        def fetch(url, headers, timeout_s):
            calls.append(url)
            return json.dumps(pods).encode()

        src = KubeletPodsUidMap(
            "http://127.0.0.1:10255/pods", refresh_s=30,
            _fetch=fetch, _clock=lambda: clock[0],
        )
        assert src.mapping()["u1"] == ("p1", "ns1")
        assert len(src.mapping()) == 1
        assert len(calls) == 1  # TTL: second mapping() served from cache
        clock[0] = 31.0
        src.mapping()
        assert len(calls) == 2  # refreshed after TTL

    def test_kubelet_fetch_error_serves_last_good(self):
        from tpu_pod_exporter.attribution.uidmap import KubeletPodsUidMap

        good = json.dumps(
            {"items": [{"metadata": {"uid": "u", "name": "p", "namespace": "n"}}]}
        ).encode()
        state = {"fail": False}
        clock = [0.0]

        def fetch(url, headers, timeout_s):
            if state["fail"]:
                raise ConnectionError("kubelet down")
            return good

        src = KubeletPodsUidMap("http://k:10255/pods", refresh_s=10,
                                _fetch=fetch, _clock=lambda: clock[0])
        assert src.mapping()["u"] == ("p", "n")
        state["fail"] = True
        clock[0] = 11.0
        assert src.mapping()["u"] == ("p", "n")  # last-good served
        assert src.fetch_errors == 1

    def test_bearer_token_over_unverified_https_refused(self, tmp_path):
        """ADVICE r2 #2: an explicit token + https + no CA must refuse at
        startup, not quietly ship the credential over unverified TLS."""
        from tpu_pod_exporter.attribution.uidmap import (
            KubeletPodsUidMap,
            UidMapError,
        )

        token = tmp_path / "token"
        token.write_text("secret")
        with pytest.raises(UidMapError, match="unverified TLS"):
            KubeletPodsUidMap(
                "https://127.0.0.1:10250/pods", token_file=str(token)
            )

    def test_bearer_token_unverified_https_explicit_opt_in(self, tmp_path):
        from tpu_pod_exporter.attribution.uidmap import KubeletPodsUidMap

        token = tmp_path / "token"
        token.write_text("secret")
        src = KubeletPodsUidMap(
            "https://127.0.0.1:10250/pods",
            token_file=str(token),
            insecure_tls=True,
            _fetch=lambda url, headers, t: b'{"items": []}',
        )
        assert src.mapping() == {}

    def test_token_with_ca_or_plain_http_is_fine(self, tmp_path):
        from tpu_pod_exporter.attribution.uidmap import KubeletPodsUidMap

        token = tmp_path / "token"
        token.write_text("secret")
        ca = tmp_path / "ca.crt"
        ca.write_text("---")
        KubeletPodsUidMap(
            "https://127.0.0.1:10250/pods",
            token_file=str(token), ca_file=str(ca),
        )
        KubeletPodsUidMap("http://127.0.0.1:10255/pods", token_file=str(token))

    def test_app_does_not_auto_default_token_without_ca(self, tmp_path, monkeypatch):
        """The auto path drops the token (with a warning) rather than
        leaking it, when the SA CA bundle is absent."""
        import tpu_pod_exporter.app as app_mod
        from tpu_pod_exporter.app import _build_uid_source
        from tpu_pod_exporter.config import ExporterConfig

        token = tmp_path / "token"
        token.write_text("secret")
        monkeypatch.setattr(
            "tpu_pod_exporter.attribution.uidmap.DEFAULT_TOKEN_FILE",
            str(token), raising=False,
        )
        monkeypatch.setattr(
            "tpu_pod_exporter.attribution.uidmap.DEFAULT_CA_FILE",
            str(tmp_path / "absent-ca.crt"), raising=False,
        )
        cfg = ExporterConfig(kubelet_pods_url="https://127.0.0.1:10250/pods")
        src = _build_uid_source(cfg)
        assert src is not None
        assert src._token_file is None  # token NOT auto-sent unverified

    def test_checkpoint_provider_uses_live_source(self, tmp_path):
        from tpu_pod_exporter.attribution.uidmap import StaticUidMap

        ckpt = tmp_path / "kubelet_internal_checkpoint"
        ckpt.write_text(json.dumps(CHECKPOINT_V2))
        uids = tmp_path / "uids.json"
        uids.write_text(json.dumps({"uid-123": ["train-0", "ml"]}))
        provider = CheckpointAttribution(
            path=str(ckpt), uid_source=StaticUidMap(str(uids))
        )
        alloc = provider.snapshot().allocations[0]
        assert (alloc.pod, alloc.namespace) == ("train-0", "ml")

    def test_checkpoint_provider_degrades_when_source_fails(self, tmp_path):
        from tpu_pod_exporter.attribution.uidmap import StaticUidMap

        ckpt = tmp_path / "kubelet_internal_checkpoint"
        ckpt.write_text(json.dumps(CHECKPOINT_V2))
        provider = CheckpointAttribution(
            path=str(ckpt), uid_source=StaticUidMap(str(tmp_path / "missing"))
        )
        # Allocations survive; pods fall back to uid-keyed names.
        assert provider.snapshot().allocations[0].pod == "uid:uid-123"

    def test_uid_map_errors_reach_exporter_metrics(self, tmp_path):
        """Source failures must surface as
        tpu_exporter_poll_errors_total{source="attribution.uid_map"}, not just a log."""
        from tpu_pod_exporter.attribution.uidmap import StaticUidMap
        from tpu_pod_exporter.backend.fake import FakeBackend
        from tpu_pod_exporter.collector import Collector
        from tpu_pod_exporter.metrics import SnapshotStore

        ckpt = tmp_path / "kubelet_internal_checkpoint"
        ckpt.write_text(json.dumps(CHECKPOINT_V2))
        provider = CheckpointAttribution(
            path=str(ckpt), uid_source=StaticUidMap(str(tmp_path / "missing"))
        )
        store = SnapshotStore()
        c = Collector(FakeBackend(chips=1), provider, store)
        c.poll_once()
        c.poll_once()
        assert store.current().value(
            "tpu_exporter_poll_errors_total", {"source": "attribution.uid_map"}
        ) == 2.0
