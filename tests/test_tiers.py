"""Multi-resolution history tiers (ISSUE 6).

Property-style tests (seeded random walks, deterministic — no hypothesis
dependency) asserting the downsample contract: tier answers must AGREE
with recomputation from the raw samples the test itself retains — gauge
min/max/mean/first/last exactly, and counter rates with the same
reset-tolerant monotonic-fold semantics. Plus tier selection at every step
boundary, coverage escalation past raw retention, and the ≥30× retention
acceptance criterion.
"""

import random

import pytest

from tpu_pod_exporter.history import (
    DEFAULT_TIER_SPEC,
    HistoryStore,
    parse_tier_spec,
)


class FakeClock:
    def __init__(self, t0: float = 0.0) -> None:
        self.t = t0

    def __call__(self) -> float:
        return self.t


BASE_WALL = 1_700_000_000.0  # aligned to 10 and 60 (multiple of 600)


def make_store(capacity=8, tiers=((10.0, 6), (60.0, 8)), **kw):
    clock = FakeClock()
    store = HistoryStore(
        capacity=capacity, max_series=64, retention_s=0.0,
        clock=clock, wallclock=lambda: BASE_WALL + clock.t,
        tiers=tiers, **kw,
    )
    return store, clock


def feed(store, clock, metric, values, labels=None, dt=1.0):
    """Append one value per dt tick; returns [(mono, wall, v), ...]."""
    out = []
    for i, v in enumerate(values):
        clock.t = i * dt
        store.append(metric, labels or {}, v)
        out.append((clock.t, BASE_WALL + clock.t, v))
    return out


class TestTierSpec:
    def test_parse_defaults(self):
        assert parse_tier_spec(DEFAULT_TIER_SPEC) == ((10.0, 60), (60.0, 240))

    def test_off_disables(self):
        for spec in ("", "off", "none", "0"):
            assert parse_tier_spec(spec) == ()

    @pytest.mark.parametrize("bad", ["10", "x:5", "10:x", "0:5", "10:1",
                                     "10:60,10:90"])
    def test_bad_specs_raise(self, bad):
        with pytest.raises(ValueError):
            parse_tier_spec(bad)

    def test_sorted_finest_first(self):
        assert parse_tier_spec("60:4,10:4") == ((10.0, 4), (60.0, 4))


class TestGaugeAgreement:
    """Tier bucket stats must recompute exactly from raw samples."""

    def test_bucket_stats_match_recomputation(self):
        rng = random.Random(42)
        h, clock = make_store(capacity=512, tiers=((10.0, 64),))
        samples = feed(h, clock, "tpu_hbm_used_bytes",
                       [rng.uniform(0, 100) for _ in range(120)])
        # Per grid point at step=10 with agg=X, the answer must equal X
        # over the raw samples of that point's bucket.
        for agg, fold in (("min", min), ("max", max), ("last", lambda v: v[-1]),
                          ("mean", lambda v: sum(v) / len(v))):
            [row] = h.query_range(
                "tpu_hbm_used_bytes",
                start=BASE_WALL, end=BASE_WALL + 119, step=10.0, agg=agg,
            )
            assert row["tier"] == 10.0
            for t, v in row["values"]:
                # The grid point carries the most recent BUCKET point at or
                # before t (a bucket's point sits at its last sample's wall
                # time); the value must equal agg over that whole bucket's
                # raw samples.
                buckets: dict[float, list[float]] = {}
                for (_m, w, sv) in samples:
                    buckets.setdefault((w // 10.0) * 10.0, []).append(sv)
                eligible = [lo for lo, _vs in buckets.items()
                            if max(w for (_m, w, _v) in samples
                                   if (w // 10.0) * 10.0 == lo) <= t]
                if not eligible:
                    continue
                raw = buckets[max(eligible)]
                assert v == pytest.approx(fold(raw)), (agg, t)

    def test_window_stats_fold_matches_raw(self):
        # Raw ring too small to cover the window; the tier fold must
        # reproduce the stats over ALL samples in the window.
        rng = random.Random(7)
        h, clock = make_store(capacity=8, tiers=((10.0, 64),))
        samples = feed(h, clock, "tpu_hbm_used_bytes",
                       [rng.uniform(0, 100) for _ in range(200)])
        [row] = h.window_stats("tpu_hbm_used_bytes", window_s=200.0)
        assert row["tier"] == 10.0  # escalated: raw holds 8 of 200 samples
        s = row["stats"]
        vals = [v for (_m, _w, v) in samples]
        assert s["samples"] == len(vals)
        assert s["min"] == pytest.approx(min(vals))
        assert s["max"] == pytest.approx(max(vals))
        assert s["mean"] == pytest.approx(sum(vals) / len(vals))
        assert s["first"] == pytest.approx(vals[0])
        assert s["last"] == pytest.approx(vals[-1])

    def test_last_sample_wall_ts_on_rows(self):
        h, clock = make_store()
        feed(h, clock, "tpu_hbm_used_bytes", [1.0, 2.0, 3.0])
        for rows in (
            h.query_range("tpu_hbm_used_bytes", start=BASE_WALL,
                          end=BASE_WALL + 10),
            h.window_stats("tpu_hbm_used_bytes", window_s=60.0),
        ):
            assert rows[0]["last_sample_wall_ts"] == BASE_WALL + 2.0


class TestCounterAgreement:
    def _raw_rate(self, vals, dt_total):
        gained = sum(d for d in (b - a for a, b in zip(vals, vals[1:]))
                     if d > 0)
        return gained / dt_total

    def test_reset_tolerant_rate_matches_raw(self):
        # Counter with resets at random positions: tier-folded rate over a
        # bucket-aligned window must equal raw recomputation exactly.
        rng = random.Random(1234)
        h, clock = make_store(capacity=8, tiers=((10.0, 64),))
        vals, v = [], 0.0
        for i in range(200):
            if rng.random() < 0.05:
                v = 0.0  # device reset
            else:
                v += rng.uniform(0, 1000)
            vals.append(v)
        feed(h, clock, "tpu_ici_transferred_bytes_total", vals,
             labels={"link": "0"})
        [row] = h.window_stats("tpu_ici_transferred_bytes_total",
                               window_s=200.0)
        assert row["tier"] == 10.0
        assert row["stats"]["rate"] == pytest.approx(
            self._raw_rate(vals, 199.0))

    def test_rate_agrees_at_many_seeds(self):
        # Property sweep: 20 seeds, resets and plateaus included; always
        # exact on full-history windows.
        for seed in range(20):
            rng = random.Random(seed)
            h, clock = make_store(capacity=4, tiers=((10.0, 64),))
            vals, v = [], 0.0
            for _ in range(100):
                r = rng.random()
                if r < 0.08:
                    v = rng.uniform(0, 10)  # reset to non-zero floor
                elif r < 0.3:
                    pass  # plateau
                else:
                    v += rng.uniform(0, 50)
                vals.append(v)
            feed(h, clock, "tpu_dcn_transferred_bytes_total", vals,
                 labels={"link": "1"})
            [row] = h.window_stats("tpu_dcn_transferred_bytes_total",
                                   window_s=100.0)
            assert row["stats"]["rate"] == pytest.approx(
                self._raw_rate(vals, 99.0)), f"seed {seed}"


class TestTierSelection:
    @pytest.mark.parametrize("step,expected", [
        (0.0, 0.0),     # raw samples
        (1.0, 0.0),     # finer than every tier → raw
        (9.9, 0.0),
        (10.0, 10.0),   # boundary: 10 s tier satisfies step 10
        (30.0, 10.0),   # coarsest tier ≤ 30 is 10
        (59.9, 10.0),
        (60.0, 60.0),   # boundary: 60 s tier
        (600.0, 60.0),  # coarsest available
    ])
    def test_step_boundaries(self, step, expected):
        h, clock = make_store(capacity=512, tiers=((10.0, 64), (60.0, 64)))
        feed(h, clock, "tpu_hbm_used_bytes", [float(i) for i in range(130)])
        # end past the data so even a 600 s grid has a point with data
        # at-or-before it (within the bucket-width-aware lookback).
        [row] = h.query_range("tpu_hbm_used_bytes", start=BASE_WALL,
                              end=BASE_WALL + 720, step=step)
        assert row["tier"] == expected, f"step {step}"

    def test_escalation_past_raw_retention(self):
        # Raw holds the last 8 s; a gridded query starting 100 s ago must
        # escalate to the 10 s tier even though step=1 prefers raw.
        h, clock = make_store(capacity=8, tiers=((10.0, 64),))
        feed(h, clock, "tpu_hbm_used_bytes", [float(i) for i in range(120)])
        [row] = h.query_range("tpu_hbm_used_bytes", start=BASE_WALL,
                              end=BASE_WALL + 119, step=1.0)
        assert row["tier"] == 10.0
        # ... but a query the raw ring CAN cover stays raw.
        [row] = h.query_range("tpu_hbm_used_bytes", start=BASE_WALL + 113,
                              end=BASE_WALL + 119, step=1.0)
        assert row["tier"] == 0.0

    def test_step_zero_never_escalates(self):
        # Raw-sample queries mean "the raw ring, whatever it holds" — the
        # pre-tier contract, bit for bit.
        h, clock = make_store(capacity=4, tiers=((10.0, 64),))
        feed(h, clock, "tpu_hbm_used_bytes", [float(i) for i in range(50)])
        [row] = h.query_range("tpu_hbm_used_bytes", start=BASE_WALL,
                              end=BASE_WALL + 50)
        assert row["tier"] == 0.0
        assert len(row["values"]) == 4  # raw ring capacity

    def test_tiers_off_is_raw_only(self):
        h, clock = make_store(capacity=8, tiers=())
        feed(h, clock, "tpu_hbm_used_bytes", [float(i) for i in range(50)])
        [row] = h.query_range("tpu_hbm_used_bytes", start=BASE_WALL,
                              end=BASE_WALL + 50, step=10.0)
        assert row["tier"] == 0.0
        assert h.stats()["tiers"] == []


class TestRetentionAcceptance:
    def test_retention_extends_30x_at_same_series_bound(self):
        # The ISSUE 6 criterion: answerable query_range retention grows
        # ≥30× at an unchanged --history-max-series bound. Shape mirrors
        # production: raw 301×1 s, default tiers, long-running series.
        h, clock = make_store(capacity=301,
                              tiers=parse_tier_spec(DEFAULT_TIER_SPEC))
        n = 16000  # ~4.4 h at 1 Hz
        for i in range(n):
            clock.t = float(i)
            h.append("tpu_tensorcore_duty_cycle_percent", {"chip_id": "0"},
                     float(i % 100))
        raw_span = 301.0
        [row] = h.query_range(
            "tpu_tensorcore_duty_cycle_percent",
            start=BASE_WALL, end=BASE_WALL + n, step=60.0,
        )
        answered_span = row["values"][-1][0] - row["values"][0][0]
        assert answered_span >= 30.0 * raw_span
        # max_series untouched; memory stays hard-bounded and accounted.
        st = h.stats()
        assert st["max_series"] == 64
        per_series = st["memory_bytes"] / st["series"]
        assert per_series == 301 * 24 + (60 + 240) * 88

    def test_tier_stats_and_eviction(self):
        h, clock = make_store(capacity=8, tiers=((10.0, 4),))
        feed(h, clock, "tpu_hbm_used_bytes", [float(i) for i in range(35)])
        st = h.stats()
        [tier] = st["tiers"]
        assert tier["step_s"] == 10.0
        # 35 samples → buckets 0..3 flushed or open; ring cap 4 (+1 open)
        assert 1 <= tier["buckets"] <= 5
        assert tier["span_s"] > 0
        # Eviction drops the series' tiers with it.
        for i in range(200):
            h.append("tpu_hbm_used_bytes", {"chip_id": str(i)}, 1.0)
        assert h.stats()["series"] <= 64


class TestCollectorIntegration:
    def test_tier_metrics_reach_exposition(self):
        from tpu_pod_exporter.attribution.fake import FakeAttribution
        from tpu_pod_exporter.backend.fake import FakeBackend
        from tpu_pod_exporter.collector import Collector
        from tpu_pod_exporter.metrics import SnapshotStore

        store = SnapshotStore()
        history = HistoryStore(capacity=16, tiers=((10.0, 4),))
        c = Collector(FakeBackend(chips=2), FakeAttribution(), store,
                      history=history)
        c.poll_once()
        c.poll_once()
        text = store.current().encode().decode()
        assert 'tpu_exporter_history_tier_buckets{tier="10"}' in text
        assert 'tpu_exporter_history_tier_span_seconds{tier="10"}' in text
        c.close()
