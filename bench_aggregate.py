"""Aggregator round-duration benchmark at v5p-128-scale inputs.

64 targets × 256-chip exposition bodies (~16k chip series + per-pod/link
series) folded by ``SliceAggregator.poll_once`` with an injected fetch, so
the number is pure parse+fold cost — no network. Prints one JSON line;
the result is recorded in BASELINE.md (VERDICT r1 #8).

Run: ``python bench_aggregate.py [--hosts 64] [--chips 256] [--rounds 5]``
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))


def _rss_bytes() -> int:
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except OSError:  # no procfs (non-Linux): report 0, keep the timings
        pass
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--hosts", type=int, default=64)
    p.add_argument("--chips", type=int, default=256)
    p.add_argument("--rounds", type=int, default=5)
    args = p.parse_args(argv)

    from tests.test_aggregate import StaticFetch, make_host_text

    from tpu_pod_exporter.aggregate import SliceAggregator
    from tpu_pod_exporter.metrics import SnapshotStore

    body = make_host_text(0, chips=args.chips)
    pages = {
        f"h{w}:8000": body.replace('host="host-0"', f'host="host-{w}"')
        for w in range(args.hosts)
    }
    total_series = sum(page.count("\n") for page in pages.values())

    store = SnapshotStore()
    agg = SliceAggregator(tuple(pages), store, fetch=StaticFetch(pages))
    agg.poll_once()  # warm (allocators, interned labels)
    times = []
    for _ in range(args.rounds):
        t0 = time.perf_counter()
        agg.poll_once()
        times.append(time.perf_counter() - t0)

    snap = store.current()
    key = {"slice_name": "slice-a", "accelerator": "v5p-64"}
    assert snap.value("tpu_slice_chip_count", key) == float(args.hosts * args.chips)
    med = statistics.median(times)
    print(json.dumps({
        "metric": f"aggregator_round_ms_{args.hosts}x{args.chips}",
        "value": round(med * 1000, 1),
        "unit": "ms",
        "hosts": args.hosts,
        "chips_per_host": args.chips,
        "approx_input_lines": total_series,
        "rounds": args.rounds,
        "min_ms": round(min(times) * 1000, 1),
        "max_ms": round(max(times) * 1000, 1),
        # Steady-state footprint incl. the per-target layout caches
        # (≈ one parsed body's strings per target — the cost of the
        # value-only re-parse path; BASELINE.md documents the trade).
        "rss_mb": round(_rss_bytes() / 1e6, 1),
        # Machine context for cross-round comparisons (see bench.py).
        "cpu_cores": os.cpu_count(),
    }))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
